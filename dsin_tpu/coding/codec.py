"""Real bitstream codec for the quantized bottleneck.

Turns the causal context model (models/probclass.py) into an actual
compressor: per-position PMFs over the L quantizer centers are quantized to
integer frequency tables and fed to the rANS coder. This completes what the
reference only stubbed (reference probclass_imgcomp.py:361-482:
``PredictionNetwork`` builds integer frequency tables for an arithmetic
coder whose driver files are missing; everything the reference reports is
the cross-entropy *estimate*, reference bits_imgcomp.py:4-21).

Design:

* **Encode** knows every symbol up front, but the PMF for each position must
  be byte-identical to what the decoder will compute from its own partially
  decoded buffer. Both sides therefore run the SAME jitted single-context
  network on the SAME buffer state (values written back sequentially in
  (depth=channel, h, w) raster order), so the floats — and hence the
  quantized frequency tables — match exactly. XLA executables are
  deterministic for fixed shapes/backend, which is what makes this sound.
* The per-position network input is the (context_D, context, context) causal
  receptive field (reference probclass_imgcomp.py:18-24: (5, 9, 9) for K=3)
  sliced from the padded volume; the masked convs guarantee the non-causal
  entries of the block cannot influence the output (verified by the
  causality tests).
* Symbol resolution inside decode uses the cumulative-frequency peek/advance
  split of `rans.Decoder`, so a fresh adaptive PMF per position costs one
  tiny jit call + O(L) host work.

Four scan engines share the same stream format (header mode byte — the
engine defines both the symbol order and the exact PMF floats, so it is a
property of the stream):

* **wavefront_np** (default) — the pure-numpy incremental engine
  (coding/incremental.py): cached per-layer activations, each computed
  exactly once at its availability front; one fully-conv forward of work
  total and no jax in the loop (~50x the jit wavefront on a 1-core host).
* **wavefront_pl** — the fused Pallas front kernel
  (coding/probclass_pallas.py): the whole 4-layer context stack per front
  in ONE device launch instead of the four-conv XLA dispatch — the
  device-speed engine for TPU-resident coding (interpret mode off-TPU).

The two jit engines remain as independently-derived cross-checks:

* **sequential** — one position per jit call in raster order; the obviously-
  correct baseline (~1k-10k symbols/s host-loop).
* **wavefront** — positions are grouped into fronts
  t = a*d + b*h + w with b = pad+1, a = pad*(b+1)+1 (for K=3: t = 25d+5h+w).
  Every causal dependency of a position provably lies in a strictly earlier
  front (see `_wavefronts`), so all PMFs of one front are computed in a
  single padded batched jit call; only the O(L) rANS symbol step stays
  sequential. Mean front parallelism at the reference bottleneck shape
  (32, 40, 120) is ~100x. Encode and decode run the identical batched
  executable over identically-padded fronts, preserving the byte-exact
  PMF agreement the stream depends on. The schedule is part of the stream
  format (header mode byte): fronts reorder symbols relative to raster.
"""

from __future__ import annotations

import functools
import struct
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dsin_tpu.coding import rans
from dsin_tpu.models import probclass as pc_lib
from dsin_tpu.utils import locks as locks_lib

MAGIC = b"DTPC"
VERSION = 2
MODE_SEQUENTIAL = 0
MODE_WAVEFRONT = 1
MODE_WAVEFRONT_NP = 2
MODE_WAVEFRONT_PL = 3
_MODES = {"sequential": MODE_SEQUENTIAL, "wavefront": MODE_WAVEFRONT,
          "wavefront_np": MODE_WAVEFRONT_NP,
          "wavefront_pl": MODE_WAVEFRONT_PL}


class BottleneckCodec:
    """Encode/decode one bottleneck symbol volume with the context model.

    Parameters
    ----------
    probclass_model : pc_lib.ResShallow
        The (flax) context-model module.
    pc_params : pytree
        Its trained parameters.
    centers : np.ndarray (L,)
        Quantizer centers; decoded symbols are mapped through these to
        rebuild the q volume the context model conditions on.
    pc_config : config
        For kernel_size / use_centers_for_padding.
    """

    @classmethod
    def for_model(cls, model, params,
                  scale_bits: int = rans.DEFAULT_SCALE_BITS):
        """Build from a DSIN model bundle + its params tree — the one
        construction every call site (CLI, test-time real_bpp) shares, so
        the probclass/centers partition wiring cannot drift."""
        return cls(model.probclass, params["probclass"], params["centers"],
                   model.pc_config, scale_bits=scale_bits)

    def __init__(self, probclass_model, pc_params, centers, pc_config,
                 scale_bits: int = rans.DEFAULT_SCALE_BITS,
                 pad_value: Optional[float] = None,
                 pallas_interpret: Optional[bool] = None):
        self.model = probclass_model
        self.pc_params = pc_params
        self.centers = np.asarray(centers, dtype=np.float32)
        self.num_centers = len(self.centers)
        self.pc_config = pc_config
        self.scale_bits = scale_bits
        self.kernel_size = int(pc_config.kernel_size)
        self.pad = pc_lib.context_size(self.kernel_size) // 2
        self.ctx_shape = pc_lib.context_shape(self.kernel_size)  # (cd, cs, cs)
        if pad_value is None:
            # an explicit pad_value (loader.codec_from_spec) skips this
            # jnp evaluation so a worker-resident rebuild in a fresh
            # process stays off the device path entirely
            pad_value = float(np.asarray(pc_lib.auto_pad_value(
                pc_config, jnp.asarray(self.centers))))
        self.pad_value = float(pad_value)

        # params enter as a traced pytree ARGUMENT, not a closure capture:
        # a captured dict would rebind per BottleneckCodec instance and
        # re-trace per identity (jaxlint: nonstatic-jit-capture)
        def _block_logits(variables, block):  # (cd, cs, cs) -> (L,)
            out = self.model.apply(variables, block[None, ..., None])
            return out[0, 0, 0, 0, :]

        variables = {"params": pc_params}
        self._block_logits = functools.partial(
            jax.jit(_block_logits), variables)
        # batched twin for wavefront fronts: (B, cd, cs, cs) -> (B, L).
        # vmap of the same per-block computation; all fronts are padded to
        # one bucket size so encode and decode hit the same executable.
        self._block_logits_batch = functools.partial(
            jax.jit(jax.vmap(_block_logits, in_axes=(None, 0))), variables)
        # lazy numpy engine (wavefront_np mode)
        self._incremental = None  # guarded-by: self._incremental_lock
        # lazy fused Pallas front kernel (wavefront_pl mode); None for
        # `pallas_interpret` resolves to interpret mode off-TPU at first
        # use — a per-process property, like the engines' same-machine
        # determinism contract
        self._pallas = None  # guarded-by: self._incremental_lock
        self._pallas_interpret = pallas_interpret
        self._incremental_lock = locks_lib.RankedLock("codec.engine")

    def _incremental_engine(self):
        with self._incremental_lock:
            if self._incremental is None:
                from dsin_tpu.coding.incremental import IncrementalResShallow
                # one-shot device->host param pull held under the lock
                # on purpose: every caller needs the engine before
                # proceeding, so the convoy IS the point (N racing
                # builders would each pay the transfer only to discard
                # N-1 engines). The blocking-call-under-lock rule does
                # not see np.asarray as tree_map's callable — this is
                # intent prose, not a policed suppression.
                params_np = jax.tree_util.tree_map(np.asarray,
                                                   self.pc_params)
                self._incremental = IncrementalResShallow(
                    params_np, self.centers, self.pc_config,
                    self.pad_value)
            return self._incremental

    def _pallas_engine(self):
        """Lazy fused-front kernel wrapper (coding/probclass_pallas.py).
        Same convoy-on-purpose locking rationale as the incremental
        engine above; read-only once built, so clones share it."""
        with self._incremental_lock:
            if self._pallas is None:
                from dsin_tpu.coding.probclass_pallas import \
                    ProbclassFrontKernel
                interpret = self._pallas_interpret
                if interpret is None:
                    interpret = jax.default_backend() != "tpu"
                params_np = jax.tree_util.tree_map(np.asarray,
                                                   self.pc_params)
                self._pallas = ProbclassFrontKernel(
                    params_np, self.pc_config, interpret=interpret)
            return self._pallas

    def thread_clone(self) -> "BottleneckCodec":
        """A per-thread twin for entropy pools (dsin_tpu/serve): shares
        this codec's read-only weights AND its incremental engine — whose
        schedule cache is lock-guarded (coding/incremental.py), so clones
        reuse schedules the parent's warmup already built — while every
        encode/decode call keeps its per-pass buffers private. Giving
        each pool thread its own instance also fences off any codec-level
        mutable state a future change might add."""
        clone = BottleneckCodec(self.model, self.pc_params, self.centers,
                                self.pc_config, scale_bits=self.scale_bits,
                                pad_value=self.pad_value,
                                pallas_interpret=self._pallas_interpret)
        clone._incremental = self._incremental_engine()
        with self._incremental_lock:
            # read-only once built; may still be None (lazy)
            clone._pallas = self._pallas
        return clone

    # -- internals ----------------------------------------------------------

    def _make_buffer(self, d: int, h: int, w: int) -> np.ndarray:
        """Padded q buffer, all pad_value: depth-front + H/W-both padding
        (matches pc_lib.pad_volume; reference probclass_imgcomp.py:285-292)."""
        p = self.pad
        return np.full((d + p, h + 2 * p, w + 2 * p), self.pad_value,
                       dtype=np.float32)

    def _freqs_at(self, buf: np.ndarray, d: int, h: int, w: int) -> np.ndarray:
        cd, cs, _ = self.ctx_shape
        block = jnp.asarray(buf[d:d + cd, h:h + cs, w:w + cs])
        logits = np.asarray(self._block_logits(block), dtype=np.float64)
        # softmax in float64 on host: cheap at L=6 and deterministic
        z = logits - logits.max()
        pmf = np.exp(z)
        pmf /= pmf.sum()
        return rans.quantize_pmf(pmf, self.scale_bits)

    def _tables_from_logits(self, logits_batch: np.ndarray):
        """(n, L) float64 logits -> (freqs (n, L) u32, cum (n, L+1) u32).
        The ONE softmax+quantize path both wavefront engines share — the
        stream format depends on encode and decode (and ideal_bits) hitting
        bit-identical tables, so there must be exactly one copy of this."""
        z = logits_batch - logits_batch.max(axis=1, keepdims=True)
        pmf = np.exp(z)
        pmf /= pmf.sum(axis=1, keepdims=True)
        freqs_b = rans.quantize_pmf_batch(pmf, self.scale_bits)
        return freqs_b, rans.cum_from_freqs_batch(freqs_b)

    def _positions(self, d: int, h: int, w: int):
        for dd in range(d):
            for hh in range(h):
                for ww in range(w):
                    yield dd, hh, ww

    def _wavefronts(self, d: int, h: int, w: int):
        """Group positions into dependency-safe fronts.

        t(d, h, w) = a*d + b*h + w with b = pad+1 and a = pad*(b+1)+1.
        Any causal dependency (d', h', w') of (d, h, w) satisfies one of
          d'=d, h'=h, w'<w          -> t-t' = w-w'          >= 1
          d'=d, h'<h, w'<=w+pad     -> t-t' >= b - pad       = 1
          d'<d, h'<=h+pad, w'<=w+pad-> t-t' >= a - b*pad-pad = 1
        so equal-t positions are mutually independent. Returns a list of
        (n_i, 3) int arrays, t ascending, raster order within a front."""
        # shared with the numpy engine's schedule builder — the two engines'
        # fronts must coincide (same symbol order in the stream format)
        from dsin_tpu.coding.incremental import wavefront_coeffs
        a_coef, b_coef = wavefront_coeffs(self.pad)
        dd, hh, ww = np.meshgrid(np.arange(d), np.arange(h), np.arange(w),
                                 indexing="ij")
        pos = np.stack([dd, hh, ww], axis=-1).reshape(-1, 3)
        t = a_coef * pos[:, 0] + b_coef * pos[:, 1] + pos[:, 2]
        # stable sort keeps raster order inside equal-t groups
        order = np.argsort(t, kind="stable")
        pos, t = pos[order], t[order]
        bounds = np.flatnonzero(np.diff(t)) + 1
        return np.split(pos, bounds)

    def _wavefront_pass(self, shape: Tuple[int, int, int], front_symbols,
                        logits_fn=None):
        """Vectorized wavefront driver: for each front (t ascending) compute
        every PMF in one padded batched jit call, obtain the front's symbols
        VECTORIZED via `front_symbols(front, cum_b, freqs_b) -> (n,) ints`
        (encode: a gather from the known volume; decode: one native rANS
        call per front), write all centers back at once, and yield
        (front (n,3), symbols (n,), cum_b (n,L+1), freqs_b (n,L)).

        No per-symbol Python work remains — the hot loop is numpy fancy
        indexing over a sliding-window VIEW of the buffer (the view sees
        each front's write-back automatically) plus one jit and one coder
        call per front. Produces byte-identical streams to the previous
        per-position implementation (same fronts, same bucket padding, same
        batched executable, same write-back order).

        `logits_fn` swaps the per-front logits launch — the default is
        the XLA batched jit; the Pallas engine (`_wavefront_pass_pl`)
        passes the fused front kernel. Everything else (fronts, bucket
        rule, write-back) is shared, so the engines cannot drift in
        schedule — only in last-ulp PMF floats, which the header mode
        byte already accounts for."""
        fn = logits_fn if logits_fn is not None else self._block_logits_batch
        d, h, w = shape
        buf = self._make_buffer(d, h, w)
        p = self.pad
        cd, cs, _ = self.ctx_shape
        win = np.lib.stride_tricks.sliding_window_view(buf, (cd, cs, cs))
        fronts = self._wavefronts(d, h, w)
        max_bucket = max(len(f) for f in fronts)
        blocks = np.zeros((max_bucket, cd, cs, cs), dtype=np.float32)
        for front in fronts:
            n = len(front)
            # pad to the next power of two, not max front: front sizes vary
            # a lot and padded rows are pure wasted compute. The bucket is a
            # deterministic function of n, so encode and decode still run
            # identical executables per front.
            bucket = min(1 << (n - 1).bit_length(), max_bucket)
            blocks[:n] = win[front[:, 0], front[:, 1], front[:, 2]]
            blocks[n:bucket] = 0.0  # deterministic padding
            logits = np.asarray(fn(
                jnp.asarray(blocks[:bucket])), dtype=np.float64)[:n]
            freqs_b, cum_b = self._tables_from_logits(logits)
            s = np.asarray(front_symbols(front, cum_b, freqs_b),
                           dtype=np.int64)
            buf[front[:, 0] + p, front[:, 1] + p, front[:, 2] + p] = \
                self.centers[s]
            yield front, s, cum_b, freqs_b

    def _wavefront_pass_np(self, shape: Tuple[int, int, int], front_symbols):
        """Same contract as `_wavefront_pass` (identical fronts, identical
        yield tuples) but PMFs come from the pure-numpy incremental engine
        (coding/incremental.py): cached per-layer activations updated
        voxel-once in wavefront order — one fully-conv forward total instead
        of a context cone per symbol, and no jax in the loop. Encode and
        decode run this same code, so the quantized tables agree exactly;
        streams are NOT interchangeable with the jit engine's (mode byte
        keeps them apart)."""
        vp = self._incremental_engine().begin(shape)
        for i, (_, front) in enumerate(vp.sch.fronts):
            logits = vp.logits_for(i).astype(np.float64)
            freqs_b, cum_b = self._tables_from_logits(logits)
            s = np.asarray(front_symbols(front, cum_b, freqs_b),
                           dtype=np.int64)
            vp.write(i, s)
            yield front, s, cum_b, freqs_b

    def _wavefront_pass_pl(self, shape: Tuple[int, int, int], front_symbols):
        """`_wavefront_pass` with PMFs from the fused Pallas front kernel
        (coding/probclass_pallas.py): one device launch per front instead
        of the four-conv XLA dispatch. Same fronts, same bucket padding,
        same write-back; encode and decode both run THIS kernel, so the
        quantized tables agree exactly. Its floats differ from the jit
        engine's in the last ulp — mode 3 streams are not interchangeable
        with mode 1 (the header mode byte keeps them apart)."""
        return self._wavefront_pass(
            shape, front_symbols,
            logits_fn=self._pallas_engine().front_logits)

    def _passes_for(self, mode_id: int):
        """Front-pass driver for a wavefront-family stream mode — the ONE
        mode->engine map `_encode_lane`, `decode`, and `ideal_bits` share
        (three private copies is how an engine goes missing from one
        site and desyncs a stream)."""
        return {MODE_WAVEFRONT: self._wavefront_pass,
                MODE_WAVEFRONT_NP: self._wavefront_pass_np,
                MODE_WAVEFRONT_PL: self._wavefront_pass_pl}[mode_id]

    def _scan(self, shape: Tuple[int, int, int], symbol_at):
        """The one sequential driver every public method builds on: walk the
        volume in causal raster order maintaining the padded buffer; at each
        position compute the frequency table, ask `symbol_at(position, cum,
        freqs)` for the symbol, write its center back, and yield
        (position, symbol, cum, freqs). Encode, decode, and ideal_bits only
        differ in where the symbol comes from — keeping them on one driver
        means the scan order and buffer write-back cannot desynchronize."""
        d, h, w = shape
        buf = self._make_buffer(d, h, w)
        p = self.pad
        for pos in self._positions(d, h, w):
            dd, hh, ww = pos
            freqs = self._freqs_at(buf, dd, hh, ww)
            cum = rans.cum_from_freqs(freqs)
            s = symbol_at(pos, cum, freqs)
            buf[dd + p, hh + p, ww + p] = self.centers[s]
            yield pos, s, cum, freqs

    # -- public API ---------------------------------------------------------

    def _encode_lane(self, symbols: np.ndarray, mode_id: int):
        """Run the scan for one volume and return its (starts, freqs)
        rANS lane — the per-image half of encode, shared by the single
        and batch entry points so the two cannot drift."""
        starts = np.empty(symbols.size, dtype=np.uint32)
        freqs_out = np.empty(symbols.size, dtype=np.uint32)
        if mode_id != MODE_SEQUENTIAL:
            passes = self._passes_for(mode_id)
            idx = 0
            known = lambda front, cum_b, freqs_b: \
                symbols[front[:, 0], front[:, 1], front[:, 2]]
            for front, s, cum_b, freqs_b in passes(
                    symbols.shape, known):
                n = len(front)
                ar = np.arange(n)
                starts[idx:idx + n] = cum_b[ar, s]
                freqs_out[idx:idx + n] = freqs_b[ar, s]
                idx += n
        else:
            take = lambda pos, cum, freqs: int(symbols[pos])
            for i, (pos, s, cum, freqs) in enumerate(
                    self._scan(symbols.shape, take)):
                starts[i] = cum[s]
                freqs_out[i] = freqs[s]
        return starts, freqs_out

    def _check_symbols(self, symbols_dhw) -> np.ndarray:
        symbols = np.asarray(symbols_dhw)
        if symbols.ndim != 3:
            raise ValueError(f"expected (D, H, W) symbols, got "
                             f"{symbols.shape}")
        if symbols.size == 0:
            # _parse_header rejects d*h*w == 0 streams, so encoding one
            # would emit bytes our own decode refuses
            raise ValueError(f"empty symbol volume {symbols.shape}")
        if symbols.min() < 0 or symbols.max() >= self.num_centers:
            raise ValueError("symbol out of range")
        return symbols

    def _header(self, mode_id: int, shape) -> bytes:
        return MAGIC + struct.pack("<BBBHHH", VERSION, mode_id,
                                   self.scale_bits, *shape)

    def encode(self, symbols_dhw: np.ndarray,
               mode: str = "wavefront_np") -> bytes:
        """symbols (D=C, H, W) int -> framed bitstream.

        Default mode is the numpy incremental engine (~50x the jit
        wavefront on a 1-core host: 0.96s vs 45s for a (32, 40, 120)
        volume); 'wavefront' (jit) and 'sequential' remain as
        cross-checking baselines. The mode is recorded in the stream
        header — decode always uses the stream's own engine."""
        symbols = self._check_symbols(symbols_dhw)
        mode_id = _MODES[mode]
        starts, freqs_out = self._encode_lane(symbols, mode_id)
        payload = rans.encode(starts, freqs_out, self.scale_bits)
        return self._header(mode_id, symbols.shape) + payload

    def encode_batch(self, volumes, mode: str = "wavefront_np") -> list:
        """N independent (D, H, W) symbol volumes -> N framed bitstreams
        with ONE native rANS call for the whole batch (rans.encode_batch
        packs the per-volume lanes; ragged shapes are fine — lanes are
        independent). Streams are bit-identical to N `encode` calls: the
        scan half is the same `_encode_lane` per volume, and a batched
        lane encodes to the same bytes as a solo one. This is the serve
        entropy stage's encode path: one GIL-dropping ctypes call per
        micro-batch instead of one per image."""
        vols = [self._check_symbols(v) for v in volumes]
        mode_id = _MODES[mode]
        lanes = [self._encode_lane(v, mode_id) for v in vols]
        payloads = rans.encode_batch([ln[0] for ln in lanes],
                                     [ln[1] for ln in lanes],
                                     self.scale_bits)
        return [self._header(mode_id, v.shape) + p
                for v, p in zip(vols, payloads)]

    def _parse_header(self, bitstream: bytes):
        """Validate a DTPC frame; -> (mode_id, (d, h, w)). Every
        corruption mode raises a typed ValueError (ISSUE 3 fuzz gate)."""
        if len(bitstream) < 13:
            # struct.error here would be a raw traceback on any truncated
            # blob — corrupted streams must fail typed (ISSUE 3 fuzz gate)
            raise ValueError(f"truncated DTPC stream: {len(bitstream)} "
                             f"bytes < 13-byte header")
        if bitstream[:4] != MAGIC:
            raise ValueError("bad magic")
        version, mode_id, scale_bits, d, h, w = struct.unpack(
            "<BBBHHH", bitstream[4:13])
        if version != VERSION:
            raise ValueError(f"unsupported bitstream version {version}")
        if mode_id not in (MODE_SEQUENTIAL, MODE_WAVEFRONT,
                           MODE_WAVEFRONT_NP, MODE_WAVEFRONT_PL):
            raise ValueError(f"unknown scan mode {mode_id}")
        if scale_bits != self.scale_bits:
            raise ValueError(f"stream scale_bits {scale_bits} != codec "
                             f"{self.scale_bits}")
        if d * h * w == 0 or d * h * w > (1 << 28):
            # a corrupt header's dims would otherwise drive a giant
            # allocation + hours of decode before anything notices
            raise ValueError(f"implausible symbol volume ({d}, {h}, {w}) "
                             f"in stream header")
        return mode_id, (d, h, w)

    def decode(self, bitstream: bytes) -> np.ndarray:
        """Framed bitstream -> symbols (D, H, W) int32. The scan engine
        (sequential/wavefront/wavefront_np) is read from the stream header —
        it defines the symbol order and the exact PMF floats, so it is a
        property of the stream, not a knob."""
        mode_id, (d, h, w) = self._parse_header(bitstream)
        symbols = np.empty((d, h, w), dtype=np.int32)
        with rans.Decoder(bitstream[13:], self.scale_bits) as dec:
            if mode_id != MODE_SEQUENTIAL:
                passes = self._passes_for(mode_id)
                take = lambda front, cum_b, freqs_b: dec.decode_front(cum_b)
                for front, s, _, _ in passes((d, h, w), take):
                    symbols[front[:, 0], front[:, 1], front[:, 2]] = s
            else:
                for pos, s, _, _ in self._scan(
                        (d, h, w),
                        lambda pos, cum, freqs: dec.decode_symbol(cum)):
                    symbols[pos] = s
        return symbols

    def decode_batch(self, streams) -> list:
        """N framed bitstreams -> N (D, H, W) int32 volumes.

        When every stream is wavefront_np with the same shape (the serve
        micro-batch case: one bucket = one bottleneck geometry), the N
        decoders advance in LOCKSTEP through the shared front schedule —
        each front costs N numpy PMF updates plus ONE native rANS call
        (`rans.decode_front_batch`) instead of N, so the ctypes round
        trips per micro-batch collapse by the batch size. Results are
        bit-identical to N `decode` calls: each lane's PMF path and
        coder state are untouched by its neighbors. Mixed shapes/modes
        fall back to the per-stream loop."""
        metas = [self._parse_header(b) for b in streams]
        if not streams:
            return []
        mode_id, shape = metas[0]
        if (mode_id != MODE_WAVEFRONT_NP or len(streams) == 1
                or any(m != (mode_id, shape) for m in metas)):
            return [self.decode(b) for b in streams]
        eng = self._incremental_engine()
        vps = [eng.begin(shape) for _ in streams]
        outs = [np.empty(shape, dtype=np.int32) for _ in streams]
        decs = [rans.Decoder(b[13:], self.scale_bits) for b in streams]
        try:
            for i, (_, front) in enumerate(vps[0].sch.fronts):
                cums = []
                for vp in vps:
                    logits = vp.logits_for(i).astype(np.float64)
                    _, cum_b = self._tables_from_logits(logits)
                    cums.append(cum_b)
                syms = rans.decode_front_batch(decs, cums)
                for vp, s, out in zip(vps, syms, outs):
                    s = np.asarray(s, dtype=np.int64)
                    vp.write(i, s)
                    out[front[:, 0], front[:, 1], front[:, 2]] = s
        finally:
            for dec in decs:
                dec.close()
        return outs

    def coding_gap(self, symbols_dhw: np.ndarray, stream: bytes) -> dict:
        """Realized stream size vs this codec's own cross-entropy bound —
        the serving coding-gap signal (ISSUE 13, serve/quality.py).

        `stream` must be a DTPC frame THIS codec produced for
        `symbols_dhw`; the scan mode is read from its header so the
        `ideal_bits` pass runs the SAME engine (engines differ in
        last-ulp PMF floats, so the bound must come from the coder that
        emitted the bytes). Returns payload bits (header excluded — the
        13 framing bytes are transport, not model redundancy), the
        bound, and the gap both absolute and relative. The gap is the
        rANS coding redundancy over the QUANTIZED tables: always >= 0
        up to the coder's final-state flush, and stable for a healthy
        model — a RISING gap under live traffic means probclass no
        longer matches the data distribution. This is the ONE gap
        definition; the serve telemetry and its tests both call it."""
        mode_id, shape = self._parse_header(stream)
        symbols = np.asarray(symbols_dhw)
        if tuple(symbols.shape) != shape:
            raise ValueError(f"symbols {tuple(symbols.shape)} are not the "
                             f"volume this stream frames {shape}")
        mode = next(name for name, mid in _MODES.items() if mid == mode_id)
        ideal = self.ideal_bits(symbols, mode=mode)
        payload_bits = (len(stream) - 13) * 8
        gap_bits = payload_bits - ideal
        return {
            "payload_bits": payload_bits,
            "ideal_bits": round(ideal, 3),
            "gap_bits": round(gap_bits, 3),
            "gap_pct": round(100.0 * gap_bits / ideal, 4) if ideal > 0
            else 0.0,
        }

    def ideal_bits(self, symbols_dhw: np.ndarray,
                   mode: str = "wavefront_np") -> float:
        """Information content under the *quantized* tables — the tight lower
        bound for the actual stream (the cross-entropy estimate differs by
        the PMF-quantization loss). `mode` picks whose tables: it must match
        the stream being bounded (engines differ in last-ulp PMF floats)."""
        if mode not in _MODES:
            raise ValueError(f"unknown mode {mode!r}; expected one of "
                             f"{sorted(_MODES)}")
        symbols = np.asarray(symbols_dhw)
        total = 0.0
        scale = float(1 << self.scale_bits)
        if mode != "sequential":
            passes = self._passes_for(_MODES[mode])
            known = lambda front, cum_b, freqs_b: \
                symbols[front[:, 0], front[:, 1], front[:, 2]]
            for front, s, _, freqs_b in passes(symbols.shape, known):
                total += float(np.sum(np.log2(
                    scale / freqs_b[np.arange(len(s)), s].astype(np.float64))))
            return total
        take = lambda pos, cum, freqs: int(symbols[pos])
        for _, s, _, freqs in self._scan(symbols.shape, take):
            total += float(np.log2(scale / float(freqs[s])))
        return total


def encode_batch(codec: BottleneckCodec, symbols_nhwc: np.ndarray) -> list:
    """(N, H, W, C) NHWC symbols -> list of per-item bitstreams (one
    native rANS call for the whole batch). The volume depth axis is the
    bottleneck channel (models/probclass.py layout note)."""
    symbols = np.asarray(symbols_nhwc)
    return codec.encode_batch([np.transpose(s, (2, 0, 1))
                               for s in symbols])


def decode_batch(codec: BottleneckCodec, streams: list) -> np.ndarray:
    """Inverse of encode_batch: list of bitstreams -> (N, H, W, C) int32
    (lockstep batch decode when the streams share one geometry)."""
    vols = [np.transpose(v, (1, 2, 0))
            for v in codec.decode_batch(list(streams))]
    return np.stack(vols, axis=0)
