"""Pure-numpy incremental wavefront engine for the bottleneck codec.

The jit engine in `codec.py` recomputes the full (context_D, cs, cs) cone of
the `res_shallow` network for every symbol: ~2.1 MFLOPs/symbol, ~322 GFLOPs
for a 320x960 image's (32, 40, 120) bottleneck — ~45 s on a 1-core host even
with wavefront batching, because neighboring cones recompute the same
intermediate activations over and over.

This engine instead keeps *cached activation buffers* for every layer of the
network (reference probclass_imgcomp.py:199-221 architecture:
conv0(first_mask) -> relu -> [conv(other)->relu->conv(other) + cropped skip]
-> conv(other) -> relu) and updates each activation voxel exactly ONCE, the
moment its causal inputs are complete. Total work collapses to one
fully-convolutional forward (~21 GFLOPs at the same shape) executed in
wavefront order as small gather+matmul batches — a pure-numpy host codec
with no jax in the loop.

Scheduling: with the wavefront time t(d, h, w) = a*d + b*h + w (same
coefficients as codec._wavefronts — any causal dependency is strictly
earlier), each layer voxel p gets an *availability time*
tau(p) = max over its unmasked filter taps of the input's availability
(tau of the padded q buffer = t of the position, -1 for padding). A voxel is
computed in the front loop right after front tau(p) is written; the output
logits for front T provably need only voxels with tau < T — the schedule
builder asserts this, which re-verifies the causal-mask structure end to end
for every shape it compiles.

Determinism: encode and decode run this identical numpy code over identical
buffer states, so the PMFs — and the quantized frequency tables — agree
bit-for-bit on a given machine/BLAS. Like the jit engine's
same-executable guarantee, streams are not portable across machines with
different float behavior; cross-machine portability would need an
integer/fixed-point context model (out of scope, as in the reference).

Thread safety: one `IncrementalResShallow` may be shared across threads
(the serve entropy pool runs per-image encodes/decodes concurrently,
dsin_tpu/serve/service.py). The weights/masks/centers are read-only
after __init__, every `begin()` returns a `_VolumePass` owning all of
its mutable buffers, and the only shared mutable state — the per-shape
schedule cache — is guarded by a lock in `schedule()`.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from dsin_tpu.models import probclass as pc_lib
from dsin_tpu.utils import locks as locks_lib


def wavefront_coeffs(pad: int) -> Tuple[int, int]:
    """(a, b) of t = a*d + b*h + w; see codec._wavefronts for the proof."""
    b = pad + 1
    return pad * (b + 1) + 1, b


def _masked_window_max(t: np.ndarray, mask: np.ndarray) -> np.ndarray:
    """VALID sliding max of `t` over `mask`'s nonzero taps (floor -1)."""
    win = np.lib.stride_tricks.sliding_window_view(t, mask.shape)
    sel = np.where(mask > 0, win, np.int64(-1))
    return sel.max(axis=(3, 4, 5))


def _flat(pos: np.ndarray, dims: Tuple[int, int, int]) -> np.ndarray:
    """(n, 3) int positions -> flat row indices for a (dims + (C,)) buffer."""
    return (pos[:, 0] * dims[1] + pos[:, 1]) * dims[2] + pos[:, 2]


def _tap_offsets(in_dims: Tuple[int, int, int],
                 fshape: Tuple[int, int, int]) -> np.ndarray:
    """Flat offsets of the filter taps inside the input buffer."""
    td, th, tw = np.meshgrid(np.arange(fshape[0]), np.arange(fshape[1]),
                             np.arange(fshape[2]), indexing="ij")
    return ((td * in_dims[1] + th) * in_dims[2] + tw).reshape(-1)


def _group_by_tau(tau: np.ndarray, self_dims, in_dims) -> Dict[int, tuple]:
    """tau volume -> {tau: (self_flat_rows, input_window_base_rows)}."""
    pos = np.argwhere(tau >= -1)          # all positions, (n, 3)
    taus = tau.reshape(-1)
    order = np.argsort(taus, kind="stable")
    pos, taus = pos[order], taus[order]
    self_flat = _flat(pos, self_dims)
    in_base = _flat(pos, in_dims)         # window starts at the same coords
    groups: Dict[int, tuple] = {}
    bounds = np.flatnonzero(np.diff(taus)) + 1
    for sf, ib, tv in zip(np.split(self_flat, bounds),
                          np.split(in_base, bounds),
                          taus[np.r_[0, bounds]]):
        groups[int(tv)] = (sf, ib)
    return groups


class _Schedule:
    """Everything shape-dependent, precomputed once per volume shape."""

    def __init__(self, shape: Tuple[int, int, int], kernel_size: int,
                 masks: List[np.ndarray]):
        d, h, w = shape
        k = kernel_size
        fd = k // 2 + 1
        pad = pc_lib.context_size(k) // 2
        a, b = wavefront_coeffs(pad)
        self.pad = pad

        def shrink(dims):
            return (dims[0] - (fd - 1), dims[1] - (k - 1), dims[2] - (k - 1))

        self.a0_dims = (d + pad, h + 2 * pad, w + 2 * pad)
        self.act1_dims = shrink(self.a0_dims)
        self.r1_dims = shrink(self.act1_dims)
        self.act3_dims = shrink(self.r1_dims)
        out_dims = shrink(self.act3_dims)
        assert out_dims == shape, (out_dims, shape)
        self.skip_off = (2 * (k // 2), k - 1, k - 1)

        # availability times
        t_q = np.full(self.a0_dims, -1, dtype=np.int64)
        dd, hh, ww = np.meshgrid(np.arange(d), np.arange(h), np.arange(w),
                                 indexing="ij")
        t_q[pad:, pad:pad + h, pad:pad + w] = a * dd + b * hh + ww
        tau1 = _masked_window_max(t_q, masks[0])
        tau_r1 = _masked_window_max(tau1, masks[1])
        so = self.skip_off
        tau3 = np.maximum(
            _masked_window_max(tau_r1, masks[2]),
            tau1[so[0]:, so[1]:-so[1] or None, so[2]:-so[2] or None])
        tau_log = _masked_window_max(tau3, masks[3])
        t_out = a * dd + b * hh + ww
        # the causal guarantee the whole stream rests on: every input any
        # front's logits touch is strictly earlier than the front itself
        assert (tau_log < t_out).all(), "causality violated in schedule"

        self.groups1 = _group_by_tau(tau1, self.act1_dims, self.a0_dims)
        self.groups_r1 = _group_by_tau(tau_r1, self.r1_dims, self.act1_dims)
        self.groups3 = _group_by_tau(tau3, self.act3_dims, self.r1_dims)

        # q fronts (identical grouping to codec._wavefronts: stable sort of
        # t keeps raster order within a front)
        posq = np.stack([dd, hh, ww], axis=-1).reshape(-1, 3)
        tq = t_out.reshape(-1)
        order = np.argsort(tq, kind="stable")
        posq, tq = posq[order], tq[order]
        bnds = np.flatnonzero(np.diff(tq)) + 1
        self.fronts = list(zip(
            [int(v) for v in tq[np.r_[0, bnds]]],
            np.split(posq, bnds)))
        self.front_a0_rows = [
            _flat(f + pad, self.a0_dims) for _, f in self.fronts]
        self.front_act3_base = [_flat(f, self.act3_dims)
                                for _, f in self.fronts]
        # skip-gather rows in act1 for act3 updates
        self.skip_rows = {}
        for tv, (sf, _) in self.groups3.items():
            p3 = np.stack(np.unravel_index(sf, self.act3_dims), axis=-1)
            self.skip_rows[tv] = _flat(p3 + np.asarray(so), self.act1_dims)

        self.offs0 = _tap_offsets(self.a0_dims, masks[0].shape)
        self.offs1 = _tap_offsets(self.act1_dims, masks[1].shape)
        self.offs2 = _tap_offsets(self.r1_dims, masks[2].shape)
        self.offs3 = _tap_offsets(self.act3_dims, masks[3].shape)


class IncrementalResShallow:
    """Numpy twin of models/probclass.ResShallow for sequential coding.

    Weights are masked once at construction; the four layers run as
    gather+matmul over flat (rows, channels) buffers.
    """

    def __init__(self, pc_params, centers: np.ndarray, pc_config, pad_value):
        self.k = int(pc_config.kernel_size)
        self.masks = [pc_lib.make_mask(self.k, include_center=bool(i))
                      for i in (0, 1, 1, 1)]
        names = sorted(pc_params.keys())  # _MaskedConv3D_0 .. _3
        assert len(names) == 4, names
        self.W, self.b = [], []
        for name, mask in zip(names, self.masks):
            kern = np.asarray(pc_params[name]["kernel"], dtype=np.float32)
            kern = kern * mask[..., None, None]
            taps = mask.size
            self.W.append(kern.reshape(taps * kern.shape[3], kern.shape[4]))
            self.b.append(np.asarray(pc_params[name]["bias"],
                                     dtype=np.float32))
        self.centers = np.asarray(centers, dtype=np.float32)
        self.pad_value = np.float32(pad_value)
        # guarded-by: self._sched_lock
        self._schedules: Dict[Tuple[int, int, int], _Schedule] = {}
        self._sched_lock = locks_lib.RankedLock("codec.schedules")

    def schedule(self, shape: Tuple[int, int, int]) -> _Schedule:
        shape = tuple(int(s) for s in shape)
        with self._sched_lock:
            sch = self._schedules.get(shape)
        if sch is None:
            # build OUTSIDE the lock: a first-seen large shape must not
            # stall pool threads coding other (cached) shapes; racing
            # builders converge via setdefault (schedules are pure
            # functions of (shape, kernel, masks), so either copy wins)
            sch = _Schedule(shape, self.k, self.masks)
            with self._sched_lock:
                sch = self._schedules.setdefault(shape, sch)
        return sch

    def cached_shapes(self) -> List[Tuple[int, int, int]]:
        """Shapes whose schedules are already built (warmup evidence —
        the serve process-backend worker-residence probe reads this)."""
        with self._sched_lock:
            return sorted(self._schedules)

    def begin(self, shape) -> "_VolumePass":
        return _VolumePass(self, self.schedule(shape))


def _gather_matmul(buf2d: np.ndarray, bases: np.ndarray, offs: np.ndarray,
                   W: np.ndarray, b: np.ndarray) -> np.ndarray:
    """rows = relu-less conv at `bases`: (n, taps*C_in) @ W + b."""
    x = buf2d[bases[:, None] + offs[None, :]]        # (n, taps, C_in)
    return x.reshape(len(bases), -1) @ W + b


class _VolumePass:
    """One encode/decode traversal: buffers + per-front update machinery."""

    def __init__(self, eng: IncrementalResShallow, sch: _Schedule):
        self.eng, self.sch = eng, sch
        self.a0 = np.full((np.prod(sch.a0_dims), 1), eng.pad_value,
                          dtype=np.float32)
        self.act1 = np.zeros((np.prod(sch.act1_dims), eng.W[0].shape[1]),
                             np.float32)
        self.r1 = np.zeros((np.prod(sch.r1_dims), eng.W[1].shape[1]),
                           np.float32)
        self.act3 = np.zeros((np.prod(sch.act3_dims), eng.W[2].shape[1]),
                             np.float32)
        self._update(-1)  # pure-padding voxels are available up front

    def _update(self, tv: int) -> None:
        """Compute every layer voxel that became available at front `tv`."""
        eng, sch = self.eng, self.sch
        g = sch.groups1.get(tv)
        if g is not None:
            sf, ib = g
            self.act1[sf] = np.maximum(_gather_matmul(
                self.a0, ib, sch.offs0, eng.W[0], eng.b[0]), 0.0)
        g = sch.groups_r1.get(tv)
        if g is not None:
            sf, ib = g
            self.r1[sf] = np.maximum(_gather_matmul(
                self.act1, ib, sch.offs1, eng.W[1], eng.b[1]), 0.0)
        g = sch.groups3.get(tv)
        if g is not None:
            sf, ib = g
            self.act3[sf] = (_gather_matmul(self.r1, ib, sch.offs2,
                                            eng.W[2], eng.b[2])
                             + self.act1[sch.skip_rows[tv]])

    def logits_for(self, front_idx: int) -> np.ndarray:
        """(n, L) float32 logits for front `front_idx` (final relu incl.)."""
        sch, eng = self.sch, self.eng
        return np.maximum(_gather_matmul(
            self.act3, sch.front_act3_base[front_idx], sch.offs3,
            eng.W[3], eng.b[3]), 0.0)

    def write(self, front_idx: int, symbols: np.ndarray) -> None:
        """Write front symbols' centers into the q buffer, then run the
        layer updates unlocked by this front."""
        tv = self.sch.fronts[front_idx][0]
        rows = self.sch.front_a0_rows[front_idx]
        self.a0[rows, 0] = self.eng.centers[symbols]
        self._update(tv)
