"""Image compress / decompress CLI — real files in, real files out.

The reference never produces a bitstream (its "test" mode dumps
reconstructions + estimated bpp; reference main.py:101-126, SURVEY §3.4);
this tool completes the pipeline: PNG -> encoder -> quantized symbols ->
context-model rANS stream on disk, and back. Decompression optionally takes
the decoder-side information image to run the full DSIN path (patch search +
siNet fusion) — the asymmetry that defines the method: the ENCODER never
sees y, so the bitstream is identical with or without it.

File format (little-endian, v3):
    b"DSIM" | u8 version | u16 img_h | u16 img_w | u32 init_seed
            | u32 crc32 | u32 payload_len | payload
where payload is a BottleneckCodec stream (its own header carries the
symbol-volume dims). `crc32` covers every header field after the magic
(except itself) plus the payload: a single flipped bit anywhere in the
frame raises a typed IntegrityError instead of decoding to a plausible
garbage image — the context-model coupling makes payload corruption
otherwise silent. v2 streams (no CRC) remain readable. `init_seed` is
the parameter-init PRNG seed the encoder ran with: when no --ckpt
restores real weights, the decoder MUST rebuild the identical random
model or the rANS probabilities diverge and the decode silently produces
garbage — so decompress defaults to the header's seed and only an
explicit --seed overrides it.

Usage:
    python -m dsin_tpu.coding.cli compress  x.png out.dsin --ckpt weights/m
    python -m dsin_tpu.coding.cli decompress out.dsin rec.png \
        --ckpt weights/m [--side y.png]
"""

from __future__ import annotations

import argparse
import os
import struct
import sys
from typing import Optional

import jax.numpy as jnp
import numpy as np

from dsin_tpu.coding.loader import load_model_state, make_codec
from dsin_tpu.utils import faults
from dsin_tpu.utils.integrity import IntegrityError, frame_crc, verify_crc

MAGIC = b"DSIM"
VERSION = 3            # v3: + CRC32 over header fields + payload
_HEADER_LEN = 21       # magic(4) + BHH(5) + seed(4) + crc(4) + len(4)
_HEADER_LEN_V2 = 17    # v2: no CRC field

# construction lives in coding/loader.py now (shared with dsin_tpu/serve);
# the old private names stay importable for existing call sites
_load_model_state = load_model_state
_make_codec = make_codec


def frame_dsim(payload: bytes, h: int, w: int, seed: int) -> bytes:
    """Frame a BottleneckCodec payload as a v3 DSIM stream."""
    head = struct.pack("<BHHI", VERSION, h, w, seed)
    tail = struct.pack("<I", len(payload))
    crc = frame_crc(head, tail, payload)
    return MAGIC + head + struct.pack("<I", crc) + tail + payload


def parse_dsim(blob: bytes):
    """-> (version, h, w, seed, payload); every corruption mode is a
    typed error. v3 verifies the frame CRC (IntegrityError on mismatch);
    v2 streams predate the CRC and parse without one. Pure bytes-in
    validation — callable without a model, which is what lets the fuzz
    tests sweep every header field cheaply."""
    if len(blob) < _HEADER_LEN_V2 or blob[:4] != MAGIC:
        raise ValueError("not a DSIM stream")
    version = blob[4]
    if version == 2:
        version, h, w, seed, n = struct.unpack("<BHHII",
                                               blob[4:_HEADER_LEN_V2])
        payload = blob[_HEADER_LEN_V2:_HEADER_LEN_V2 + n]
    elif version == VERSION:
        if len(blob) < _HEADER_LEN:
            raise ValueError(f"truncated DSIM v3 header: {len(blob)} of "
                             f"{_HEADER_LEN} bytes")
        version, h, w, seed, crc, n = struct.unpack("<BHHIII",
                                                    blob[4:_HEADER_LEN])
        payload = blob[_HEADER_LEN:_HEADER_LEN + n]
    else:
        raise ValueError(f"unsupported version {version}")
    if len(payload) != n:
        # the rANS decoder cannot detect truncation itself — it would
        # silently produce garbage symbols
        raise ValueError(f"truncated stream: payload {len(payload)} of "
                         f"{n} bytes")
    if version == VERSION:
        verify_crc(crc, "DSIM stream", struct.pack("<BHHI", version, h, w,
                                                   seed),
                   struct.pack("<I", n), payload)
    return version, h, w, seed, payload


def compress(x_path: str, out_path: str, ae_config: str, pc_config: str,
             ckpt: Optional[str] = None, seed: int = 0) -> dict:
    from dsin_tpu.coding.codec import encode_batch
    from dsin_tpu.data.loader import decode_image

    x = decode_image(x_path).astype(np.float32)
    h, w, _ = x.shape
    if h % 8 or w % 8:
        raise ValueError(
            f"image {h}x{w} must be divisible by the subsampling factor 8")
    if not 0 <= seed < 2 ** 32:
        # the header stores u32; a masked seed would init DIFFERENT weights
        # on the decode side and silently corrupt the reconstruction
        raise ValueError(f"seed must fit u32 (0 <= seed < 2**32), got {seed}")
    model, state = _load_model_state(ae_config, pc_config, ckpt, (h, w),
                                     need_sinet=False, seed=seed)
    enc_out, _ = model.encode(state.params, state.batch_stats,
                              jnp.asarray(x[None]), train=False)
    symbols = np.asarray(enc_out.symbols[0])          # (h/8, w/8, C)
    payload = encode_batch(_make_codec(model, state), symbols[None])[0]

    with open(out_path, "wb") as f:
        f.write(frame_dsim(payload, h, w, seed))
    bpp = len(payload) * 8.0 / (h * w)
    return {"bytes": len(payload), "bpp": bpp, "shape": (h, w)}


def decompress(in_path: str, out_path: str, ae_config: str, pc_config: str,
               ckpt: Optional[str] = None,
               side: Optional[str] = None,
               seed: Optional[int] = None) -> dict:
    """`seed=None` (default) re-inits with the seed recorded in the
    stream header — the only value that can reproduce the encoder's
    weights when no checkpoint restores them. An explicit seed that
    DISAGREES with the header is a hard error: the mismatched init would
    silently decode garbage (the rANS probabilities diverge from the
    encoder's), so there is no legitimate override to offer."""
    from dsin_tpu.coding.codec import decode_batch
    from dsin_tpu.data.loader import decode_image
    from dsin_tpu.models.quantizer import centers_lookup

    with open(in_path, "rb") as f:
        blob = f.read()
    blob = faults.corrupt("io.read", blob)   # no-op without a fault plan
    _, h, w, hdr_seed, payload = parse_dsim(blob)
    if seed is None:
        seed = hdr_seed
    elif seed != hdr_seed:
        raise ValueError(
            f"--seed {seed} disagrees with the stream header's init seed "
            f"{hdr_seed}: the encoder ran with seed {hdr_seed}, so any "
            f"other init decodes garbage. Drop --seed to trust the header.")

    model, state = _load_model_state(ae_config, pc_config, ckpt, (h, w),
                                     need_sinet=side is not None, seed=seed)
    if side is not None:
        # validate the SI path up front — the entropy decode below is the
        # slow part and must not be wasted on a doomed reconstruction
        ph, pw = model.ae_config.y_patch_size
        if h % ph or w % pw:
            raise ValueError(
                f"image {h}x{w} not divisible by y_patch_size ({ph}, {pw});"
                f" the side-information search needs whole patches")
    codec = _make_codec(model, state)
    symbols = decode_batch(codec, [payload])          # (1, h/8, w/8, C)
    q = centers_lookup(jnp.asarray(state.params["centers"]),
                       jnp.asarray(symbols))
    x_dec, _ = model.decode(state.params, state.batch_stats, q, train=False)

    if side is not None:
        from dsin_tpu.ops.sifinder import (gaussian_position_mask,
                                           synthesize_side_image)
        y = decode_image(side).astype(np.float32)[None]
        if y.shape[1:3] != (h, w):
            raise ValueError(f"side image {y.shape[1:3]} != stream image "
                             f"({h}, {w})")
        y_enc, _ = model.encode(state.params, state.batch_stats,
                                jnp.asarray(y), train=False)
        y_dec, _ = model.decode(state.params, state.batch_stats,
                                y_enc.qbar, train=False)
        ph, pw = model.ae_config.y_patch_size
        mask = (jnp.asarray(gaussian_position_mask(h, w, ph, pw))
                if model.ae_config.use_gauss_mask else None)
        y_syn = synthesize_side_image(x_dec, jnp.asarray(y), y_dec, mask,
                                      ph, pw, model.ae_config)
        out = model.apply_sinet(state.params, x_dec, y_syn)
    else:
        out = x_dec

    img = np.clip(np.asarray(out[0]), 0, 255).astype(np.uint8)
    from PIL import Image
    Image.fromarray(img).save(out_path)
    return {"shape": (h, w), "with_si": side is not None}


def main(argv=None) -> None:
    base = os.path.join(os.path.dirname(__file__), os.pardir, "configs")
    p = argparse.ArgumentParser(description="dsin_tpu image codec")
    sub = p.add_subparsers(dest="cmd", required=True)
    for name in ("compress", "decompress"):
        sp = sub.add_parser(name)
        sp.add_argument("input")
        sp.add_argument("output")
        sp.add_argument("--ae_config",
                        default=os.path.join(base, "ae_kitti_stereo"))
        sp.add_argument("--pc_config",
                        default=os.path.join(base, "pc_default"))
        sp.add_argument("--ckpt", default=None,
                        help="checkpoint dir (weights/<model_name>)")
    sub.choices["compress"].add_argument(
        "--seed", type=int, default=0,
        help="parameter-init PRNG seed, recorded in the stream header "
             "(matters when no --ckpt restores weights)")
    sub.choices["decompress"].add_argument(
        "--seed", type=int, default=None,
        help="assert the stream's init seed (a value disagreeing with "
             "the header is an error — default: trust the header)")
    sub.choices["decompress"].add_argument(
        "--side", default=None,
        help="decoder-side information image (enables the SI path)")
    args = p.parse_args(argv)

    try:
        if args.cmd == "compress":
            info = compress(args.input, args.output, args.ae_config,
                            args.pc_config, args.ckpt, seed=args.seed)
            print(f"{args.output}: {info['bytes']} bytes, "
                  f"{info['bpp']:.4f} bpp @ {info['shape']}")
        else:
            info = decompress(args.input, args.output, args.ae_config,
                              args.pc_config, args.ckpt, args.side,
                              seed=args.seed)
            print(f"{args.output}: reconstructed {info['shape']}"
                  f"{' with side information' if info['with_si'] else ''}")
    except IntegrityError as e:
        # a corrupted stream is an environment failure, not a bug: one
        # clear line naming the CRC mismatch, clean exit 2, no traceback
        print(f"integrity error: {e}", file=sys.stderr)
        raise SystemExit(2)
    except ValueError as e:
        # bad streams / flag-header disagreements are user errors, not
        # crashes: report one clear line, not a traceback
        print(f"error: {e}", file=sys.stderr)
        raise SystemExit(2)


if __name__ == "__main__":
    main()
