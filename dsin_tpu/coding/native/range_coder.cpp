// Byte-wise rANS entropy coder (native backend of dsin_tpu.coding.rans).
//
// The reference repo ships only vestigial arithmetic-coding hooks that are
// never called and whose drivers are missing (reference
// probclass_imgcomp.py:361-482: integer frequency tables at freqs_resolution
// for an external coder that does not exist in the repo). This file is the
// real thing: a static-per-symbol-frequency rANS coder that turns the
// context model's per-position PMFs into an actual bitstream.
//
// Algorithm: standard byte-renormalized rANS ("ryg_rans" construction):
//   state x in [RANS_L, RANS_L*256), RANS_L = 1<<23, frequencies quantized
//   to sum to 1<<scale_bits (scale_bits <= 16).
// Encoding consumes symbols in REVERSE order and emits bytes; the final
// stream is [4-byte little-endian final state][renorm bytes in reverse
// emission order], so the decoder reads strictly forward. Reverse-order
// encoding is fine for an autoregressive context model: the encoder knows
// every symbol up front (teacher forcing); only the DECODER is sequential.
//
// The Python fallback in ../rans.py implements the identical integer
// algorithm; both produce bit-identical streams (tested).

#include <cstdint>
#include <cstdlib>

namespace {

constexpr uint32_t kRansL = 1u << 23;  // lower bound of the state interval

struct Decoder {
  const uint8_t* data;
  long size;
  long pos;       // next byte to read
  uint32_t state;
};

}  // namespace

extern "C" {

// Shared encode core: one independent symbol lane into `out`, renorm
// bytes staged in the caller-provided `scratch` (>= cap bytes). Returns
// bytes written or -1 if cap is too small.
static long encode_lane(const uint32_t* starts, const uint32_t* freqs,
                        long n, int scale_bits, uint8_t* out, long cap,
                        uint8_t* scratch) {
  long sp = 0;
  uint64_t x = kRansL;
  for (long i = n - 1; i >= 0; --i) {
    uint32_t freq = freqs[i];
    // renormalize: keep x < ((RANS_L >> scale_bits) << 8) * freq
    uint64_t x_max =
        (static_cast<uint64_t>(kRansL >> scale_bits) << 8) * freq;
    while (x >= x_max) {
      if (sp >= cap) return -1;
      scratch[sp++] = static_cast<uint8_t>(x & 0xff);
      x >>= 8;
    }
    x = ((x / freq) << scale_bits) + (x % freq) + starts[i];
  }
  long total = sp + 4;
  if (total > cap) return -1;
  out[0] = static_cast<uint8_t>(x & 0xff);
  out[1] = static_cast<uint8_t>((x >> 8) & 0xff);
  out[2] = static_cast<uint8_t>((x >> 16) & 0xff);
  out[3] = static_cast<uint8_t>((x >> 24) & 0xff);
  for (long i = 0; i < sp; ++i) out[4 + i] = scratch[sp - 1 - i];
  return total;
}

// Encode n symbols given per-symbol (start, freq) in FORWARD order.
// Returns the number of bytes written to out, -1 if cap is too small
// (the Python side retries with a doubled cap), or -2 if the scratch
// allocation failed (a retry would only make the OOM worse — the
// Python side raises, coding/rans.py).
// Layout: out[0..3] = final state (LE), then renorm bytes.
long rans_encode(const uint32_t* starts, const uint32_t* freqs, long n,
                 int scale_bits, uint8_t* out, long cap) {
  // Emit into a scratch buffer forward, then reverse into `out`.
  uint8_t* scratch = static_cast<uint8_t*>(malloc(cap > 0 ? cap : 1));
  if (!scratch) return -2;
  long total = encode_lane(starts, freqs, n, scale_bits, out, cap, scratch);
  free(scratch);
  return total;
}

// Batch encode: n_lanes INDEPENDENT symbol lanes packed into one flat
// (starts, freqs) pair; lane i spans [lane_offsets[i], lane_offsets[i+1])
// of the packed arrays and its stream lands at out + out_offsets[i]
// (per-lane capacity out_offsets[i+1] - out_offsets[i] — sized by each
// lane's own length, not the longest lane's) with its byte count in
// out_sizes[i]. One call per micro-batch: the whole loop runs in C with
// the GIL dropped (ctypes releases it for the call), so an entropy-pool
// thread coding a batch no longer serializes the other pool threads'
// Python framing. Streams are byte-identical to n_lanes separate
// rans_encode calls (each lane is a self-contained coder run).
// Returns 0 on success, -(i+1) if lane i overflowed its capacity (the
// Python side retries that lane with a doubled cap, coding/rans.py),
// or -(n_lanes+1) if the scratch allocation failed (OOM: never
// retried with MORE memory).
long rans_encode_batch(const uint32_t* starts, const uint32_t* freqs,
                       const long* lane_offsets, long n_lanes,
                       int scale_bits, uint8_t* out,
                       const long* out_offsets, long* out_sizes) {
  long max_cap = 1;
  for (long i = 0; i < n_lanes; ++i) {
    long cap = out_offsets[i + 1] - out_offsets[i];
    if (cap > max_cap) max_cap = cap;
  }
  uint8_t* scratch = static_cast<uint8_t*>(malloc(max_cap));
  if (!scratch) return -(n_lanes + 1);
  for (long i = 0; i < n_lanes; ++i) {
    long off = lane_offsets[i];
    long n = lane_offsets[i + 1] - off;
    long cap = out_offsets[i + 1] - out_offsets[i];
    long total = encode_lane(starts + off, freqs + off, n, scale_bits,
                             out + out_offsets[i], cap, scratch);
    if (total < 0) { free(scratch); return -(i + 1); }
    out_sizes[i] = total;
  }
  free(scratch);
  return 0;
}

void* rans_decoder_new(const uint8_t* data, long size) {
  if (size < 4) return nullptr;
  Decoder* d = new Decoder;
  d->data = data;
  d->size = size;
  d->state = static_cast<uint32_t>(data[0]) |
             (static_cast<uint32_t>(data[1]) << 8) |
             (static_cast<uint32_t>(data[2]) << 16) |
             (static_cast<uint32_t>(data[3]) << 24);
  d->pos = 4;
  return d;
}

// Cumulative-frequency value of the next symbol (caller maps it to a symbol
// via its cumulative table, then calls rans_decoder_advance).
uint32_t rans_decoder_peek(void* handle, int scale_bits) {
  Decoder* d = static_cast<Decoder*>(handle);
  return d->state & ((1u << scale_bits) - 1);
}

void rans_decoder_advance(void* handle, uint32_t start, uint32_t freq,
                          int scale_bits) {
  Decoder* d = static_cast<Decoder*>(handle);
  uint32_t mask = (1u << scale_bits) - 1;
  uint64_t x = static_cast<uint64_t>(freq) * (d->state >> scale_bits) +
               (d->state & mask) - start;
  while (x < kRansL && d->pos < d->size) {
    x = (x << 8) | d->data[d->pos++];
  }
  d->state = static_cast<uint32_t>(x);
}

void rans_decoder_free(void* handle) {
  delete static_cast<Decoder*>(handle);
}

// Shared decode loop: n symbols, the i-th resolved against the cumulative
// table at cums + i*cum_stride (stride 0 = one static table for all;
// stride num_syms+1 = a fresh adaptive table per symbol).
static void decode_n(Decoder* d, const uint32_t* cums, long cum_stride,
                     int num_syms, long n, int scale_bits, int32_t* out) {
  uint32_t mask = (1u << scale_bits) - 1;
  for (long i = 0; i < n; ++i) {
    const uint32_t* cum = cums + i * cum_stride;
    uint32_t cf = d->state & mask;
    // linear scan: num_syms is small (L=6 centers)
    int s = num_syms - 1;
    for (int j = 1; j <= num_syms; ++j) {
      if (cum[j] > cf) { s = j - 1; break; }
    }
    out[i] = s;
    uint64_t x = static_cast<uint64_t>(cum[s + 1] - cum[s]) *
                     (d->state >> scale_bits) +
                 cf - cum[s];
    while (x < kRansL && d->pos < d->size) {
      x = (x << 8) | d->data[d->pos++];
    }
    d->state = static_cast<uint32_t>(x);
  }
}

// Batched decode of n symbols that all share one frequency table
// (cum: scale-sorted cumulative array of length num_syms+1, cum[num_syms] =
// 1<<scale_bits). Writes symbol indices to out. Used for header-less bulk
// payloads with static tables; the adaptive path peeks/advances per symbol.
void rans_decode_static(void* handle, const uint32_t* cum, int num_syms,
                        long n, int scale_bits, int32_t* out) {
  decode_n(static_cast<Decoder*>(handle), cum, 0, num_syms, n, scale_bits,
           out);
}

// Batched decode of n symbols where EVERY symbol has its own frequency
// table (cums: n rows of num_syms+1 cumulative values, row-major) — the
// adaptive-model hot path. One call replaces n Python-level peek/advance
// round trips per wavefront.
void rans_decode_front(void* handle, const uint32_t* cums, long n,
                       int num_syms, int scale_bits, int32_t* out) {
  decode_n(static_cast<Decoder*>(handle), cums, num_syms + 1, num_syms, n,
           scale_bits, out);
}

// Batch front decode across n_lanes INDEPENDENT streams: lane i's
// decoder advances k_i = lane_offsets[i+1] - lane_offsets[i] symbols,
// the j-th resolved against its own adaptive cumulative table (cums
// rows packed in lane order, num_syms+1 values per row; out shares the
// lane_offsets layout). One call replaces n_lanes rans_decode_front
// round trips per wavefront — the serve entropy stage's decode loop
// over a micro-batch stays in C with the GIL dropped. Per-lane results
// are identical to separate rans_decode_front calls (lanes share no
// state). Empty lanes (k_i = 0) are legal and advance nothing.
void rans_decode_batch(void** handles, const uint32_t* cums,
                       const long* lane_offsets, long n_lanes,
                       int num_syms, int scale_bits, int32_t* out) {
  for (long i = 0; i < n_lanes; ++i) {
    long off = lane_offsets[i];
    long k = lane_offsets[i + 1] - off;
    decode_n(static_cast<Decoder*>(handles[i]),
             cums + off * (num_syms + 1), num_syms + 1, num_syms, k,
             scale_bits, out + off);
  }
}

}  // extern "C"
