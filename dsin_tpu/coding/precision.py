"""Precision ladder for the serving path (ISSUE 19).

A `PrecisionPolicy` names one rung of the inference precision ladder and
knows how to cast a DSIN parameter tree onto it:

* ``fp32``  — the baseline; everything float32 (identity cast).
* ``bf16``  — distortion-side networks (encoder, decoder, siNet) carry
  bfloat16 weights and run their convs in bfloat16 (the AE config's
  ``compute_dtype`` knob, models/autoencoder.py `_ConvBN`).
* ``int8``  — experimental: distortion-side weights are symmetrically
  fake-quantized to 8-bit levels (per-tensor scale = max|w|/127, round,
  dequantize) and stored/run in bfloat16 containers. This measures the
  RD cost of int8 *weights* with today's kernels; a true int8 matmul
  path would keep the same levels, so the RD evidence transfers.

The one hard constraint the ladder must never touch is the rANS
contract: `models/probclass.py` logits feed softmax -> quantized integer
frequency tables (coding/codec.py `_tables_from_logits`) consumed by
`coding/rans.py`, and encoder and decoder must reproduce those tables
BIT-FOR-BIT from their own buffer state. One flipped mantissa bit in a
probclass activation can move a quantized frequency by 1 and desync the
coder mid-stream. The entropy-critical partitions (``probclass``, the
quantizer ``centers`` it conditions on) are therefore *frozen-point-
exact*: `cast_params` never touches them at any rung, and
`check_entropy_critical` verifies every leaf is float32 — the
cross-precision stream bit-identity gate (tests/test_precision.py,
serve_bench ``--precision``) rests on this invariant.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

#: ladder rungs, cheapest-precision last
RUNGS = ("fp32", "bf16", "int8")

#: top-level param partitions pinned to float32 at EVERY rung — the
#: entropy-critical path (probclass logits -> PMFs -> rANS tables)
ENTROPY_CRITICAL = frozenset({"probclass", "centers"})

#: distortion-side partitions a rung may cast (siNet is optional)
DISTORTION_SIDE = ("encoder", "decoder", "sinet")


class PrecisionError(ValueError):
    """Typed refusal: unknown rung or a violated fp32 contract."""


def _fake_quant_int8(leaf: np.ndarray) -> jnp.ndarray:
    """Symmetric per-tensor int8 fake-quant, dequantized into bfloat16.

    scale = max|w|/127; levels are exactly representable as
    (int in [-127, 127]) * scale up to the bf16 rounding of the product,
    which is what the serving matmuls would see anyway."""
    arr = np.asarray(leaf, dtype=np.float32)
    amax = float(np.max(np.abs(arr))) if arr.size else 0.0
    if amax == 0.0:
        return jnp.asarray(arr, dtype=jnp.bfloat16)
    scale = amax / 127.0
    q = np.clip(np.rint(arr / scale), -127, 127)
    return jnp.asarray(q * scale, dtype=jnp.bfloat16)


@dataclass(frozen=True)
class PrecisionPolicy:
    """One rung of the precision ladder; picklable and hashable so it can
    ride a ServiceConfig and a CodecSpec across process boundaries."""

    rung: str = "fp32"

    def __post_init__(self):
        if self.rung not in RUNGS:
            raise PrecisionError(
                f"unknown precision rung {self.rung!r}; ladder is "
                f"{RUNGS}")

    @property
    def compute_dtype(self) -> str:
        """The AE-config ``compute_dtype`` this rung runs its convs in
        (models/autoencoder.py `_ConvBN`): int8 weights still multiply
        on the bf16 MXU path."""
        return "float32" if self.rung == "fp32" else "bfloat16"

    def cast_leaf(self, leaf):
        if self.rung == "fp32":
            return leaf
        if self.rung == "bf16":
            return jnp.asarray(leaf, dtype=jnp.bfloat16)
        return _fake_quant_int8(leaf)

    def cast_params(self, params: dict) -> dict:
        """Cast the distortion-side partitions of a DSIN params dict to
        this rung; entropy-critical partitions pass through UNTOUCHED
        (same leaves, not copies — the fp32 contract is identity-level).
        Unknown partitions are refused rather than guessed at: a future
        partition must be classified here before it can serve."""
        out = {}
        for name, sub in params.items():
            if name in ENTROPY_CRITICAL:
                out[name] = sub
            elif name in DISTORTION_SIDE:
                out[name] = jax.tree_util.tree_map(self.cast_leaf, sub)
            else:
                raise PrecisionError(
                    f"partition {name!r} is neither entropy-critical "
                    f"{sorted(ENTROPY_CRITICAL)} nor distortion-side "
                    f"{list(DISTORTION_SIDE)} — classify it in "
                    f"coding/precision.py before serving it on a "
                    f"precision ladder")
        return out


def check_entropy_critical(params: dict) -> None:
    """Raise `PrecisionError` unless every entropy-critical leaf is
    float32 — the load-time tripwire behind the stream bit-identity
    gate. Called after any cast touches a tree that will feed a codec."""
    for name in ENTROPY_CRITICAL:
        if name not in params:
            continue
        for path, leaf in jax.tree_util.tree_flatten_with_path(
                params[name])[0]:
            dt = jnp.asarray(leaf).dtype
            if dt != jnp.float32:
                raise PrecisionError(
                    f"entropy-critical partition {name!r} leaf "
                    f"{jax.tree_util.keystr(path)} is {dt} — the "
                    f"probclass->rANS path is frozen-point-exact fp32 "
                    f"at every ladder rung")
