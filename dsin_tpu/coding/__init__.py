"""Real entropy coding: rANS range coder + autoregressive bottleneck codec.

The reference never produces a bitstream (its arithmetic-coding hooks are
vestigial, reference probclass_imgcomp.py:361-482); this package does.
"""

from dsin_tpu.coding.codec import (BottleneckCodec, decode_batch,
                                   encode_batch)
from dsin_tpu.coding.rans import (Decoder, cum_from_freqs, encode,
                                  native_available, quantize_pmf)

__all__ = ["BottleneckCodec", "encode_batch", "decode_batch", "Decoder",
           "encode", "quantize_pmf", "cum_from_freqs", "native_available"]
