"""Shared model/codec construction for every codec entry point.

Factored out of coding/cli.py so a long-lived process (dsin_tpu/serve/)
builds model + jit state ONCE and amortizes it across requests, while the
one-shot CLI keeps the identical construction path — the two must not
drift, or a stream compressed by the service would decode against a
differently-wired model in the CLI (and vice versa).

DSIN's modules are fully convolutional: `img_shape` only sizes the dummy
batch that `init_variables` traces shapes with, the resulting parameter
tree is shape-independent. A service can therefore init at one bucket
geometry and run every other bucket through the same parameters.
"""

from __future__ import annotations

import os
import time
import warnings
from dataclasses import dataclass
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def load_model_state(ae_config_path: str, pc_config_path: str,
                     ckpt_dir: Optional[str], img_shape: Tuple[int, int],
                     need_sinet: bool, seed: int = 0,
                     persistent_cache: bool = False,
                     precision: str = "fp32"):
    """Build DSIN (+ optional checkpoint restore) with a minimal state.

    `seed` drives the parameter init and only matters when no checkpoint
    is restored (smoke runs / tests); callers thread their --seed flag
    through so un-checkpointed runs are reproducible without a
    hard-coded key.

    `persistent_cache` points jax's persistent compilation cache at the
    shared repo cache dir (utils/cache.py) BEFORE anything compiles, so
    a restarted long-lived process (dsin_tpu/serve) re-warms from disk
    instead of re-running XLA — the serve warmup dict reports the split
    (compiles vs cache_hits, utils/recompile.py).

    `precision` is a ladder rung (coding/precision.py): the distortion-
    side partitions are cast AFTER the manifest verification (identity
    is checked against what was restored, not what will serve) and the
    AE config's compute_dtype follows the rung; the entropy-critical
    probclass/centers partitions stay frozen-point-exact fp32."""
    if persistent_cache:
        from dsin_tpu.utils.cache import enable_compilation_cache
        enable_compilation_cache()
    from dsin_tpu.coding import precision as precision_lib
    from dsin_tpu.config import parse_config_file
    from dsin_tpu.models.dsin import DSIN
    from dsin_tpu.train import checkpoint as ckpt_lib
    from dsin_tpu.train.step import TrainState

    policy = precision_lib.PrecisionPolicy(precision)
    ae_cfg = parse_config_file(ae_config_path)
    if not need_sinet:
        ae_cfg = ae_cfg.replace(AE_only=True)
    else:
        # symmetric override: a caller that NEEDS the SI path (the
        # enable_si service, ISSUE 10) gets siNet built even from a
        # config snapshot whose training phase set AE_only=True
        ae_cfg = ae_cfg.replace(AE_only=False)
    if policy.rung != "fp32":
        ae_cfg = ae_cfg.replace(compute_dtype=policy.compute_dtype)
    pc_cfg = parse_config_file(pc_config_path)
    model = DSIN(ae_cfg, pc_cfg)
    variables = model.init_variables(jax.random.PRNGKey(seed),
                                     (1, *img_shape, 3))
    state = TrainState(params=variables.params,
                       batch_stats=variables.batch_stats,
                       opt_state=(), step=jnp.int32(0))
    if ckpt_dir:
        parts = list(ckpt_lib.AE_PARTITIONS)
        if need_sinet:
            parts.append("sinet")
        state = ckpt_lib.restore_partitions(ckpt_dir, state, parts)
        # verify what was restored against the checkpoint's manifest
        # (ISSUE 9): a mismatch raises typed ManifestMismatch HERE, at
        # build time — never discovered as flaky bit-identity in
        # production. Pre-manifest checkpoints load with a recorded
        # warning (the operator's cue to re-save with identity).
        info = ckpt_lib.verify_manifest(ckpt_dir, state, parts,
                                        pc_config=pc_cfg)
        if info["status"] == "legacy":
            warnings.warn(
                f"checkpoint {ckpt_dir} predates manifest.json — loaded "
                f"WITHOUT identity verification (re-save it to gain "
                f"digest/pc-hash checks and hot-swap eligibility)",
                stacklevel=2)
    if policy.rung != "fp32":
        # cast AFTER restore + manifest verification: identity checks
        # run against the checkpoint's own bytes, then the serving copy
        # drops to the rung. The tripwire re-proves the rANS contract.
        state = state.replace(params=policy.cast_params(state.params))
        precision_lib.check_entropy_critical(state.params)
    return model, state


def load_swap_state(ckpt_dir: str, state, *, pc_config=None, buckets=None,
                    need_sinet: bool = False):
    """Restore an INCOMING checkpoint's params into a copy of a live
    service's state template (same architecture — the template's pytree
    IS the compatibility contract) and verify its manifest, for the
    hot-swap path. Returns (new_state, manifest_info); any identity
    disagreement raises typed ManifestMismatch, a manifest-less
    checkpoint is REFUSED (unlike cold start, a hot swap replaces a
    known-good model — adopting an unverifiable one silently is exactly
    the failure mode manifests exist to kill)."""
    from dsin_tpu.train import checkpoint as ckpt_lib
    parts = list(ckpt_lib.AE_PARTITIONS)
    if need_sinet:
        parts.append("sinet")
    new_state = ckpt_lib.restore_partitions(ckpt_dir, state, parts)
    info = ckpt_lib.verify_manifest(ckpt_dir, new_state, parts,
                                    pc_config=pc_config, buckets=buckets)
    if info["status"] == "legacy":
        raise ckpt_lib.ManifestMismatch(
            f"checkpoint {ckpt_dir} has no manifest.json — hot-swap "
            f"refuses unversioned checkpoints (re-save it with the "
            f"current trainer to gain a manifest)")
    return new_state, info


def make_codec(model, state):
    """The one BottleneckCodec construction every call site shares."""
    from dsin_tpu.coding.codec import BottleneckCodec
    return BottleneckCodec.for_model(model, state.params)


def params_digest(tree, rung: str = "fp32") -> str:
    """Order-stable digest of a parameter pytree (structure + dtypes +
    shapes + bytes + precision rung). The multi-replica front door
    (serve/router.py) compares every replica's digest at the ready
    handshake: shared-nothing replicas must have built the SAME model
    from the same config/seed/checkpoint, or two replicas would answer
    one request with different bytes — a mismatch is refused at start,
    not discovered as flaky bit-identity in production.

    Every preimage field is length-prefixed (ISSUE 19): the old plain
    concatenation let adjacent fields donate bytes to each other, so
    two different (dtype, shape, bytes) triples could in principle
    collide. The `rung` tag folds the precision ladder into the same
    identity — an fp32 and a bf16 cast of one checkpoint hash apart
    even if a future dtype alias made their leaf descriptions match, so
    the fleet handshake, hot-swap manifests, and canary goldens can
    never mix rungs silently."""
    import hashlib
    h = hashlib.sha256()

    def _field(data: bytes) -> None:
        h.update(len(data).to_bytes(8, "little"))
        h.update(data)

    _field(b"dsin-params-digest-v2")
    _field(str(rung).encode())
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    _field(repr(treedef).encode())
    for leaf in leaves:
        arr = np.asarray(leaf)
        _field(str(arr.dtype).encode())
        _field(str(arr.shape).encode())
        _field(arr.tobytes())
    return h.hexdigest()[:16]


# -- worker-resident codecs (the serve process entropy backend) ---------------
#
# A live BottleneckCodec cannot cross a process boundary: its params are
# backend arrays and its jit wrappers / incremental engine hold
# process-local state. The process entropy backend therefore ships a
# small picklable SPEC instead, and each pool worker rebuilds its codec
# ONCE at initializer time (and warms the per-shape schedule cache for
# the shapes it will serve) — worker-resident state, zero per-task
# construction. The parent's `make_codec_spec` and the worker's
# `codec_from_spec` live side by side here so the two constructions
# cannot drift from `make_codec` above.

@dataclass
class CodecSpec:
    """Everything needed to rebuild a bit-identical BottleneckCodec in
    another process: numpy context-model params, quantizer centers, the
    pc config as its canonical text snapshot (config.py round-trips it),
    the precomputed pad value (so the worker never touches the device
    path during init), and the coder's scale_bits."""
    pc_params: Any
    centers: np.ndarray
    pc_config_text: str
    pad_value: float
    scale_bits: int
    #: precision-ladder rung of the bundle this codec serves alongside
    #: (ISSUE 19). METADATA ONLY: the codec's own numerics are fp32 at
    #: every rung (the probclass path is frozen-point-exact), but a
    #: worker must be able to report which rung its replica runs so
    #: cross-process identity checks can compare like with like.
    rung: str = "fp32"


def make_codec_spec(codec, rung: str = "fp32") -> CodecSpec:
    """Picklable spec from a live BottleneckCodec (the parent side)."""
    return CodecSpec(
        pc_params=jax.tree_util.tree_map(np.asarray, codec.pc_params),
        centers=np.asarray(codec.centers),
        pc_config_text=str(codec.pc_config),
        pad_value=float(codec.pad_value),
        scale_bits=int(codec.scale_bits),
        rung=str(rung))


def codec_from_spec(spec: CodecSpec):
    """Rebuild the codec a spec describes. Streams it produces/consumes
    are bit-identical to the origin codec's: same numpy params, same
    config, same quantized-PMF path (the incremental engine is pure
    numpy, so no cross-process float drift on one host)."""
    from dsin_tpu.coding.codec import BottleneckCodec
    from dsin_tpu.config import parse_config
    from dsin_tpu.models import probclass as pc_lib
    pc_cfg = parse_config(spec.pc_config_text, name="codec_spec")
    # dispatch through the arch registry, exactly like models/dsin.py —
    # a hardcoded class here would silently rebuild the wrong network
    # for any future second arch
    model = pc_lib.get_network_cls(pc_cfg)(
        pc_cfg, num_centers=len(spec.centers))
    return BottleneckCodec(model, spec.pc_params, spec.centers, pc_cfg,
                           scale_bits=spec.scale_bits,
                           pad_value=spec.pad_value)


# one codec per POOL WORKER PROCESS, set exactly once by the pool
# initializer before any task runs — single-threaded within the worker,
# so no lock guards it (ProcessPoolExecutor workers run tasks serially)
_worker_codec = None


#: shm lane ring this worker attached at init (ISSUE 17); None = the
#: pipe transport. Single-threaded per pool worker, like _worker_codec.
_worker_rings = None


def init_worker_codec(spec: CodecSpec,
                      warm_shapes: Sequence[Tuple[int, int, int]] = (),
                      lane_manifest=None) -> None:
    """ProcessPoolExecutor initializer: rebuild the codec once for this
    worker's lifetime and warm its schedule cache for every (D, H, W)
    volume geometry the service's buckets map to — after this, tasks pay
    coding work only. `lane_manifest` (shm transport) attaches this
    worker to the parent's lane ring: task payloads arrive as LaneRef
    descriptors and results write into the parent-claimed reply lane."""
    global _worker_codec, _worker_rings
    if lane_manifest is not None:
        # serve/shmlane.py imports only utils — this is the transport
        # layer reaching down, not coding reaching into the serve stack
        from dsin_tpu.serve import shmlane
        _worker_rings = shmlane.LaneRing.attach(lane_manifest)
    _worker_codec = codec_from_spec(spec)
    eng = _worker_codec._incremental_engine()
    for shape in warm_shapes:
        eng.schedule(tuple(int(s) for s in shape))


def _resolve_task(data):
    """Inline payloads pass through; a LaneRef copies out of the
    attached ring WITHOUT freeing — the parent is the sole allocator
    and reclaims the task lane when the future settles."""
    from dsin_tpu.serve import shmlane
    if not isinstance(data, shmlane.LaneRef):
        return data
    if _worker_rings is None:
        raise shmlane.ShmLaneError(
            "task arrived as a shm lane descriptor but this worker was "
            "initialized without a lane ring — parent and worker "
            "disagree about the transport")
    return _worker_rings.take_obj(data, free=False)


def _lane_reply(result, reply):
    """Ship a task result back through the parent-claimed reply lane
    when it fits (returning the written descriptor), else inline over
    the pipe — the same per-message fallback contract the request
    direction has. The parent frees the reply lane either way."""
    if reply is None or _worker_rings is None:
        return result
    import pickle as _pickle

    from dsin_tpu.serve import shmlane
    blob = _pickle.dumps(result, protocol=_pickle.HIGHEST_PROTOCOL)
    if len(blob) < shmlane.SMALL_INLINE_MAX:
        return result
    try:
        return _worker_rings.write_into(reply, blob)
    except shmlane.ShmLaneError:
        return result          # oversize for the lane: inline fallback


def _resident_codec():
    if _worker_codec is None:
        raise RuntimeError("entropy worker used before init_worker_codec "
                           "ran (ProcessPoolExecutor initializer missing)")
    return _worker_codec


def worker_ping(settle_s: float = 0.05) -> dict:
    """Worker-residence probe (and warmup vehicle): reports this
    worker's pid, its resident codec's identity, and the schedule-cache
    shapes the initializer warmed. The short sleep keeps concurrent
    warmup pings from all landing on one eager worker."""
    time.sleep(settle_s)
    codec = _resident_codec()
    return {"pid": os.getpid(), "codec_id": id(codec),
            "schedules": codec._incremental_engine().cached_shapes()}


def encode_batch_isolated(codec, volumes) -> list:
    """Encode N (D, H, W) symbol volumes -> [(payload, None) |
    (None, exception)] per lane, via the one-native-call batch path,
    retrying lane by lane ONLY if the batch call refuses the set (rare:
    a pathological lane exhausting its capacity doublings, a scratch
    allocation failure) — the encode half of the per-lane
    fault-isolation contract, mirroring decode_batch_isolated: one
    lane's coding error must fail only ITS request, never its
    batchmates."""
    try:
        return [(p, None) for p in codec.encode_batch(list(volumes))]
    except Exception:
        out = []
        for vol in volumes:
            try:
                out.append((codec.encode(vol), None))
            except Exception as exc:  # noqa: BLE001 — per-lane isolation
                out.append((None, exc))
        return out


def _traced_task(fn, data, trace):
    """Run a coding task in this worker, echoing the serialized trace
    contexts back with the child-side timing (ISSUE 11): the parent
    bridge bit-checks the echo against what it sent (the propagation
    contract across the spawn boundary) and records the child's coding
    span. `trace` is an opaque picklable tuple of TraceContexts —
    nothing here imports the serve stack."""
    t0 = time.monotonic()
    out = fn(data)
    t1 = time.monotonic()
    return out, {"trace": trace, "pid": os.getpid(),
                 "coding_ms": (t1 - t0) * 1e3}


def worker_encode_batch(volumes, trace=None, reply=None):
    """Process-pool task: encode N (D, H, W) symbol volumes with the
    resident codec — one native rANS call for the whole micro-batch,
    per-lane isolation on refusal (encode_batch_isolated's
    [(payload, None) | (None, exception)] contract). With `trace`
    (sampled TraceContexts riding the task), returns (lanes, echo) —
    the echo carries the contexts back bit-identical plus the
    child-side coding time. shm transport: `volumes` may arrive as a
    LaneRef and `reply` as a parent-claimed reply lane the result
    writes into (descriptor back, bytes out of band)."""
    volumes = _resolve_task(volumes)
    if trace is None:
        out = encode_batch_isolated(_resident_codec(), volumes)
    else:
        out = _traced_task(
            lambda v: encode_batch_isolated(_resident_codec(), v),
            volumes, trace)
    return _lane_reply(out, reply)


def decode_batch_isolated(codec, payloads) -> list:
    """Decode N DTPC payloads -> [(volume, None) | (None, exception)]
    per lane, via the lockstep batch path, retrying lane by lane ONLY
    if the batch refuses the set (rare header/structure errors) — the
    per-lane fault-isolation contract both serve entropy backends
    share (service.py thread path, worker_decode_batch process path)."""
    try:
        return [(vol, None) for vol in codec.decode_batch(list(payloads))]
    except Exception:
        out = []
        for blob in payloads:
            try:
                out.append((codec.decode(blob), None))
            except Exception as exc:  # noqa: BLE001 — per-lane isolation
                out.append((None, exc))
        return out


def worker_decode_batch(payloads, trace=None, reply=None):
    """Process-pool task: decode N payloads with the resident codec.
    Payloads arrive CRC-verified (the parent-side bridge keeps the
    per-request verify + fault-site semantics). `trace` as in
    `worker_encode_batch`: (lanes, echo) when contexts ride the task.
    `payloads`/`reply` lane semantics as in `worker_encode_batch`."""
    payloads = _resolve_task(payloads)
    if trace is None:
        out = decode_batch_isolated(_resident_codec(), payloads)
    else:
        out = _traced_task(
            lambda p: decode_batch_isolated(_resident_codec(), p),
            payloads, trace)
    return _lane_reply(out, reply)
