"""Shared model/codec construction for every codec entry point.

Factored out of coding/cli.py so a long-lived process (dsin_tpu/serve/)
builds model + jit state ONCE and amortizes it across requests, while the
one-shot CLI keeps the identical construction path — the two must not
drift, or a stream compressed by the service would decode against a
differently-wired model in the CLI (and vice versa).

DSIN's modules are fully convolutional: `img_shape` only sizes the dummy
batch that `init_variables` traces shapes with, the resulting parameter
tree is shape-independent. A service can therefore init at one bucket
geometry and run every other bucket through the same parameters.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def load_model_state(ae_config_path: str, pc_config_path: str,
                     ckpt_dir: Optional[str], img_shape: Tuple[int, int],
                     need_sinet: bool, seed: int = 0,
                     persistent_cache: bool = False):
    """Build DSIN (+ optional checkpoint restore) with a minimal state.

    `seed` drives the parameter init and only matters when no checkpoint
    is restored (smoke runs / tests); callers thread their --seed flag
    through so un-checkpointed runs are reproducible without a
    hard-coded key.

    `persistent_cache` points jax's persistent compilation cache at the
    shared repo cache dir (utils/cache.py) BEFORE anything compiles, so
    a restarted long-lived process (dsin_tpu/serve) re-warms from disk
    instead of re-running XLA — the serve warmup dict reports the split
    (compiles vs cache_hits, utils/recompile.py)."""
    if persistent_cache:
        from dsin_tpu.utils.cache import enable_compilation_cache
        enable_compilation_cache()
    from dsin_tpu.config import parse_config_file
    from dsin_tpu.models.dsin import DSIN
    from dsin_tpu.train import checkpoint as ckpt_lib
    from dsin_tpu.train.step import TrainState

    ae_cfg = parse_config_file(ae_config_path)
    if not need_sinet:
        ae_cfg = ae_cfg.replace(AE_only=True)
    pc_cfg = parse_config_file(pc_config_path)
    model = DSIN(ae_cfg, pc_cfg)
    variables = model.init_variables(jax.random.PRNGKey(seed),
                                     (1, *img_shape, 3))
    state = TrainState(params=variables.params,
                       batch_stats=variables.batch_stats,
                       opt_state=(), step=jnp.int32(0))
    if ckpt_dir:
        parts = list(ckpt_lib.AE_PARTITIONS)
        if need_sinet:
            parts.append("sinet")
        state = ckpt_lib.restore_partitions(ckpt_dir, state, parts)
    return model, state


def make_codec(model, state):
    """The one BottleneckCodec construction every call site shares."""
    from dsin_tpu.coding.codec import BottleneckCodec
    return BottleneckCodec.for_model(model, state.params)
