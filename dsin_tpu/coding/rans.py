"""rANS entropy coder: ctypes bindings to the native backend + pure-Python
fallback.

Both paths implement the identical integer algorithm (see
native/range_coder.cpp for the construction and the bitstream layout) and
produce bit-identical streams. The native library is compiled on demand with
g++ into ``native/_build/`` and loaded via ctypes; if compilation or loading
fails (no toolchain), the Python implementation takes over transparently.

Reference counterpart: none functional — the reference's arithmetic-coding
hooks are vestigial (reference probclass_imgcomp.py:361-364: their drivers
``val.py``/``bpp_helpers.py`` do not exist in the repo). This module closes
that gap.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Sequence

import numpy as np

from dsin_tpu.utils import locks as locks_lib

RANS_L = 1 << 23
DEFAULT_SCALE_BITS = 16

_HERE = os.path.dirname(__file__)
_SRC = os.path.join(_HERE, "native", "range_coder.cpp")
_BUILD_DIR = os.path.join(_HERE, "native", "_build")
_LIB_PATH = os.path.join(_BUILD_DIR, "librange_coder.so")

_lib_lock = locks_lib.RankedLock("rans.native")
_lib: Optional[ctypes.CDLL] = None    # guarded-by: _lib_lock (module)
_lib_tried = False                    # guarded-by: _lib_lock (module)


class _NativeLoadError(RuntimeError):
    """Internal: one compile-or-bind attempt failed (retriable)."""


def _compile_native() -> Optional[str]:
    try:
        os.makedirs(_BUILD_DIR, exist_ok=True)
        if (os.path.exists(_LIB_PATH)
                and os.path.getmtime(_LIB_PATH) >= os.path.getmtime(_SRC)):
            return _LIB_PATH
        # compile to a private temp name, then rename atomically so
        # concurrent processes never dlopen a half-written .so
        tmp = os.path.join(_BUILD_DIR, f".range_coder.{os.getpid()}.so")
        cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC,
               "-o", tmp]
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _LIB_PATH)
    except (OSError, subprocess.SubprocessError):
        return None
    return _LIB_PATH


def _load_native() -> Optional[ctypes.CDLL]:
    global _lib, _lib_tried
    with _lib_lock:
        if _lib_tried:
            return _lib
        _lib_tried = True

        def _attempt() -> ctypes.CDLL:
            lib = _load_and_bind()
            if lib is None:
                raise _NativeLoadError("compile/dlopen/bind failed")
            return lib

        def _force_rebuild(attempt: int, exc: BaseException) -> None:
            # a stale prebuilt .so (restored cache / copied tree with
            # newer mtimes) can pass the mtime check yet miss newer
            # symbols — drop it so _compile_native rebuilds from source
            try:
                if os.path.exists(_LIB_PATH):
                    os.remove(_LIB_PATH)
            except OSError:
                pass

        from dsin_tpu.utils.retry import RetryPolicy, call_with_retry
        try:
            # one forced rebuild + retry (shared policy), then give up:
            # the pure-Python implementation takes over transparently
            _lib = call_with_retry(
                _attempt, RetryPolicy(max_attempts=2, base_delay_s=0.0),
                retry_on=(_NativeLoadError,), on_retry=_force_rebuild)
        except _NativeLoadError:
            _lib = None
        return _lib


def _load_and_bind() -> Optional[ctypes.CDLL]:
    path = _compile_native()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        u32p = ctypes.POINTER(ctypes.c_uint32)
        lib.rans_encode.restype = ctypes.c_long
        lib.rans_encode.argtypes = [u32p, u32p, ctypes.c_long, ctypes.c_int,
                                    u8p, ctypes.c_long]
        lib.rans_decoder_new.restype = ctypes.c_void_p
        lib.rans_decoder_new.argtypes = [u8p, ctypes.c_long]
        lib.rans_decoder_peek.restype = ctypes.c_uint32
        lib.rans_decoder_peek.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.rans_decoder_advance.restype = None
        lib.rans_decoder_advance.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                                             ctypes.c_uint32, ctypes.c_int]
        lib.rans_decoder_free.restype = None
        lib.rans_decoder_free.argtypes = [ctypes.c_void_p]
        lib.rans_decode_static.restype = None
        lib.rans_decode_static.argtypes = [
            ctypes.c_void_p, u32p, ctypes.c_int, ctypes.c_long, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int32)]
        lib.rans_decode_front.restype = None
        lib.rans_decode_front.argtypes = [
            ctypes.c_void_p, u32p, ctypes.c_long, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int32)]
        return lib
    except (OSError, AttributeError):
        # OSError: dlopen failure; AttributeError: the .so predates a
        # symbol — _load_native forces one rebuild and retries via
        # utils/retry before falling back to the pure-Python path
        return None


def native_available() -> bool:
    return _load_native() is not None


# -- encode -------------------------------------------------------------------

def _encode_py(starts: np.ndarray, freqs: np.ndarray,
               scale_bits: int) -> bytes:
    out = bytearray()
    x = RANS_L
    shift = (RANS_L >> scale_bits) << 8
    for i in range(len(starts) - 1, -1, -1):
        freq = int(freqs[i])
        x_max = shift * freq
        while x >= x_max:
            out.append(x & 0xFF)
            x >>= 8
        x = ((x // freq) << scale_bits) + (x % freq) + int(starts[i])
    head = bytes((x & 0xFF, (x >> 8) & 0xFF, (x >> 16) & 0xFF,
                  (x >> 24) & 0xFF))
    return head + bytes(reversed(out))


def encode(starts: Sequence[int], freqs: Sequence[int],
           scale_bits: int = DEFAULT_SCALE_BITS) -> bytes:
    """Encode n symbols given per-symbol cumulative start and frequency
    (forward order). freq must be >= 1 and start+freq <= 1<<scale_bits."""
    starts = np.ascontiguousarray(starts, dtype=np.uint32)
    freqs = np.ascontiguousarray(freqs, dtype=np.uint32)
    if starts.shape != freqs.shape or starts.ndim != 1:
        raise ValueError(f"starts/freqs mismatch: {starts.shape} vs "
                         f"{freqs.shape}")
    if len(freqs) and int(freqs.min()) < 1:
        # freq=0 would be an unencodable symbol (and integer div-by-zero
        # in the native coder)
        raise ValueError("all frequencies must be >= 1")
    lib = _load_native()
    if lib is None:
        return _encode_py(starts, freqs, scale_bits)
    # worst case ~4 bytes/symbol at scale_bits<=16, plus state flush
    cap = 8 * len(starts) + 64
    out = np.empty(cap, dtype=np.uint8)
    n = lib.rans_encode(
        starts.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        freqs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        len(starts), scale_bits,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), cap)
    if n < 0:
        raise RuntimeError("rans_encode: buffer overflow")
    return out[:n].tobytes()


# -- decode -------------------------------------------------------------------

class Decoder:
    """Sequential rANS decoder over one bitstream.

    peek() returns the cumulative-frequency value of the next symbol; the
    caller resolves it to a symbol against its own cumulative table and calls
    advance(start, freq). This split is what lets an autoregressive model
    supply a fresh PMF per position.
    """

    def __init__(self, data: bytes, scale_bits: int = DEFAULT_SCALE_BITS):
        if len(data) < 4:
            raise ValueError("truncated rANS stream (< 4 bytes)")
        self.scale_bits = scale_bits
        self._lib = _load_native()
        if self._lib is not None:
            self._buf = np.frombuffer(data, dtype=np.uint8)
            self._handle = self._lib.rans_decoder_new(
                self._buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                len(data))
            if not self._handle:
                raise ValueError("rANS decoder rejected the stream")
        else:
            self._data = data
            self._state = int.from_bytes(data[:4], "little")
            self._pos = 4

    def peek(self) -> int:
        if self._lib is not None:
            return int(self._lib.rans_decoder_peek(self._handle,
                                                   self.scale_bits))
        return self._state & ((1 << self.scale_bits) - 1)

    def advance(self, start: int, freq: int) -> None:
        if self._lib is not None:
            self._lib.rans_decoder_advance(self._handle, start, freq,
                                           self.scale_bits)
            return
        mask = (1 << self.scale_bits) - 1
        x = freq * (self._state >> self.scale_bits) \
            + (self._state & mask) - start
        while x < RANS_L and self._pos < len(self._data):
            x = (x << 8) | self._data[self._pos]
            self._pos += 1
        self._state = x

    def decode_symbol(self, cum: np.ndarray) -> int:
        """Resolve + consume one symbol against cumulative table `cum`
        (length L+1, cum[L] == 1<<scale_bits)."""
        cf = self.peek()
        s = int(np.searchsorted(cum, cf, side="right")) - 1
        self.advance(int(cum[s]), int(cum[s + 1] - cum[s]))
        return s

    def decode_front(self, cums: np.ndarray) -> np.ndarray:
        """Decode one symbol per row of `cums` ((n, L+1) cumulative tables,
        one fresh adaptive table per symbol) — the wavefront hot path. One
        native call instead of n peek/advance round trips."""
        cums = np.ascontiguousarray(cums, dtype=np.uint32)
        n = cums.shape[0]
        if self._lib is not None:
            out = np.empty(n, dtype=np.int32)
            self._lib.rans_decode_front(
                self._handle,
                cums.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
                n, cums.shape[1] - 1, self.scale_bits,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
            return out
        return np.array([self.decode_symbol(cums[i]) for i in range(n)],
                        dtype=np.int32)

    def decode_static(self, cum: np.ndarray, n: int) -> np.ndarray:
        """Decode n symbols sharing one cumulative table (bulk path)."""
        cum = np.ascontiguousarray(cum, dtype=np.uint32)
        if self._lib is not None:
            out = np.empty(n, dtype=np.int32)
            self._lib.rans_decode_static(
                self._handle,
                cum.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
                len(cum) - 1, n, self.scale_bits,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
            return out
        return np.array([self.decode_symbol(cum) for _ in range(n)],
                        dtype=np.int32)

    def close(self) -> None:
        if self._lib is not None and self._handle:
            self._lib.rans_decoder_free(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# -- pmf quantization ---------------------------------------------------------

def quantize_pmf(pmf: np.ndarray,
                 scale_bits: int = DEFAULT_SCALE_BITS) -> np.ndarray:
    """Deterministically quantize a float PMF to integer frequencies summing
    to 1<<scale_bits, every entry >= 1 (so any symbol stays decodable —
    the reference's hooks had the same all-nonzero requirement via
    +1 smoothing, reference probclass_imgcomp.py:470-476)."""
    total = 1 << scale_bits
    pmf = np.asarray(pmf, dtype=np.float64)
    pmf = np.maximum(pmf, 0.0)
    norm = pmf.sum()
    if not np.isfinite(norm) or norm <= 0:
        pmf = np.ones_like(pmf)
        norm = pmf.sum()
    freqs = np.floor(pmf / norm * total).astype(np.int64)
    freqs = np.maximum(freqs, 1)
    # deterministic fix-up of the rounding drift: push the difference onto
    # the largest bins (ties -> lowest index via argmax), never below 1
    diff = total - int(freqs.sum())
    while diff != 0:
        if diff > 0:
            freqs[int(np.argmax(freqs))] += diff
            diff = 0
        else:
            i = int(np.argmax(freqs))
            take = min(-diff, int(freqs[i]) - 1)
            if take == 0:
                raise ValueError("cannot satisfy min-frequency constraint")
            freqs[i] -= take
            diff += take
    return freqs.astype(np.uint32)


def quantize_pmf_batch(pmfs: np.ndarray,
                       scale_bits: int = DEFAULT_SCALE_BITS) -> np.ndarray:
    """Row-wise `quantize_pmf` over (B, L) PMFs, bit-identical results.

    The common path (floor + clamp, positive drift onto the argmax bin —
    a single step in the scalar routine too) is fully vectorized; rows
    needing the rare negative-drift loop fall back to the scalar function.
    """
    total = 1 << scale_bits
    pmfs = np.asarray(pmfs, dtype=np.float64)
    pmfs = np.maximum(pmfs, 0.0)
    norm = pmfs.sum(axis=1, keepdims=True)
    bad = ~np.isfinite(norm[:, 0]) | (norm[:, 0] <= 0)
    if bad.any():
        pmfs = pmfs.copy()
        pmfs[bad] = 1.0
        norm = pmfs.sum(axis=1, keepdims=True)
    freqs = np.floor(pmfs / norm * total).astype(np.int64)
    freqs = np.maximum(freqs, 1)
    diff = total - freqs.sum(axis=1)
    pos = diff > 0
    if pos.any():
        rows = np.flatnonzero(pos)
        freqs[rows, np.argmax(freqs[rows], axis=1)] += diff[rows]
    for r in np.flatnonzero(diff < 0):
        freqs[r] = quantize_pmf(pmfs[r], scale_bits)
    return freqs.astype(np.uint32)


def cum_from_freqs_batch(freqs: np.ndarray) -> np.ndarray:
    """Row-wise `cum_from_freqs`: (B, L) -> (B, L+1) uint32."""
    freqs = np.asarray(freqs, dtype=np.uint64)
    out = np.zeros((freqs.shape[0], freqs.shape[1] + 1), dtype=np.uint64)
    np.cumsum(freqs, axis=1, out=out[:, 1:])
    return out.astype(np.uint32)


def cum_from_freqs(freqs: np.ndarray) -> np.ndarray:
    """Cumulative table (L+1,) from frequencies (L,)."""
    cum = np.zeros(len(freqs) + 1, dtype=np.uint32)
    np.cumsum(freqs, out=cum[1:])
    return cum
