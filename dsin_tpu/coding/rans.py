"""rANS entropy coder: ctypes bindings to the native backend + pure-Python
fallback.

Both paths implement the identical integer algorithm (see
native/range_coder.cpp for the construction and the bitstream layout) and
produce bit-identical streams. The native library is compiled on demand with
g++ into ``native/_build/`` and loaded via ctypes; if compilation or loading
fails (no toolchain), the Python implementation takes over transparently.

Reference counterpart: none functional — the reference's arithmetic-coding
hooks are vestigial (reference probclass_imgcomp.py:361-364: their drivers
``val.py``/``bpp_helpers.py`` do not exist in the repo). This module closes
that gap.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from dsin_tpu.utils import locks as locks_lib

RANS_L = 1 << 23
DEFAULT_SCALE_BITS = 16

_HERE = os.path.dirname(__file__)
_SRC = os.path.join(_HERE, "native", "range_coder.cpp")
_BUILD_DIR = os.path.join(_HERE, "native", "_build")
_LIB_PATH = os.path.join(_BUILD_DIR, "librange_coder.so")

_lib_lock = locks_lib.RankedLock("rans.native")
_lib: Optional[ctypes.CDLL] = None    # guarded-by: _lib_lock (module)
_lib_tried = False                    # guarded-by: _lib_lock (module)

# per-entry-point native invocation counts — the test probe behind the
# "one native call per micro-batch" contract (tests/test_rans_batch.py
# and the serve entropy-stage tests read these)
_counts_lock = locks_lib.RankedLock("rans.counters")
_native_calls: Dict[str, int] = {}    # guarded-by: _counts_lock (module)


def _count(name: str) -> None:
    with _counts_lock:
        _native_calls[name] = _native_calls.get(name, 0) + 1


def native_call_counts() -> Dict[str, int]:
    """{entry point: native invocations since the last reset} — counts
    only calls that actually crossed into the C library (the pure-Python
    fallback does not bump them)."""
    with _counts_lock:
        return dict(_native_calls)


def reset_native_call_counts() -> None:
    with _counts_lock:
        _native_calls.clear()


class _NativeLoadError(RuntimeError):
    """Internal: one compile-or-bind attempt failed (retriable)."""


class RansCapacityError(RuntimeError):
    """The native encoder overflowed its output buffer even after the
    doubled-cap retries — the stream expanded past every offered
    capacity. Never silently falls back to the Python path: the caller
    must see the condition (a silent re-run would hide a native-layer
    bug behind a ~100x slowdown)."""


def _compile_native() -> Optional[str]:
    try:
        os.makedirs(_BUILD_DIR, exist_ok=True)
        if (os.path.exists(_LIB_PATH)
                and os.path.getmtime(_LIB_PATH) >= os.path.getmtime(_SRC)):
            return _LIB_PATH
        # compile to a private temp name, then rename atomically so
        # concurrent processes never dlopen a half-written .so
        tmp = os.path.join(_BUILD_DIR, f".range_coder.{os.getpid()}.so")
        cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC,
               "-o", tmp]
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _LIB_PATH)
    except (OSError, subprocess.SubprocessError):
        return None
    return _LIB_PATH


def _load_native() -> Optional[ctypes.CDLL]:
    global _lib, _lib_tried
    with _lib_lock:
        if _lib_tried:
            return _lib
        _lib_tried = True

        def _attempt() -> ctypes.CDLL:
            lib = _load_and_bind()
            if lib is None:
                raise _NativeLoadError("compile/dlopen/bind failed")
            return lib

        def _force_rebuild(attempt: int, exc: BaseException) -> None:
            # a stale prebuilt .so (restored cache / copied tree with
            # newer mtimes) can pass the mtime check yet miss newer
            # symbols — drop it so _compile_native rebuilds from source
            try:
                if os.path.exists(_LIB_PATH):
                    os.remove(_LIB_PATH)
            except OSError:
                pass

        from dsin_tpu.utils.retry import RetryPolicy, call_with_retry
        try:
            # one forced rebuild + retry (shared policy), then give up:
            # the pure-Python implementation takes over transparently
            _lib = call_with_retry(
                _attempt, RetryPolicy(max_attempts=2, base_delay_s=0.0),
                retry_on=(_NativeLoadError,), on_retry=_force_rebuild)
        except _NativeLoadError:
            _lib = None
        return _lib


def _load_and_bind() -> Optional[ctypes.CDLL]:
    path = _compile_native()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        u32p = ctypes.POINTER(ctypes.c_uint32)
        lib.rans_encode.restype = ctypes.c_long
        lib.rans_encode.argtypes = [u32p, u32p, ctypes.c_long, ctypes.c_int,
                                    u8p, ctypes.c_long]
        lib.rans_decoder_new.restype = ctypes.c_void_p
        lib.rans_decoder_new.argtypes = [u8p, ctypes.c_long]
        lib.rans_decoder_peek.restype = ctypes.c_uint32
        lib.rans_decoder_peek.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.rans_decoder_advance.restype = None
        lib.rans_decoder_advance.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                                             ctypes.c_uint32, ctypes.c_int]
        lib.rans_decoder_free.restype = None
        lib.rans_decoder_free.argtypes = [ctypes.c_void_p]
        lib.rans_decode_static.restype = None
        lib.rans_decode_static.argtypes = [
            ctypes.c_void_p, u32p, ctypes.c_int, ctypes.c_long, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int32)]
        lib.rans_decode_front.restype = None
        lib.rans_decode_front.argtypes = [
            ctypes.c_void_p, u32p, ctypes.c_long, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int32)]
        i64p = ctypes.POINTER(ctypes.c_long)
        lib.rans_encode_batch.restype = ctypes.c_long
        lib.rans_encode_batch.argtypes = [
            u32p, u32p, i64p, ctypes.c_long, ctypes.c_int, u8p,
            i64p, i64p]
        lib.rans_decode_batch.restype = None
        lib.rans_decode_batch.argtypes = [
            ctypes.POINTER(ctypes.c_void_p), u32p, i64p, ctypes.c_long,
            ctypes.c_int, ctypes.c_int, ctypes.POINTER(ctypes.c_int32)]
        return lib
    except (OSError, AttributeError):
        # OSError: dlopen failure; AttributeError: the .so predates a
        # symbol — _load_native forces one rebuild and retries via
        # utils/retry before falling back to the pure-Python path
        return None


def native_available() -> bool:
    return _load_native() is not None


# -- encode -------------------------------------------------------------------

def _encode_py(starts: np.ndarray, freqs: np.ndarray,
               scale_bits: int) -> bytes:
    out = bytearray()
    x = RANS_L
    shift = (RANS_L >> scale_bits) << 8
    for i in range(len(starts) - 1, -1, -1):
        freq = int(freqs[i])
        x_max = shift * freq
        while x >= x_max:
            out.append(x & 0xFF)
            x >>= 8
        x = ((x // freq) << scale_bits) + (x % freq) + int(starts[i])
    head = bytes((x & 0xFF, (x >> 8) & 0xFF, (x >> 16) & 0xFF,
                  (x >> 24) & 0xFF))
    return head + bytes(reversed(out))


#: capacity-retry policy for the native encoder: start from
#: `_encode_cap(n)` and double up to this many times before raising the
#: typed RansCapacityError. The initial cap (8 bytes/symbol + flush) is
#: already ~4x the true worst case (renorm emits <= scale_bits bits per
#: symbol, 2 bytes at scale_bits=16), so a real stream never retries —
#: tests shrink `_encode_cap` to exercise the path deterministically.
_CAP_DOUBLINGS = 4


def _encode_cap(n: int) -> int:
    """Initial output capacity for an n-symbol lane (bytes)."""
    return 8 * n + 64


def _validate_lane(starts: np.ndarray, freqs: np.ndarray) -> None:
    if starts.shape != freqs.shape or starts.ndim != 1:
        raise ValueError(f"starts/freqs mismatch: {starts.shape} vs "
                         f"{freqs.shape}")
    if len(freqs) and int(freqs.min()) < 1:
        # freq=0 would be an unencodable symbol (and integer div-by-zero
        # in the native coder)
        raise ValueError("all frequencies must be >= 1")


def encode(starts: Sequence[int], freqs: Sequence[int],
           scale_bits: int = DEFAULT_SCALE_BITS) -> bytes:
    """Encode n symbols given per-symbol cumulative start and frequency
    (forward order). freq must be >= 1 and start+freq <= 1<<scale_bits."""
    starts = np.ascontiguousarray(starts, dtype=np.uint32)
    freqs = np.ascontiguousarray(freqs, dtype=np.uint32)
    _validate_lane(starts, freqs)
    lib = _load_native()
    if lib is None:
        return _encode_py(starts, freqs, scale_bits)
    cap = _encode_cap(len(starts))
    for _ in range(_CAP_DOUBLINGS + 1):
        out = np.empty(cap, dtype=np.uint8)
        _count("encode")
        n = lib.rans_encode(
            starts.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            freqs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            len(starts), scale_bits,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), cap)
        if n >= 0:
            return out[:n].tobytes()
        if n == -2:
            # scratch malloc failed: retrying with a DOUBLED buffer
            # would only deepen the OOM — surface it as what it is
            raise MemoryError("rans_encode: native scratch allocation "
                              "failed")
        # cap too small (-1): retry with double the room — the output is
        # re-encoded from scratch, so the retried stream is bit-identical
        # to what a large-enough first cap would have produced
        cap *= 2
    raise RansCapacityError(
        f"rans_encode overflowed a {cap // 2}-byte buffer for "
        f"{len(starts)} symbols after {_CAP_DOUBLINGS} doublings")


def encode_batch(starts_list: Sequence[np.ndarray],
                 freqs_list: Sequence[np.ndarray],
                 scale_bits: int = DEFAULT_SCALE_BITS) -> List[bytes]:
    """Encode N independent symbol lanes in ONE native call.

    Lane i is `(starts_list[i], freqs_list[i])` in forward order; lanes
    may be ragged (different lengths, empty lanes are legal). Streams
    are bit-identical to N separate `encode` calls — each lane is a
    self-contained coder run; batching only moves the per-lane loop into
    C so a micro-batch costs one GIL-dropping ctypes call instead of N
    (dsin_tpu/serve's entropy stage). Falls back to the per-lane Python
    coder when the native library is unavailable."""
    if len(starts_list) != len(freqs_list):
        raise ValueError(f"{len(starts_list)} starts lanes vs "
                         f"{len(freqs_list)} freqs lanes")
    lanes = [(np.ascontiguousarray(s, dtype=np.uint32),
              np.ascontiguousarray(f, dtype=np.uint32))
             for s, f in zip(starts_list, freqs_list)]
    for s, f in lanes:
        _validate_lane(s, f)
    if not lanes:
        return []
    lib = _load_native()
    if lib is None:
        return [_encode_py(s, f, scale_bits) for s, f in lanes]
    offsets = np.zeros(len(lanes) + 1, dtype=np.int64)
    np.cumsum([len(s) for s, _ in lanes], out=offsets[1:])
    starts = (np.concatenate([s for s, _ in lanes])
              if offsets[-1] else np.zeros(0, np.uint32))
    freqs = (np.concatenate([f for _, f in lanes])
             if offsets[-1] else np.zeros(0, np.uint32))
    i64p = ctypes.POINTER(ctypes.c_long)
    # per-lane output capacity (sized by each lane's own length — a
    # ragged batch with one huge lane must not allocate huge slots for
    # every small lane); on overflow only the GUILTY lane's cap doubles
    caps = np.array([_encode_cap(len(s)) for s, _ in lanes],
                    dtype=np.int64)
    doublings = np.zeros(len(lanes), dtype=np.int64)
    while True:
        out_offsets = np.zeros(len(lanes) + 1, dtype=np.int64)
        np.cumsum(caps, out=out_offsets[1:])
        out = np.empty(int(out_offsets[-1]), dtype=np.uint8)
        sizes = np.zeros(len(lanes), dtype=np.int64)
        _count("encode_batch")
        rc = lib.rans_encode_batch(
            starts.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            freqs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            offsets.ctypes.data_as(i64p), len(lanes), scale_bits,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            out_offsets.ctypes.data_as(i64p),
            sizes.ctypes.data_as(i64p))
        if rc == 0:
            return [out[out_offsets[i]:out_offsets[i] + int(sizes[i])]
                    .tobytes() for i in range(len(lanes))]
        if rc == -(len(lanes) + 1):
            raise MemoryError("rans_encode_batch: native scratch "
                              "allocation failed")
        # -(i+1): lane i overflowed its cap — double THAT lane and
        # re-run the batch (lanes are deterministic, so the retried
        # streams are bit-identical; the overflow is pathological, see
        # _CAP_DOUBLINGS)
        guilty = -int(rc) - 1
        if doublings[guilty] >= _CAP_DOUBLINGS:
            raise RansCapacityError(
                f"rans_encode_batch overflowed a {int(caps[guilty])}-"
                f"byte lane buffer (lane {guilty} of {len(lanes)}) "
                f"after {_CAP_DOUBLINGS} doublings")
        caps[guilty] *= 2
        doublings[guilty] += 1


# -- decode -------------------------------------------------------------------

class Decoder:
    """Sequential rANS decoder over one bitstream.

    peek() returns the cumulative-frequency value of the next symbol; the
    caller resolves it to a symbol against its own cumulative table and calls
    advance(start, freq). This split is what lets an autoregressive model
    supply a fresh PMF per position.
    """

    def __init__(self, data: bytes, scale_bits: int = DEFAULT_SCALE_BITS):
        if len(data) < 4:
            raise ValueError("truncated rANS stream (< 4 bytes)")
        self.scale_bits = scale_bits
        self._lib = _load_native()
        if self._lib is not None:
            self._buf = np.frombuffer(data, dtype=np.uint8)
            self._handle = self._lib.rans_decoder_new(
                self._buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                len(data))
            if not self._handle:
                raise ValueError("rANS decoder rejected the stream")
        else:
            self._data = data
            self._state = int.from_bytes(data[:4], "little")
            self._pos = 4

    def peek(self) -> int:
        if self._lib is not None:
            return int(self._lib.rans_decoder_peek(self._handle,
                                                   self.scale_bits))
        return self._state & ((1 << self.scale_bits) - 1)

    def advance(self, start: int, freq: int) -> None:
        if self._lib is not None:
            self._lib.rans_decoder_advance(self._handle, start, freq,
                                           self.scale_bits)
            return
        mask = (1 << self.scale_bits) - 1
        x = freq * (self._state >> self.scale_bits) \
            + (self._state & mask) - start
        while x < RANS_L and self._pos < len(self._data):
            x = (x << 8) | self._data[self._pos]
            self._pos += 1
        self._state = x

    def decode_symbol(self, cum: np.ndarray) -> int:
        """Resolve + consume one symbol against cumulative table `cum`
        (length L+1, cum[L] == 1<<scale_bits)."""
        cf = self.peek()
        s = int(np.searchsorted(cum, cf, side="right")) - 1
        self.advance(int(cum[s]), int(cum[s + 1] - cum[s]))
        return s

    def decode_front(self, cums: np.ndarray) -> np.ndarray:
        """Decode one symbol per row of `cums` ((n, L+1) cumulative tables,
        one fresh adaptive table per symbol) — the wavefront hot path. One
        native call instead of n peek/advance round trips."""
        cums = np.ascontiguousarray(cums, dtype=np.uint32)
        n = cums.shape[0]
        if self._lib is not None:
            out = np.empty(n, dtype=np.int32)
            _count("decode_front")
            self._lib.rans_decode_front(
                self._handle,
                cums.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
                n, cums.shape[1] - 1, self.scale_bits,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
            return out
        return np.array([self.decode_symbol(cums[i]) for i in range(n)],
                        dtype=np.int32)

    def decode_static(self, cum: np.ndarray, n: int) -> np.ndarray:
        """Decode n symbols sharing one cumulative table (bulk path)."""
        cum = np.ascontiguousarray(cum, dtype=np.uint32)
        if self._lib is not None:
            out = np.empty(n, dtype=np.int32)
            _count("decode_static")
            self._lib.rans_decode_static(
                self._handle,
                cum.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
                len(cum) - 1, n, self.scale_bits,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
            return out
        return np.array([self.decode_symbol(cum) for _ in range(n)],
                        dtype=np.int32)

    def close(self) -> None:
        if self._lib is not None and self._handle:
            self._lib.rans_decoder_free(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def decode_front_batch(decoders: Sequence[Decoder],
                       cums_list: Sequence[np.ndarray]) -> List[np.ndarray]:
    """Advance N independent decoders one wavefront each in ONE native
    call. `cums_list[i]` is decoder i's (k_i, L+1) adaptive cumulative
    tables (its next k_i symbols); lanes may be ragged and k_i = 0 is
    legal (that decoder advances nothing). Per-lane results are
    identical to N separate `decode_front` calls — lanes share no coder
    state; batching only moves the lane loop into C so a micro-batch's
    front costs one GIL-dropping ctypes call instead of N. Falls back to
    the per-decoder path when the native library is unavailable."""
    if len(decoders) != len(cums_list):
        raise ValueError(f"{len(decoders)} decoders vs {len(cums_list)} "
                         f"cum-table lanes")
    if not decoders:
        return []
    cums = [np.ascontiguousarray(c, dtype=np.uint32) for c in cums_list]
    widths = {c.shape[1] for c in cums if len(c)}
    if len(widths) > 1:
        raise ValueError(f"lanes disagree on table width: {sorted(widths)}")
    scale_bits = decoders[0].scale_bits
    if any(d.scale_bits != scale_bits for d in decoders):
        raise ValueError("decoders disagree on scale_bits")
    if any(d._lib is None for d in decoders) or not widths:
        return [d.decode_front(c) for d, c in zip(decoders, cums)]
    lib = decoders[0]._lib
    num_syms = next(iter(widths)) - 1
    offsets = np.zeros(len(cums) + 1, dtype=np.int64)
    np.cumsum([len(c) for c in cums], out=offsets[1:])
    packed = np.concatenate(
        [c for c in cums if len(c)], axis=0) if offsets[-1] else \
        np.zeros((0, num_syms + 1), np.uint32)
    packed = np.ascontiguousarray(packed)
    handles = (ctypes.c_void_p * len(decoders))(
        *[d._handle for d in decoders])
    out = np.empty(int(offsets[-1]), dtype=np.int32)
    i64p = ctypes.POINTER(ctypes.c_long)
    _count("decode_batch")
    lib.rans_decode_batch(
        handles, packed.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        offsets.ctypes.data_as(i64p), len(decoders), num_syms, scale_bits,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    return [out[offsets[i]:offsets[i + 1]] for i in range(len(decoders))]


# -- pmf quantization ---------------------------------------------------------

def quantize_pmf(pmf: np.ndarray,
                 scale_bits: int = DEFAULT_SCALE_BITS) -> np.ndarray:
    """Deterministically quantize a float PMF to integer frequencies summing
    to 1<<scale_bits, every entry >= 1 (so any symbol stays decodable —
    the reference's hooks had the same all-nonzero requirement via
    +1 smoothing, reference probclass_imgcomp.py:470-476)."""
    total = 1 << scale_bits
    pmf = np.asarray(pmf, dtype=np.float64)
    pmf = np.maximum(pmf, 0.0)
    norm = pmf.sum()
    if not np.isfinite(norm) or norm <= 0:
        pmf = np.ones_like(pmf)
        norm = pmf.sum()
    freqs = np.floor(pmf / norm * total).astype(np.int64)
    freqs = np.maximum(freqs, 1)
    # deterministic fix-up of the rounding drift: push the difference onto
    # the largest bins (ties -> lowest index via argmax), never below 1
    diff = total - int(freqs.sum())
    while diff != 0:
        if diff > 0:
            freqs[int(np.argmax(freqs))] += diff
            diff = 0
        else:
            i = int(np.argmax(freqs))
            take = min(-diff, int(freqs[i]) - 1)
            if take == 0:
                raise ValueError("cannot satisfy min-frequency constraint")
            freqs[i] -= take
            diff += take
    return freqs.astype(np.uint32)


def quantize_pmf_batch(pmfs: np.ndarray,
                       scale_bits: int = DEFAULT_SCALE_BITS) -> np.ndarray:
    """Row-wise `quantize_pmf` over (B, L) PMFs, bit-identical results.

    The common path (floor + clamp, positive drift onto the argmax bin —
    a single step in the scalar routine too) is fully vectorized; rows
    needing the rare negative-drift loop fall back to the scalar function.
    """
    total = 1 << scale_bits
    pmfs = np.asarray(pmfs, dtype=np.float64)
    pmfs = np.maximum(pmfs, 0.0)
    norm = pmfs.sum(axis=1, keepdims=True)
    bad = ~np.isfinite(norm[:, 0]) | (norm[:, 0] <= 0)
    if bad.any():
        pmfs = pmfs.copy()
        pmfs[bad] = 1.0
        norm = pmfs.sum(axis=1, keepdims=True)
    freqs = np.floor(pmfs / norm * total).astype(np.int64)
    freqs = np.maximum(freqs, 1)
    diff = total - freqs.sum(axis=1)
    pos = diff > 0
    if pos.any():
        rows = np.flatnonzero(pos)
        freqs[rows, np.argmax(freqs[rows], axis=1)] += diff[rows]
    for r in np.flatnonzero(diff < 0):
        freqs[r] = quantize_pmf(pmfs[r], scale_bits)
    return freqs.astype(np.uint32)


def cum_from_freqs_batch(freqs: np.ndarray) -> np.ndarray:
    """Row-wise `cum_from_freqs`: (B, L) -> (B, L+1) uint32."""
    freqs = np.asarray(freqs, dtype=np.uint64)
    out = np.zeros((freqs.shape[0], freqs.shape[1] + 1), dtype=np.uint64)
    np.cumsum(freqs, axis=1, out=out[:, 1:])
    return out.astype(np.uint32)


def cum_from_freqs(freqs: np.ndarray) -> np.ndarray:
    """Cumulative table (L+1,) from frequencies (L,)."""
    cum = np.zeros(len(freqs) + 1, dtype=np.uint32)
    np.cumsum(freqs, out=cum[1:])
    return cum
