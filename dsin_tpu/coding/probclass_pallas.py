"""Pallas TPU kernel: one fused wavefront-front of the probclass model.

The jit wavefront engine (codec.py `_wavefront_pass`) dispatches the
whole 4-layer masked-conv stack to XLA once per diagonal front — at the
reference bottleneck (32, 40, 120) that is ~1.5k executable launches per
volume, each doing four tiny convs over a (B, 5, 9, 9) context batch.
This kernel fuses the entire per-front network into ONE Pallas call:
all four masked convolutions, both relus, and the residual skip run
over VMEM-resident activations, so per front the device sees a single
launch and HBM sees only the context blocks in and the logits out.

Layout / schedule:
  * grid = (batch_tiles,): each step loads a (TB, cd, cs, cs) tile of
    bucket-padded context blocks plus the (pre-masked) weight matrices,
    and writes a (TB, L) logits tile.
  * Every conv is a static tap loop (taps = (K//2+1)*K*K, 18 at K=3):
    tap t contributes `slice(act) @ W[t*Cin:(t+1)*Cin]` with all slice
    bounds static — no dynamic indexing anywhere, so the whole body is
    straight-line MXU work.
  * Weights arrive pre-masked in the SAME (taps*Cin, Cout) row-major
    matrices the numpy incremental engine builds
    (coding/incremental.py `IncrementalResShallow.__init__`), so the
    three engines share one weight-preparation convention.
  * Everything is float32 with `preferred_element_type=jnp.float32`:
    this kernel sits on the entropy-critical path (its logits become
    rANS frequency tables), which the precision ladder pins to
    frozen-point-exact fp32 at every rung (coding/precision.py).

Stream-format note: the kernel's logits differ from the XLA batch
engine's in the last ulp (different reduction order), so a stream whose
PMFs came from this kernel is NOT interchangeable with the other
engines' — codec.py gives it its own header mode byte
(`MODE_WAVEFRONT_PL`), exactly like the numpy engine got mode 2.

CPU CI runs this kernel in interpret mode (tests fuzz it against the
XLA reference); real-Mosaic timing is a `tools/tpu_checks.py` campaign
row (`probclass_front`), where any TPU-only layout issue would surface.
"""

from __future__ import annotations

import functools
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from dsin_tpu.models import probclass as pc_lib
from dsin_tpu.utils.jax_compat import pl, pltpu, require_pallas

_MAX_TILE = 128     # batch rows per grid step


def _next_pow2(n: int) -> int:
    return 1 << (int(n) - 1).bit_length() if n > 1 else 1


def _conv_taps(act, w_full, b_full, fshape):
    """VALID masked conv as a static tap loop: act (TB, D, H, W, Cin),
    w_full (taps*Cin, Cout) in (td, th, tw) row-major tap order."""
    tb, d, h, w, cin = act.shape
    fd, fh, fw = fshape
    do, ho, wo = d - fd + 1, h - fh + 1, w - fw + 1
    cout = w_full.shape[1]
    acc = jnp.zeros((tb * do * ho * wo, cout), dtype=jnp.float32)
    tap = 0
    for td in range(fd):
        for th in range(fh):
            for tw in range(fw):
                sl = act[:, td:td + do, th:th + ho, tw:tw + wo, :]
                acc = acc + jnp.dot(
                    sl.reshape(tb * do * ho * wo, cin),
                    w_full[tap * cin:(tap + 1) * cin, :],
                    preferred_element_type=jnp.float32)
                tap += 1
    return (acc + b_full[0]).reshape(tb, do, ho, wo, cout)


def _front_kernel(x_ref, w0_ref, b0_ref, w1_ref, b1_ref, w2_ref, b2_ref,
                  w3_ref, b3_ref, out_ref, *, ks: int):
    fs = pc_lib.filter_shape(ks)
    act0 = x_ref[...][..., None]                     # (TB, cd, cs, cs, 1)
    act1 = jnp.maximum(_conv_taps(act0, w0_ref[...], b0_ref[...], fs), 0.0)
    r1 = jnp.maximum(_conv_taps(act1, w1_ref[...], b1_ref[...], fs), 0.0)
    dd, hw = 2 * (ks // 2), ks - 1
    act3 = (_conv_taps(r1, w2_ref[...], b2_ref[...], fs)
            + act1[:, dd:, hw:-hw, hw:-hw, :])
    logits = jnp.maximum(_conv_taps(act3, w3_ref[...], b3_ref[...], fs),
                         0.0)                        # (TB, 1, 1, 1, L)
    out_ref[...] = logits.reshape(out_ref.shape)


@partial(jax.jit, static_argnames=("interpret",))
def probclass_front_logits(blocks, w0, b0, w1, b1, w2, b2, w3, b3, *,
                           interpret: bool = False):
    """(B, cd, cs, cs) f32 context blocks -> (B, L) f32 logits, one
    fused Pallas call (batch-tiled). Weights are the pre-masked
    (taps*Cin, Cout) matrices; biases are (1, Cout). B is padded to a
    tile multiple internally (zero blocks — same deterministic padding
    the wavefront driver uses) and the pad rows are sliced back off."""
    require_pallas()
    b, cd, cs, _ = blocks.shape
    ks = (cs - 1) // 4 + 1
    assert (cd, cs, cs) == pc_lib.context_shape(ks), (blocks.shape, ks)
    l_out = w3.shape[1]

    tile = min(_MAX_TILE, _next_pow2(b))
    bp = -(-b // tile) * tile
    blocks = jnp.pad(blocks, ((0, bp - b), (0, 0), (0, 0), (0, 0)))

    kernel = partial(_front_kernel, ks=ks)
    full = lambda arr: pl.BlockSpec(arr.shape, lambda i: (0,) * arr.ndim,
                                    memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        kernel,
        grid=(bp // tile,),
        in_specs=[
            pl.BlockSpec((tile, cd, cs, cs), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            full(w0), full(b0), full(w1), full(b1),
            full(w2), full(b2), full(w3), full(b3),
        ],
        out_specs=pl.BlockSpec((tile, l_out), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((bp, l_out), jnp.float32),
        interpret=interpret,
    )(blocks, w0, b0, w1, b1, w2, b2, w3, b3)
    return out[:b]


class ProbclassFrontKernel:
    """Weight-holding wrapper the codec's Pallas engine mode uses.

    Builds the pre-masked weight matrices ONCE (identical convention to
    `IncrementalResShallow`) and exposes `front_logits` with the jit
    boundary taking params as traced ARGUMENTS (functools.partial over a
    module-level jit, the codec.py idiom) — never closure captures.
    Read-only after construction, so one instance may be shared across
    codec thread clones."""

    def __init__(self, pc_params, pc_config, *, interpret: bool = False):
        self.ks = int(pc_config.kernel_size)
        masks = [pc_lib.make_mask(self.ks, include_center=bool(i))
                 for i in (0, 1, 1, 1)]
        names = sorted(pc_params.keys())     # _MaskedConv3D_0 .. _3
        assert len(names) == 4, names
        flat = []
        for name, mask in zip(names, masks):
            kern = np.asarray(pc_params[name]["kernel"], dtype=np.float32)
            kern = kern * mask[..., None, None]
            taps = mask.size
            flat.append(jnp.asarray(
                kern.reshape(taps * kern.shape[3], kern.shape[4])))
            flat.append(jnp.asarray(
                np.asarray(pc_params[name]["bias"],
                           dtype=np.float32)[None, :]))
        self.interpret = bool(interpret)
        self._fn = functools.partial(probclass_front_logits,
                                     interpret=self.interpret)
        self._weights = tuple(flat)

    def front_logits(self, blocks) -> jnp.ndarray:
        """(B, cd, cs, cs) -> (B, L) f32 logits (device array)."""
        return self._fn(jnp.asarray(blocks, dtype=jnp.float32),
                        *self._weights)
