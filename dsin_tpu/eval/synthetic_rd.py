"""End-to-end 3-phase RD evidence on the synthetic stereo corpus.

Drives the reference's full workflow (reference AE.py:158-175 +
main.py:101-126) with no real dataset required:

  phase 1  train AE_only                         -> best-val checkpoint
  (test)   AE-only inference on the test split   -> RD point without SI
  phase 2  warm-start AE weights, train +siNet   -> best-val checkpoint
  (test)   full-SI inference on the test split   -> RD point with SI

and writes `rd_synthetic.json` holding both points (bpp / PSNR / MS-SSIM
means) plus run metadata. The side-information value proposition is the
delta between the two points at (nearly) the same bpp.

Usage:
    python -m dsin_tpu.eval.synthetic_rd --out_root /tmp/rd_run \
        [--data_dir /tmp/synth] [--phase1_steps N] [--phase2_steps N]
"""

from __future__ import annotations

import argparse
import json
import os
import time
from typing import Optional

from dsin_tpu.config import parse_config_file
from dsin_tpu.utils import color_print


def _latest_resumable(out_root: str, ae_config, ae_only: bool):
    """Newest prior attempt of this phase (same target + mode) holding the
    highest-step restorable checkpoint under out_root/weights. Returns
    (name relative to the weights root — possibly '<dir>/periodic' or
    '<dir>/emergency' — , step), or (None, 0).

    This is what makes a multi-hour RD run retryable on a flaky chip
    relay: a killed attempt leaves best-val / periodic / emergency
    checkpoints behind, and the retry continues from the furthest one
    instead of repeating hours of training.
    """
    from dsin_tpu.train import checkpoint as ckpt_lib

    weights = os.path.join(out_root, "weights")
    # derive the prefix from the one naming authority (an empty timestamp
    # yields exactly the 'target_bpp<x>_<mode>_' prefix) so a format change
    # there cannot silently break resume discovery here
    prefix = ckpt_lib.model_name_for(
        ae_config.replace(AE_only=ae_only), "")
    best_name, best_step = None, 0
    if not os.path.isdir(weights):
        return None, 0
    for d in sorted(os.listdir(weights)):
        if not d.startswith(prefix):
            continue
        for sub in ("", "periodic", "emergency"):
            cand = os.path.join(weights, d, sub) if sub else \
                os.path.join(weights, d)
            # a save SIGKILLed between its swap renames leaves only a
            # rotated `.prev-*` sibling — still a resumable checkpoint
            # (train/checkpoint.py latest_checkpoint)
            name = os.path.join(d, sub) if sub else d
            if not os.path.exists(os.path.join(cand, "meta.json")):
                resolved = ckpt_lib.latest_checkpoint(cand)
                if resolved is None:
                    continue
                cand, name = resolved, os.path.relpath(resolved, weights)
            try:
                step = int(ckpt_lib.load_meta(cand)["step"])
            except (OSError, KeyError, ValueError, json.JSONDecodeError):
                continue
            if step > best_step:
                best_name = name
                best_step = step
    return best_name, best_step


def _prior_best_dir(out_root: str, prior: Optional[str]):
    """Candidate for Experiment.restore_best_for_test on a RESUMED phase:
    the prior attempt's best-val dir. `prior` is what _latest_resumable
    returned — possibly '<dir>/periodic' or '<dir>/emergency', whose
    parent holds the prior best-val checkpoint (untouched by the new
    attempt, which writes under its own timestamped name)."""
    if not prior:
        return ()
    root = prior
    for sub in ("periodic", "emergency"):
        if root.endswith("/" + sub):
            root = root[: -len(sub) - 1]
    return (os.path.join(out_root, "weights", root),)


def run_3phase(ae_config, pc_config, out_root: str,
               phase1_steps=None, phase2_steps=None,
               max_test_images=None, phase1_until_target=False,
               rate_window=200) -> dict:
    """Both phases are retry-safe: a completed phase 1 leaves a
    `phase1_done.json` marker in out_root and is skipped wholesale on
    retry; an interrupted phase warm-resumes from the furthest checkpoint
    a prior attempt left behind (`_latest_resumable`), with the phase's
    TOTAL step budget preserved. Phase 2 has no marker — its completion is
    the final `rd_synthetic.json`; a retry after a crash in the closing
    test re-resumes phase 2 (min 1 step) and re-tests. Periodic
    checkpoints (default every 2000 steps unless the config says
    otherwise, including an explicit "off") bound the re-done work."""
    from dsin_tpu.main import Experiment
    from dsin_tpu.train import checkpoint as ckpt_lib

    t0 = time.time()
    os.makedirs(out_root, exist_ok=True)
    results = {"config": os.path.basename(
                   str(getattr(ae_config, "_name", "config"))),
               "crop": list(ae_config.crop_size),
               "eval_crop": list(ae_config.get("eval_crop_size",
                                               ae_config.crop_size)),
               "H_target": ae_config.H_target,
               "target_bpp": ae_config.H_target /
               (64.0 / ae_config.num_chan_bn)}
    # default only the truly-unset case: an explicit 0/None means the
    # config deliberately disabled periodic checkpoints
    ckpt_every = (ae_config.get("checkpoint_every")
                  if "checkpoint_every" in ae_config else 2000)

    # -- phase 1: AE_only ---------------------------------------------------
    marker1 = os.path.join(out_root, "phase1_done.json")
    if os.path.exists(marker1):
        with open(marker1) as f:
            done = json.load(f)
        results["phase1"] = done["phase1"]
        results["ae_only_test"] = done["ae_only_test"]
        phase1_name = done["phase1"]["model_name"]
        color_print(f"phase 1 already complete ({phase1_name}); skipping",
                    "green")
    else:
        prior, prior_step = _latest_resumable(out_root, ae_config,
                                              ae_only=True)
        if prior:
            color_print(f"phase 1 resumes from {prior} (step {prior_step})",
                        "yellow")
        cfg1 = ae_config.replace(AE_only=True, load_model=prior is not None,
                                 load_model_name=prior or "",
                                 load_train_step=prior is not None,
                                 train_model=True, test_model=False,
                                 checkpoint_every=ckpt_every)
        exp1 = Experiment(cfg1, pc_config, out_root=out_root)
        exp1.maybe_restore()
        color_print(f"phase 1 (AE_only) -> {exp1.model_name}", "cyan",
                    bold=True)
        # max_steps counts steps to RUN from the restored position — keep
        # the phase's TOTAL budget by deducting already-done work (min 1:
        # 0 would mean "uncapped", and the closing validate must still run)
        steps1 = (max(phase1_steps - prior_step, 1)
                  if prior and phase1_steps else phase1_steps)
        r1 = exp1.train(max_steps=steps1,
                        until_rate_target=phase1_until_target,
                        rate_window=rate_window)
        # a RESUMED phase 1 may never beat the restored best_val in its
        # short tail, in which case no checkpoint was written under the
        # NEW model_name — and phase 2 (plus the done-marker) point there.
        # Guarantee the dir holds the final trained state.
        if not os.path.exists(os.path.join(exp1.ckpt_dir, "meta.json")):
            ckpt_lib.save_checkpoint(exp1.ckpt_dir, exp1.state,
                                     extra_meta={"kind": "phase1_final"},
                                     manifest_extra=exp1._manifest_extra())
        best1 = exp1.restore_best_for_test(
            extra_candidates=_prior_best_dir(out_root, prior))
        t1 = exp1.test(max_images=max_test_images, save_images=True)
        # phase 2 (and the done-marker) must point at the checkpoint the
        # test just SCORED: on a resumed phase 1 that never beat the prior
        # attempt's best_val, that is the prior attempt's dir — while
        # exp1.model_name's dir holds only the last-iterate phase1_final
        # weights, and warm-starting phase 2 from those would silently
        # build on weights worse than the reported phase-1 quality.
        phase1_name = (os.path.relpath(best1, exp1.weights_root)
                       if best1 else exp1.model_name)
        results["phase1"] = {"model_name": phase1_name, **r1}
        results["ae_only_test"] = t1
        with open(marker1, "w") as f:
            json.dump({"phase1": results["phase1"],
                       "ae_only_test": t1}, f, indent=2)

    # -- phase 2: warm-start AE, fresh siNet --------------------------------
    # (resume-of-phase-2 restores siNet + optimizer from the prior attempt;
    # a fresh phase 2 partial-restores only the AE partitions from phase 1)
    prior2, prior2_step = _latest_resumable(out_root, ae_config,
                                            ae_only=False)
    if prior2:
        color_print(f"phase 2 resumes from {prior2} (step {prior2_step})",
                    "yellow")
    # Phase-scoped divergence guard: phase 2's validation profile is
    # tighter than phase 1's (measured: healthy +siNet runs oscillate to
    # <=1.41x best with no two consecutive >1.3x — rd_pipe_bpp0.06/0.12
    # logs — while the diverging 0.04 phase 2 put 39.0/24.2 = 1.61x TWO
    # validations running at steps 875/1000 on its way to 2.06x). 1.3/2
    # stops that case ~500 steps early; phase 1 keeps train()'s looser
    # 1.5/3 default, which its larger rate-hinge noise needs (a 1.3/2
    # guard would have false-stopped the healthy 0.04 phase 1 at step
    # 11000, before its 12,522-step rate-target bind). Explicit config
    # values still win.
    cfg2 = ae_config.replace(AE_only=False, load_model=True,
                             load_model_name=prior2 or phase1_name,
                             load_train_step=prior2 is not None,
                             train_model=True, test_model=False,
                             checkpoint_every=ckpt_every,
                             divergence_factor=ae_config.get(
                                 "divergence_factor", 1.3),
                             divergence_patience=ae_config.get(
                                 "divergence_patience", 2))
    exp2 = Experiment(cfg2, pc_config, out_root=out_root)
    exp2.maybe_restore()
    color_print(f"phase 2 (+siNet) -> {exp2.model_name}", "cyan", bold=True)
    steps2 = (max(phase2_steps - prior2_step, 1)
              if prior2 and phase2_steps else phase2_steps)
    r2 = exp2.train(max_steps=steps2)
    # same two guarantees as phase 1: the new model_name dir always holds
    # SOMETHING restorable (a resumed tail that never improves saves no
    # checkpoint there otherwise), and the recorded name points at the
    # checkpoint the closing test actually scored
    if not os.path.exists(os.path.join(exp2.ckpt_dir, "meta.json")):
        ckpt_lib.save_checkpoint(exp2.ckpt_dir, exp2.state,
                                 extra_meta={"kind": "phase2_final"},
                                 manifest_extra=exp2._manifest_extra())
    best2 = exp2.restore_best_for_test(
        extra_candidates=_prior_best_dir(out_root, prior2))
    t2 = exp2.test(max_images=max_test_images, save_images=True,
                   real_bpp=True)
    phase2_name = (os.path.relpath(best2, exp2.weights_root)
                   if best2 else exp2.model_name)
    results["phase2"] = {"model_name": phase2_name, **r2}
    results["with_si_test"] = t2
    results["wall_clock_s"] = round(time.time() - t0, 1)

    out_path = os.path.join(out_root, "rd_synthetic.json")
    with open(out_path, "w") as f:
        json.dump(results, f, indent=2)
    color_print(f"3-phase RD evidence written to {out_path}", "green",
                bold=True)
    return results


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="synthetic 3-phase RD run")
    base = os.path.join(os.path.dirname(__file__), os.pardir, "configs")
    p.add_argument("-ae_config",
                   default=os.path.join(base, "ae_synthetic_stereo"))
    p.add_argument("-pc_config", default=os.path.join(base, "pc_default"))
    p.add_argument("--out_root", required=True)
    p.add_argument("--data_dir", default=None,
                   help="synthetic corpus dir (generated if missing)")
    p.add_argument("--phase1_steps", type=int, default=None)
    p.add_argument("--phase2_steps", type=int, default=None)
    p.add_argument("--phase1_until_target", action="store_true",
                   help="stop phase 1 as soon as mean H_soft over "
                        "--rate_window steps reaches H_target (the rate "
                        "constraint binds) instead of guessing a step "
                        "budget; --phase1_steps/iterations still cap it")
    p.add_argument("--rate_window", type=int, default=200)
    p.add_argument("--max_test_images", type=int, default=None)
    p.add_argument("--H_target", type=float, default=None,
                   help="override the config's rate target (bits per "
                        "bottleneck voxel); target_bpp = H_target / "
                        "(64 / num_chan_bn) — one RD-curve point per value")
    p.add_argument("--target_bpp", type=float, default=None,
                   help="rate target in bits per pixel; converted to "
                        "H_target via the config's num_chan_bn (no "
                        "hardcoded factor). Mutually exclusive with "
                        "--H_target")
    p.add_argument("--iterations", type=int, default=None,
                   help="override the config's iterations cap — without "
                        "this, --phase*_steps beyond the config's "
                        "`iterations` are silently clamped "
                        "(Experiment.train caps at cfg.iterations)")
    args = p.parse_args(argv)

    ae_config = parse_config_file(args.ae_config)
    pc_config = parse_config_file(args.pc_config)
    if args.H_target is not None and args.target_bpp is not None:
        p.error("--H_target and --target_bpp are mutually exclusive")
    if args.H_target is not None:
        ae_config = ae_config.replace(H_target=args.H_target)
    if args.target_bpp is not None:
        from dsin_tpu.eval.rd_sweep import h_target_for_bpp
        ae_config = ae_config.replace(H_target=h_target_for_bpp(
            args.target_bpp, ae_config.num_chan_bn))
    if args.iterations is not None:
        ae_config = ae_config.replace(iterations=args.iterations)
    if args.data_dir:
        ae_config = ae_config.replace(root_data=args.data_dir)

    manifest = os.path.join(ae_config.root_data,
                            ae_config.file_path_train)
    synth_manifest = os.path.join(ae_config.root_data,
                                  "synthetic_stereo_train.txt")
    if not os.path.exists(manifest) and os.path.exists(synth_manifest):
        # a synthetic corpus already lives here — rewire instead of
        # regenerating 40 full-size PNGs per invocation
        ae_config = ae_config.replace(
            **{f"file_path_{split}": f"synthetic_stereo_{split}.txt"
               for split in ("train", "val", "test")})
        manifest = synth_manifest
    if not os.path.exists(manifest):
        from dsin_tpu.data.synthetic import write_corpus
        eh, ew = ae_config.get("eval_crop_size", ae_config.crop_size)
        color_print(f"generating synthetic corpus in {ae_config.root_data}",
                    "yellow")
        manifests = write_corpus(ae_config.root_data, num_train=40,
                                 num_val=8, num_test=8, height=eh, width=ew)
        # point the config at the manifests actually generated — a config
        # naming KITTI manifests (e.g. ae_kitti_stereo at the reference
        # geometry) would otherwise FileNotFoundError after generating a
        # corpus it then ignores
        ae_config = ae_config.replace(
            **{f"file_path_{split}": os.path.basename(path)
               for split, path in manifests.items()})

    os.makedirs(args.out_root, exist_ok=True)
    run_3phase(ae_config, pc_config, args.out_root,
               phase1_steps=args.phase1_steps,
               phase2_steps=args.phase2_steps,
               max_test_images=args.max_test_images,
               phase1_until_target=args.phase1_until_target,
               rate_window=args.rate_window)


if __name__ == "__main__":
    main()
