"""End-to-end 3-phase RD evidence on the synthetic stereo corpus.

Drives the reference's full workflow (reference AE.py:158-175 +
main.py:101-126) with no real dataset required:

  phase 1  train AE_only                         -> best-val checkpoint
  (test)   AE-only inference on the test split   -> RD point without SI
  phase 2  warm-start AE weights, train +siNet   -> best-val checkpoint
  (test)   full-SI inference on the test split   -> RD point with SI

and writes `rd_synthetic.json` holding both points (bpp / PSNR / MS-SSIM
means) plus run metadata. The side-information value proposition is the
delta between the two points at (nearly) the same bpp.

Usage:
    python -m dsin_tpu.eval.synthetic_rd --out_root /tmp/rd_run \
        [--data_dir /tmp/synth] [--phase1_steps N] [--phase2_steps N]
"""

from __future__ import annotations

import argparse
import json
import os
import time

from dsin_tpu.config import parse_config_file
from dsin_tpu.utils import color_print


def run_3phase(ae_config, pc_config, out_root: str,
               phase1_steps=None, phase2_steps=None,
               max_test_images=None, phase1_until_target=False,
               rate_window=200) -> dict:
    from dsin_tpu.main import Experiment

    t0 = time.time()
    results = {"config": os.path.basename(
                   str(getattr(ae_config, "_name", "config"))),
               "crop": list(ae_config.crop_size),
               "eval_crop": list(ae_config.get("eval_crop_size",
                                               ae_config.crop_size)),
               "H_target": ae_config.H_target,
               "target_bpp": ae_config.H_target /
               (64.0 / ae_config.num_chan_bn)}

    # -- phase 1: AE_only ---------------------------------------------------
    cfg1 = ae_config.replace(AE_only=True, load_model=False,
                             train_model=True, test_model=False)
    exp1 = Experiment(cfg1, pc_config, out_root=out_root)
    exp1.maybe_restore()
    color_print(f"phase 1 (AE_only) -> {exp1.model_name}", "cyan", bold=True)
    r1 = exp1.train(max_steps=phase1_steps,
                    until_rate_target=phase1_until_target,
                    rate_window=rate_window)
    t1 = exp1.test(max_images=max_test_images, save_images=True)
    results["phase1"] = {"model_name": exp1.model_name, **r1}
    results["ae_only_test"] = t1

    # -- phase 2: warm-start AE, fresh siNet --------------------------------
    cfg2 = ae_config.replace(AE_only=False, load_model=True,
                             load_model_name=exp1.model_name,
                             load_train_step=False,
                             train_model=True, test_model=False)
    exp2 = Experiment(cfg2, pc_config, out_root=out_root)
    exp2.maybe_restore()
    color_print(f"phase 2 (+siNet) -> {exp2.model_name}", "cyan", bold=True)
    r2 = exp2.train(max_steps=phase2_steps)
    t2 = exp2.test(max_images=max_test_images, save_images=True,
                   real_bpp=True)
    results["phase2"] = {"model_name": exp2.model_name, **r2}
    results["with_si_test"] = t2
    results["wall_clock_s"] = round(time.time() - t0, 1)

    out_path = os.path.join(out_root, "rd_synthetic.json")
    with open(out_path, "w") as f:
        json.dump(results, f, indent=2)
    color_print(f"3-phase RD evidence written to {out_path}", "green",
                bold=True)
    return results


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="synthetic 3-phase RD run")
    base = os.path.join(os.path.dirname(__file__), os.pardir, "configs")
    p.add_argument("-ae_config",
                   default=os.path.join(base, "ae_synthetic_stereo"))
    p.add_argument("-pc_config", default=os.path.join(base, "pc_default"))
    p.add_argument("--out_root", required=True)
    p.add_argument("--data_dir", default=None,
                   help="synthetic corpus dir (generated if missing)")
    p.add_argument("--phase1_steps", type=int, default=None)
    p.add_argument("--phase2_steps", type=int, default=None)
    p.add_argument("--phase1_until_target", action="store_true",
                   help="stop phase 1 as soon as mean H_soft over "
                        "--rate_window steps reaches H_target (the rate "
                        "constraint binds) instead of guessing a step "
                        "budget; --phase1_steps/iterations still cap it")
    p.add_argument("--rate_window", type=int, default=200)
    p.add_argument("--max_test_images", type=int, default=None)
    p.add_argument("--H_target", type=float, default=None,
                   help="override the config's rate target (bits per "
                        "bottleneck voxel); target_bpp = H_target / "
                        "(64 / num_chan_bn) — one RD-curve point per value")
    p.add_argument("--target_bpp", type=float, default=None,
                   help="rate target in bits per pixel; converted to "
                        "H_target via the config's num_chan_bn (no "
                        "hardcoded factor). Mutually exclusive with "
                        "--H_target")
    p.add_argument("--iterations", type=int, default=None,
                   help="override the config's iterations cap — without "
                        "this, --phase*_steps beyond the config's "
                        "`iterations` are silently clamped "
                        "(Experiment.train caps at cfg.iterations)")
    args = p.parse_args(argv)

    ae_config = parse_config_file(args.ae_config)
    pc_config = parse_config_file(args.pc_config)
    if args.H_target is not None and args.target_bpp is not None:
        p.error("--H_target and --target_bpp are mutually exclusive")
    if args.H_target is not None:
        ae_config = ae_config.replace(H_target=args.H_target)
    if args.target_bpp is not None:
        from dsin_tpu.eval.rd_sweep import h_target_for_bpp
        ae_config = ae_config.replace(H_target=h_target_for_bpp(
            args.target_bpp, ae_config.num_chan_bn))
    if args.iterations is not None:
        ae_config = ae_config.replace(iterations=args.iterations)
    if args.data_dir:
        ae_config = ae_config.replace(root_data=args.data_dir)

    manifest = os.path.join(ae_config.root_data,
                            ae_config.file_path_train)
    synth_manifest = os.path.join(ae_config.root_data,
                                  "synthetic_stereo_train.txt")
    if not os.path.exists(manifest) and os.path.exists(synth_manifest):
        # a synthetic corpus already lives here — rewire instead of
        # regenerating 40 full-size PNGs per invocation
        ae_config = ae_config.replace(
            **{f"file_path_{split}": f"synthetic_stereo_{split}.txt"
               for split in ("train", "val", "test")})
        manifest = synth_manifest
    if not os.path.exists(manifest):
        from dsin_tpu.data.synthetic import write_corpus
        eh, ew = ae_config.get("eval_crop_size", ae_config.crop_size)
        color_print(f"generating synthetic corpus in {ae_config.root_data}",
                    "yellow")
        manifests = write_corpus(ae_config.root_data, num_train=40,
                                 num_val=8, num_test=8, height=eh, width=ew)
        # point the config at the manifests actually generated — a config
        # naming KITTI manifests (e.g. ae_kitti_stereo at the reference
        # geometry) would otherwise FileNotFoundError after generating a
        # corpus it then ignores
        ae_config = ae_config.replace(
            **{f"file_path_{split}": os.path.basename(path)
               for split, path in manifests.items()})

    os.makedirs(args.out_root, exist_ok=True)
    run_3phase(ae_config, pc_config, args.out_root,
               phase1_steps=args.phase1_steps,
               phase2_steps=args.phase2_steps,
               max_test_images=args.max_test_images,
               phase1_until_target=args.phase1_until_target,
               rate_window=args.rate_window)


if __name__ == "__main__":
    main()
