"""Numpy MS-SSIM: the host-side eval oracle.

The reference keeps a second, independent MS-SSIM implementation in
numpy/scipy for test-time reporting (reference ms_ssim_np_imgcomp.py,
used by utils.py:94-99) so graph and eval scores can cross-check each
other. This module plays the same role for the JAX implementation
(`dsin_tpu.ops.msssim`): written directly from the Wang et al. 2003 spec,
sharing no code with the device path.

Spec: 5 scales, weights [0.0448, 0.2856, 0.3001, 0.2363, 0.1333]; per scale
SSIM/contrast means from an 11x11 sigma-1.5 Gaussian window (VALID
convolution); between scales a 2x2 box blur with reflect boundary then
stride-2 subsampling.
"""

from __future__ import annotations

import numpy as np

_WEIGHTS = np.array([0.0448, 0.2856, 0.3001, 0.2363, 0.1333])


def _gauss_2d(size: int, sigma: float) -> np.ndarray:
    ax = np.arange(size, dtype=np.float64) - (size - 1) / 2.0
    xx, yy = np.meshgrid(ax, ax)
    g = np.exp(-(xx * xx + yy * yy) / (2.0 * sigma * sigma))
    return g / g.sum()


def _ssim_cs(a: np.ndarray, b: np.ndarray, max_val: float,
             filter_size: int, filter_sigma: float,
             k1: float, k2: float):
    """Mean SSIM and mean contrast-structure term for one scale.

    a, b: (N, H, W, C) float64.
    """
    from scipy.signal import fftconvolve

    _, h, w, _ = a.shape
    size = min(filter_size, h, w)
    # shrink sigma proportionally when the image is smaller than the window
    sigma = size * filter_sigma / filter_size if filter_size else 0.0
    win = _gauss_2d(size, sigma).reshape(1, size, size, 1)

    mu_a = fftconvolve(a, win, mode="valid")
    mu_b = fftconvolve(b, win, mode="valid")
    sigma_aa = fftconvolve(a * a, win, mode="valid") - mu_a * mu_a
    sigma_bb = fftconvolve(b * b, win, mode="valid") - mu_b * mu_b
    sigma_ab = fftconvolve(a * b, win, mode="valid") - mu_a * mu_b

    c1 = (k1 * max_val) ** 2
    c2 = (k2 * max_val) ** 2
    v1 = 2.0 * sigma_ab + c2
    v2 = sigma_aa + sigma_bb + c2
    ssim = np.mean(((2.0 * mu_a * mu_b + c1) * v1) /
                   ((mu_a * mu_a + mu_b * mu_b + c1) * v2))
    cs = np.mean(v1 / v2)
    return ssim, cs


def _downsample_2x(x: np.ndarray) -> np.ndarray:
    """2x2 box blur (reflect boundary) + stride-2 subsample."""
    from scipy.ndimage import convolve

    kernel = np.ones((1, 2, 2, 1)) / 4.0
    return convolve(x, kernel, mode="reflect")[:, ::2, ::2, :]


def multiscale_ssim_np(img1: np.ndarray, img2: np.ndarray, *,
                       max_val: float = 255.0, filter_size: int = 11,
                       filter_sigma: float = 1.5, k1: float = 0.01,
                       k2: float = 0.03, levels: int = 5) -> float:
    """MS-SSIM of two image batches.

    img1, img2: (N, H, W, C) or (H, W, C) arrays in [0, max_val].
    Returns a python float in [0, 1] (1 = identical).
    """
    a = np.asarray(img1, dtype=np.float64)
    b = np.asarray(img2, dtype=np.float64)
    if a.ndim == 3:
        a, b = a[None], b[None]
    assert a.shape == b.shape and a.ndim == 4, (a.shape, b.shape)

    mssim = np.empty(levels)
    mcs = np.empty(levels)
    for lvl in range(levels):
        mssim[lvl], mcs[lvl] = _ssim_cs(a, b, max_val, filter_size,
                                        filter_sigma, k1, k2)
        if lvl < levels - 1:
            a, b = _downsample_2x(a), _downsample_2x(b)

    # clamp to >= 0 before the fractional powers (negative mean cs from an
    # anti-correlated scale would give NaN); mirrors the device path
    mcs = np.maximum(mcs, 0.0)
    mssim = np.maximum(mssim, 0.0)
    w = _WEIGHTS[:levels]
    return float(np.prod(mcs[:-1] ** w[:-1]) * (mssim[-1] ** w[-1]))
