"""Host-side evaluation: numpy oracles, score lists, image dumps, plots."""

from dsin_tpu.eval.msssim_np import multiscale_ssim_np
from dsin_tpu.eval.reporting import (ScoreLists, l1_np, mse_np,
                                     pearson_per_patch, psnr_np, save_image,
                                     image_output_path)

__all__ = ["multiscale_ssim_np", "ScoreLists", "l1_np", "mse_np", "psnr_np",
           "pearson_per_patch", "save_image", "image_output_path"]
