"""Rate-distortion sweep: train/evaluate one model per target bitrate.

The reference ships a single operating point (0.02 bpp — reference
ae_run_configs:21, pretrained `KITTI_stereo_target_bpp0.02`) and the paper's
RD curves were produced by re-running training with different `H_target`s.
This runner automates that: for each target bpp it derives
`H_target = bpp * 64 / num_chan_bn` (inverting the reference's back-formula
`bpp = H_target / (64 / C)`, reference main.py:143), runs the full
train+test pipeline, and collects the per-point test means into
`rd_curve.json` — the artifact to plot against the paper's curves.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence

from dsin_tpu.config import Config
from dsin_tpu.utils import color_print

DEFAULT_TARGETS = (0.01, 0.02, 0.04, 0.08)


def h_target_for_bpp(bpp: float, num_chan_bn: int) -> float:
    """Invert reference main.py:143: bpp = H_target / (64 / C)."""
    return bpp * 64.0 / num_chan_bn


def sweep(ae_config: Config, pc_config: Config, out_root: str = ".",
          targets: Sequence[float] = DEFAULT_TARGETS,
          max_steps: Optional[int] = None,
          max_val_batches: Optional[int] = None,
          max_test_images: Optional[int] = None) -> List[Dict[str, float]]:
    """Run the pipeline once per target bpp; returns one result dict per
    point and writes `<out_root>/rd_curve.json`."""
    from dsin_tpu.main import run

    out_path = os.path.join(out_root, "rd_curve.json")
    os.makedirs(out_root or ".", exist_ok=True)
    points = []
    for bpp in targets:
        h_t = h_target_for_bpp(bpp, ae_config.num_chan_bn)
        color_print(f"RD point: target_bpp={bpp} (H_target={h_t})", "cyan",
                    bold=True)
        cfg = ae_config.replace(H_target=h_t)
        results = run(cfg, pc_config, out_root=out_root,
                      max_steps=max_steps, max_val_batches=max_val_batches,
                      max_test_images=max_test_images)
        points.append({"target_bpp": bpp, "H_target": h_t, **results})
        # each point is a full training run — persist incrementally so a
        # late-point crash doesn't discard finished points
        with open(out_path, "w") as f:
            json.dump(points, f, indent=2)

    color_print(f"RD curve written to {out_path}", "green", bold=True)
    return points


def main(argv=None) -> None:
    import argparse

    from dsin_tpu.config import parse_config_file

    p = argparse.ArgumentParser(description="dsin_tpu RD sweep")
    base = os.path.join(os.path.dirname(__file__), os.pardir, "configs")
    p.add_argument("-ae_config", default=os.path.join(base, "ae_kitti_stereo"))
    p.add_argument("-pc_config", default=os.path.join(base, "pc_default"))
    p.add_argument("--out_root", default=".")
    p.add_argument("--targets", type=float, nargs="+",
                   default=list(DEFAULT_TARGETS))
    p.add_argument("--max_steps", type=int, default=None)
    p.add_argument("--max_test_images", type=int, default=None)
    args = p.parse_args(argv)

    sweep(parse_config_file(args.ae_config), parse_config_file(args.pc_config),
          out_root=args.out_root, targets=args.targets,
          max_steps=args.max_steps, max_test_images=args.max_test_images)


if __name__ == "__main__":
    main()
