"""Optional matplotlib figures: loss curves + inference panels.

Capability parity with the reference's plotting helpers
(reference utils.py:12-79: `plot_loss`, `plot_inference`). Matplotlib is
imported lazily with the Agg backend so headless training never needs a
display and the dependency stays optional.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence


def _plt():
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    return plt


def plot_loss(train_losses: Sequence[float], val_losses: Sequence[float],
              val_every: int, out_path: str,
              title: str = "loss") -> None:
    """Train/val loss curves on one axis (reference utils.py:12-32)."""
    plt = _plt()
    fig, ax = plt.subplots(figsize=(8, 5))
    ax.plot(range(len(train_losses)), train_losses, label="train")
    if val_losses:
        xs = [min((i + 1) * val_every, len(train_losses))
              for i in range(len(val_losses))]
        ax.plot(xs, val_losses, label="val", marker="o", markersize=3)
    ax.set_xlabel("iteration")
    ax.set_ylabel("loss")
    ax.set_title(title)
    ax.legend()
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    fig.savefig(out_path, bbox_inches="tight")
    plt.close(fig)


def plot_inference(x, x_dec, x_with_si, y, y_syn, out_path: str,
                   bpp: Optional[float] = None) -> None:
    """5-panel inference figure: x, x̂ (AE), x+SI, y, y_syn
    (reference utils.py:35-79)."""
    import numpy as np
    plt = _plt()
    panels = [("x (input)", x), ("x_dec (AE)", x_dec),
              ("x_with_si (final)", x_with_si), ("y (side info)", y),
              ("y_syn (matched)", y_syn)]
    fig, axes = plt.subplots(len(panels), 1,
                             figsize=(10, 2.2 * len(panels)))
    for ax, (name, img) in zip(axes, panels):
        if img is None:
            ax.axis("off")
            continue
        arr = np.clip(np.asarray(img), 0, 255).astype(np.uint8)
        ax.imshow(arr)
        ax.set_title(name, fontsize=9)
        ax.axis("off")
    if bpp is not None:
        fig.suptitle(f"{bpp:.4f} bpp")
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    fig.savefig(out_path, bbox_inches="tight", dpi=120)
    plt.close(fig)
