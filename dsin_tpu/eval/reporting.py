"""Host-side test reporting: per-image metrics, score lists, image dumps.

Capability parity with the reference's `utils.py` eval helpers:
  * numpy L1 / PSNR / MS-SSIM per test image (reference utils.py:82-99);
  * reconstruction PNG saved as ``<idx>_<bpp>bpp.png`` under the model's
    image directory (reference utils.py:102-111);
  * appended txt score lists — one value per test image — for bpp, L1,
    PSNR, MS-SSIM, plus the x-vs-y_syn MSE and mean per-patch Pearson
    diagnostics (reference utils.py:114-158);
  * ``pearson_per_patch`` (reference utils.py:161-180).

Everything here is pure numpy/PIL on host arrays — it runs after device
compute, off the hot path.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from dsin_tpu.eval.msssim_np import multiscale_ssim_np


def l1_np(x: np.ndarray, x_out: np.ndarray) -> float:
    """Mean absolute error on int-truncated pixels (reference utils.py:82-85)."""
    return float(np.mean(np.abs(x_out.astype(np.int64) -
                                x.astype(np.int64))))


def mse_np(x: np.ndarray, x_out: np.ndarray) -> float:
    return float(np.mean((x_out.astype(np.int64) -
                          x.astype(np.int64)) ** 2.0))


def psnr_np(x: np.ndarray, x_out: np.ndarray) -> float:
    """PSNR in dB, max_val 255, int-truncated (reference utils.py:87-91).
    Identical images give +inf (numpy division semantics)."""
    mse = mse_np(x, x_out)
    if mse == 0.0:
        return float("inf")
    return float(10.0 * np.log10(255.0 ** 2 / mse))


def pearson_per_patch(a: np.ndarray, b: np.ndarray, patch_h: int,
                      patch_w: int) -> np.ndarray:
    """Pearson correlation of corresponding non-overlapping patches.

    a, b: (H, W, C) images; returns (num_patches,) correlations in grid
    row-major order (reference utils.py:161-180). Constant patches give 0.
    """
    h, w = a.shape[:2]
    gh, gw = h // patch_h, w // patch_w
    a = a[:gh * patch_h, :gw * patch_w].astype(np.float64)
    b = b[:gh * patch_h, :gw * patch_w].astype(np.float64)

    def flat_patches(img):
        c = img.shape[-1]
        x = img.reshape(gh, patch_h, gw, patch_w, c)
        return x.transpose(0, 2, 1, 3, 4).reshape(gh * gw, -1)

    pa, pb = flat_patches(a), flat_patches(b)
    pa = pa - pa.mean(axis=1, keepdims=True)
    pb = pb - pb.mean(axis=1, keepdims=True)
    denom = np.sqrt((pa * pa).sum(axis=1) * (pb * pb).sum(axis=1))
    num = (pa * pb).sum(axis=1)
    return np.where(denom > 0, num / np.maximum(denom, 1e-12), 0.0)


def save_image(img: np.ndarray, path: str) -> None:
    """Save an (H, W, 3) float/uint8 [0,255] array as PNG."""
    from PIL import Image
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    arr = np.clip(np.asarray(img), 0, 255).astype(np.uint8)
    Image.fromarray(arr).save(path)


def image_output_path(image_dir: str, index: int, bpp: float) -> str:
    """``<dir>/<idx>_<bpp:.4f>bpp.png`` (reference utils.py:102-111)."""
    return os.path.join(image_dir, f"{index}_{bpp:.4f}bpp.png")


class ScoreLists:
    """Accumulates per-image eval scores and persists them as txt lists.

    One file per metric, one float per line, appended in test order —
    the reference's `loss_list_saver` contract (utils.py:114-158), which
    downstream RD-curve tooling consumes.
    """

    METRICS = ("bpp", "real_bpp", "l1", "psnr", "ms_ssim",
               "mse_x_ysyn", "pearson_x_ysyn")

    def __init__(self, out_dir: str, model_name: str):
        self.out_dir = out_dir
        self.model_name = model_name
        self.values: Dict[str, List[float]] = {m: [] for m in self.METRICS}
        self._flushed = 0  # images already written by save()

    def add_image(self, x: np.ndarray, x_out: np.ndarray, bpp: float,
                  y_syn: Optional[np.ndarray] = None,
                  patch_size: Optional[Sequence[int]] = None,
                  real_bpp: Optional[float] = None) -> Dict[str, float]:
        """Score one test image; returns this image's metrics. `bpp` is the
        cross-entropy estimate (all the reference ever reports); `real_bpp`,
        when provided, is the measured size of an ACTUAL encoded bitstream
        (dsin_tpu.coding) — the capability the reference stubbed."""
        scores = {
            "bpp": float(bpp),
            "l1": l1_np(x, x_out),
            "psnr": psnr_np(x, x_out),
            "ms_ssim": multiscale_ssim_np(x, x_out),
        }
        if real_bpp is not None:
            scores["real_bpp"] = float(real_bpp)
        if y_syn is not None:
            scores["mse_x_ysyn"] = mse_np(x, y_syn)
            if patch_size is not None:
                ph, pw = patch_size
                scores["pearson_x_ysyn"] = float(
                    np.mean(pearson_per_patch(x, y_syn, ph, pw)))
        # every metric gets a row per image (nan when not computed) so line i
        # of every txt file refers to test image i, as in the reference
        for key in self.METRICS:
            self.values[key].append(scores.get(key, float("nan")))
        return scores

    def means(self) -> Dict[str, float]:
        """Per-metric means over the finite values seen so far (nan rows mark
        metrics not computed; inf PSNR from an exact reconstruction must not
        make the whole run's mean inf)."""
        out = {}
        for k, v in self.values.items():
            arr = np.asarray(v, dtype=np.float64)
            arr = arr[np.isfinite(arr)]
            if arr.size:
                out[k] = float(arr.mean())
        return out

    def save(self) -> None:
        """Append rows not yet written; safe to call after every image."""
        os.makedirs(self.out_dir, exist_ok=True)
        n = len(self.values["bpp"])
        for metric in self.METRICS:
            vals = self.values[metric][self._flushed:n]
            if not vals:
                continue
            path = os.path.join(self.out_dir,
                                f"{metric}_list_{self.model_name}.txt")
            with open(path, "a") as f:
                for v in vals:
                    f.write(f"{v}\n")
        self._flushed = n

    @staticmethod
    def load_list(out_dir: str, metric: str, model_name: str) -> np.ndarray:
        path = os.path.join(out_dir, f"{metric}_list_{model_name}.txt")
        with open(path) as f:
            return np.array([float(line) for line in f if line.strip()])
