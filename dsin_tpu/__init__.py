"""dsin_tpu — a TPU-native framework for decoder-side-information image compression.

A from-scratch JAX/XLA re-design of the capabilities of ayziksha/DSIN
(ECCV 2020, "Deep Image Compression using Decoder Side Information"):
a learned lossy codec whose decoder exploits a correlated side image the
encoder never sees.

Design principles (TPU-first, not a port):
  * NHWC layouts everywhere (TPU native), bfloat16-friendly compute paths.
  * One jitted train step — no feed_dicts, no separate "create y_dec" pass;
    the whole DSIN pipeline (encode -> quantize -> decode -> patch search ->
    fusion -> entropy model -> losses -> grads) is a single XLA program.
  * Batched by construction: the reference forces batch=1 whenever the
    side-information path is on (reference AE.py:26); here the SI search is
    vmapped and the train step is sharded over a `jax.sharding.Mesh`.
  * Static shapes, `lax` control flow, XLA fusion; Pallas for the hot
    correlation kernel.
"""

__version__ = "0.1.0"

import os as _os

# Package-wide, not per-CLI: some environments install an import hook that
# overrides `jax_platforms` at jax-import time; re-applying the documented
# JAX_PLATFORMS env var here covers every dsin_tpu entry point. No-op when
# the var is unset (does not even import jax).
if _os.environ.get("JAX_PLATFORMS"):
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

from dsin_tpu.config import Config, parse_config, parse_config_file  # noqa: F401,E402
