"""Sharded train/eval steps over a device mesh.

One jitted SPMD program: parameters/optimizer state replicated, the batch
sharded over the 'data' axis. The loss is a global batch mean, so GSPMD
emits the `psum` gradient all-reduce over ICI on its own — no hand-written
collectives, exactly the "annotate shardings, let XLA insert collectives"
recipe. Multi-host: call `jax.distributed.initialize()` first and feed each
host its `PairDataset` shard (data/loader.py host_id/num_hosts).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from dsin_tpu.models.dsin import DSIN
from dsin_tpu.parallel import mesh as mesh_lib
from dsin_tpu.train import step as step_lib


def make_sharded_train_step(model: DSIN, tx: optax.GradientTransformation,
                            mesh, si_mask: Optional[jnp.ndarray] = None,
                            donate: bool = True, grad_accum: int = 1):
    """(state, x, y) -> (state, metrics), batch sharded over 'data'.
    `grad_accum` micro-batches the GLOBAL batch with strided micros (see
    step.build_train_step_fn), so every micro stays spread over all 'data'
    shards with no resharding; each micro's gradient all-reduce rides the
    same GSPMD insertion."""
    fn = step_lib.build_train_step_fn(model, tx, si_mask,
                                      grad_accum=grad_accum)
    repl = mesh_lib.replicated(mesh)
    batch = mesh_lib.batch_sharding(mesh)
    return jax.jit(
        fn,
        in_shardings=(repl, batch, batch),
        out_shardings=(repl, repl),
        donate_argnums=(0,) if donate else (),
    )


def make_sharded_eval_step(model: DSIN, mesh,
                           si_mask: Optional[jnp.ndarray] = None):
    eval_fn = step_lib.build_eval_step_fn(model, si_mask)
    repl = mesh_lib.replicated(mesh)
    batch = mesh_lib.batch_sharding(mesh)
    return jax.jit(eval_fn, in_shardings=(repl, batch, batch),
                   out_shardings=repl)


def _build_spatial_syn(model: DSIN, mesh, img_h: int, img_w: int):
    """The ONE construction of the width-sharded search both spatial step
    builders share (same mask/dtype config reading — train and eval must
    run the same search)."""
    from dsin_tpu.ops.sifinder import sifinder_conv_dtype, sifinder_row_chunk
    from dsin_tpu.parallel.spatial import build_synthesize_shmap

    cfg = model.ae_config
    ph, pw = cfg.y_patch_size
    # sifinder_impl='xla_tiled' composes row tiling into the width shards:
    # per-device search memory O(row_chunk * Wl * P) — the very-large-extent
    # configuration (sharding and tiling multiply)
    row_chunk = (sifinder_row_chunk(cfg)
                 if getattr(cfg, "sifinder_impl", "auto") == "xla_tiled"
                 else None)
    return build_synthesize_shmap(mesh, ph, pw, img_h, img_w,
                                  use_mask=bool(cfg.use_gauss_mask),
                                  conv_dtype=sifinder_conv_dtype(cfg),
                                  row_chunk=row_chunk)


def make_spatial_eval_step(model: DSIN, mesh, img_h: int, img_w: int):
    """Width-sharded eval twin of make_spatial_train_step: same shard_map'd
    search, forward-only, metrics replicated."""
    syn = _build_spatial_syn(model, mesh, img_h, img_w)
    fn = step_lib.build_eval_step_fn(model, si_mask=None, synthesize_fn=syn)
    return jax.jit(fn,
                   in_shardings=(mesh_lib.replicated(mesh),
                                 mesh_lib.image_sharding(mesh),
                                 mesh_lib.image_sharding(mesh)),
                   out_shardings=mesh_lib.replicated(mesh))


def make_spatial_train_step(model: DSIN, tx: optax.GradientTransformation,
                            mesh, img_h: int, img_w: int,
                            donate: bool = True, grad_accum: int = 1):
    """Width-sharded FULL training step over a (data, spatial) mesh — the
    large-extent training path (SURVEY §5: Cityscapes-and-beyond crops whose
    score map / activations exceed one chip):

      * batch over 'data', image width over 'spatial' for both x and y;
      * the conv stacks (encoder/decoder/probclass/siNet) and the backward
        pass run under jit-with-shardings — GSPMD inserts the conv halo
        exchanges and the gradient all-reduce;
      * the patch search runs through the hand-reduced shard_map
        (parallel/spatial.build_synthesize_shmap: ppermute halo +
        all_gather argmax) because GSPMD would all-gather its score map.
        The search is fully stop-gradiented (reference AE.py:67,74), so
        the shard_map needs no VJP.

    Gradient parity with the unsharded step is pinned by
    tests/test_spatial.py. (state, x, y) -> (state, metrics); x and y must
    be (N, img_h, img_w, 3)."""
    assert not model.ae_only, (
        "spatial training is the SI path; AE_only needs no hand-sharded "
        "search — use make_sharded_train_step (GSPMD shards its convs)")
    syn = _build_spatial_syn(model, mesh, img_h, img_w)
    fn = step_lib.build_train_step_fn(model, tx, si_mask=None,
                                      synthesize_fn=syn,
                                      grad_accum=grad_accum)
    repl = mesh_lib.replicated(mesh)
    img_sh = mesh_lib.image_sharding(mesh)
    return jax.jit(
        fn,
        in_shardings=(repl, img_sh, img_sh),
        out_shardings=(repl, repl),
        donate_argnums=(0,) if donate else (),
    )
