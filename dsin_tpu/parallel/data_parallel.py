"""Sharded train/eval steps over a device mesh.

One jitted SPMD program: parameters/optimizer state replicated, the batch
sharded over the 'data' axis. The loss is a global batch mean, so GSPMD
emits the `psum` gradient all-reduce over ICI on its own — no hand-written
collectives, exactly the "annotate shardings, let XLA insert collectives"
recipe. Multi-host: call `jax.distributed.initialize()` first and feed each
host its `PairDataset` shard (data/loader.py host_id/num_hosts).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import optax

from dsin_tpu.models.dsin import DSIN
from dsin_tpu.parallel import mesh as mesh_lib
from dsin_tpu.train import step as step_lib


def make_sharded_train_step(model: DSIN, tx: optax.GradientTransformation,
                            mesh, si_mask: Optional[jnp.ndarray] = None,
                            donate: bool = True):
    """(state, x, y) -> (state, metrics), batch sharded over 'data'."""
    fn = step_lib.build_train_step_fn(model, tx, si_mask)
    repl = mesh_lib.replicated(mesh)
    batch = mesh_lib.batch_sharding(mesh)
    return jax.jit(
        fn,
        in_shardings=(repl, batch, batch),
        out_shardings=(repl, repl),
        donate_argnums=(0,) if donate else (),
    )


def make_sharded_eval_step(model: DSIN, mesh,
                           si_mask: Optional[jnp.ndarray] = None):
    eval_fn = step_lib.build_eval_step_fn(model, si_mask)
    repl = mesh_lib.replicated(mesh)
    batch = mesh_lib.batch_sharding(mesh)
    return jax.jit(eval_fn, in_shardings=(repl, batch, batch),
                   out_shardings=repl)
