"""Device-mesh construction and sharding specs.

The reference is strictly single-GPU (SURVEY §2: no distribution of any
kind); this layer is the new-first-class TPU capability: data parallelism
over ICI via `jax.sharding.Mesh` + jit-with-shardings (GSPMD inserts the
gradient all-reduce collectives), multi-host via `jax.distributed`.

Axes:
  * 'data'    — batch axis; gradients all-reduce over ICI automatically
                because the loss is a global batch mean under jit-SPMD.
  * 'spatial' — optional second axis for sharding image height on very
                large inputs (halo'd convs via GSPMD); 1 by default.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
SPATIAL_AXIS = "spatial"


def make_mesh(num_devices: Optional[int] = None,
              spatial: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a (data, spatial) mesh over the available devices."""
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        devices = devices[:num_devices]
    n = len(devices)
    # a typed error, not an assert: the serve placement layer now feeds
    # this from user-supplied --devices values, and asserts vanish under
    # `python -O`
    if n < 1:
        raise ValueError(
            "cannot build a mesh over zero devices — num_devices/devices "
            "selected an empty set")
    if spatial < 1 or n % spatial != 0:
        raise ValueError(
            f"device count {n} is not divisible by spatial={spatial}: "
            f"the (data, spatial) mesh needs n_devices to be a positive "
            f"multiple of the spatial axis")
    arr = np.asarray(devices).reshape(n // spatial, spatial)
    return Mesh(arr, axis_names=(DATA_AXIS, SPATIAL_AXIS))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) axis over 'data'; replicate the rest."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def _put_sharded(sh: NamedSharding, arrays):
    """Device-put host arrays under `sh`. Single-process: plain sharded
    device_put. Multi-process (jax.distributed): each host holds only ITS
    loader shard of the global batch (loader.py `host_id::num_hosts`), so
    the local array is this process's slice and the global array is
    assembled across hosts — device_put can't address other hosts'
    devices."""
    if jax.process_count() > 1:
        out = tuple(
            jax.make_array_from_process_local_data(sh, np.asarray(a))
            for a in arrays)
    else:
        out = tuple(jax.device_put(a, sh) for a in arrays)
    return out if len(out) > 1 else out[0]


def shard_batch(mesh: Mesh, *arrays):
    """Device-put arrays with the batch axis sharded over 'data'."""
    return _put_sharded(batch_sharding(mesh), arrays)


def image_sharding(mesh: Mesh) -> NamedSharding:
    """(N, H, W, C) images: batch over 'data', width over 'spatial'."""
    return NamedSharding(mesh, P(DATA_AXIS, None, SPATIAL_AXIS, None))


def shard_images(mesh: Mesh, *arrays):
    """Device-put (N, H, W, C) arrays with batch over 'data' and width over
    'spatial' — input layout for the width-sharded train/eval steps."""
    return _put_sharded(image_sharding(mesh), arrays)


def replicate_state(mesh: Mesh, state):
    """Replicate a TrainState (or any pytree) across the mesh. In
    multi-process mode every host passes the same host-local values (same
    init seed / restored checkpoint), which device_put broadcasts onto the
    fully-replicated sharding."""
    sh = replicated(mesh)
    return jax.device_put(state, sh)
