"""Spatial (width-axis) sharding for the SI patch search.

DSIN's long-extent axis is image resolution, not sequence length (SURVEY §5):
the analog of sequence/context parallelism here is sharding the *side-image
width* over the mesh's 'spatial' axis, so each device correlates every
x-patch against only its slice of y and the per-device score-map memory drops
from O(Hc*Wc*P) to O(Hc*Wc*P / S). Every x-patch must still see all of y —
the classic all-gather-or-ring situation — but only the *reductions* cross
devices, never the score map:

  1. halo exchange (`lax.ppermute` from the right neighbor) gives each shard
     the patch_w-1 boundary columns its last correlation windows need — the
     same halo pattern a sharded conv uses, sized for the search window;
  2. each shard computes its local masked score map and reduces it to P
     (value, flat-index) candidates + the P candidate patches gathered from
     its haloed ORIGINAL y slice;
  3. one `all_gather` over 'spatial' moves S*P scalars + S*P patches
     (~a few MB) over ICI; an argmax over the shard axis picks winners.

Ties resolve to the lowest global flat index (shards cover ascending column
ranges and local argmax picks the first maximum), so results are bit-identical
to the unsharded XLA path. Pearson mode only: the L2 variant's additive mask
discount needs a score-map global mean (see ops/sifinder.py) — supportable
via psum but not worth it for a non-default mode.

The autoencoder/siNet convs need no hand-written halo logic: under
jit-with-shardings GSPMD inserts halo exchanges for spatially-sharded convs
on its own. This module exists because the search's argmax+gather does NOT
shard well under GSPMD (it would all-gather the score map); the reduction
structure here is hand-picked instead.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from dsin_tpu.ops import color as color_lib
from dsin_tpu.ops import sifinder
from dsin_tpu.ops.patches import assemble_patches, extract_patches
from dsin_tpu.parallel.mesh import DATA_AXIS, SPATIAL_AXIS
from dsin_tpu.utils.jax_compat import shard_map


def _halo_from_right(z: jnp.ndarray, halo: int, axis_name: str):
    """Append the first `halo` width-columns of the right neighbor's shard.
    z: (H, Wl, C) -> (H, Wl + halo, C); the last shard gets zeros (those
    columns correspond to out-of-range global positions)."""
    n = jax.lax.psum(1, axis_name)
    left_edge = z[:, :halo, :]
    # shift shard s+1 -> s
    perm = [(src, dst) for dst, src in
            [(i, (i + 1) % n) for i in range(n)]]
    recv = jax.lax.ppermute(left_edge, axis_name, perm)
    idx = jax.lax.axis_index(axis_name)
    recv = jnp.where(idx == n - 1, jnp.zeros_like(recv), recv)
    return jnp.concatenate([z, recv], axis=1)


def _local_search(x_dec, y_img, y_dec, gh, gw, patch_h, patch_w, img_w,
                  eps=1e-12, conv_dtype=None, row_chunk=None):
    """Per-shard search for ONE pair. x_dec (H, W, 3) replicated;
    y_img/y_dec (H, Wl, 3) width shards. Returns y_syn (H, W, 3).

    `row_chunk=None` materializes the local (Hc, Wl, P) score map;
    an int runs the same math as a row-chunked `lax.scan` (the spatial
    composition of ops/sifinder.search_single_tiled), dropping per-shard
    peak memory to O(row_chunk * Wl * P) — width sharding and row tiling
    multiply, which is what makes Cityscapes-and-beyond extents fit."""
    axis = SPATIAL_AXIS
    h, w_local = y_dec.shape[0], y_dec.shape[1]
    wc = img_w - patch_w + 1
    halo = patch_w - 1
    shard = jax.lax.axis_index(axis)
    col0 = shard * w_local

    y_dec_h = _halo_from_right(y_dec, halo, axis)
    y_img_h = _halo_from_right(y_img, halo, axis)

    x_patches = extract_patches(x_dec, patch_h, patch_w)
    q = color_lib.search_transform(x_patches, False)
    r_img = color_lib.search_transform(y_dec_h, False)

    hc = h - patch_h + 1
    wl = w_local
    p_count = q.shape[0]
    gw_l = jax.lax.dynamic_slice(gw, (col0, 0), (wl, p_count))
    # validity of this shard's global columns (right edge of the last shard)
    cols_valid = (col0 + jnp.arange(wl)) < wc

    def _mask_chunk(scores, gh_slice):
        # combine the factors FIRST so each masked score is
        # scores * (gh*gw) — the exact multiply order of the unsharded
        # path's combined mask (gaussian_position_mask builds the same f32
        # product), keeping near-tie argmax winners bit-identical
        scores = scores * (gh_slice[:, None, :] * gw_l[None, :, :])
        return jnp.where(cols_valid[None, :, None], scores, -jnp.inf)

    if row_chunk is None:
        scores = sifinder.match_scores(q, r_img, use_l2=False, eps=eps,
                                       conv_dtype=conv_dtype)
        scores = _mask_chunk(scores, gh)
        flat = scores.reshape(hc * wl, p_count)
        best_local = jnp.argmax(flat, axis=0).astype(jnp.int32)   # (P,)
        best_val = jnp.max(flat, axis=0)                          # (P,)
    else:
        # the scan body (padding, per-chunk argmax, strict-">" tie merge)
        # lives in ops/sifinder.chunked_score_argmax — ONE copy of the
        # bit-parity contract for the unsharded and sharded tiled paths
        num_chunks = -(-hc // row_chunk)
        pad_rows = num_chunks * row_chunk + patch_h - 1 - r_img.shape[0]
        r_pad = jnp.pad(r_img, ((0, pad_rows), (0, 0), (0, 0)))
        gh_pad = jnp.pad(gh, ((0, num_chunks * row_chunk - hc), (0, 0)))

        def mask_chunk(scores, r0):
            gh_s = jax.lax.dynamic_slice(gh_pad, (r0, 0),
                                         (row_chunk, p_count))
            return _mask_chunk(scores, gh_s)

        best_val, best_local = sifinder.chunked_score_argmax(
            q, r_pad, hc, wl, row_chunk, mask_chunk, patch_h,
            conv_dtype=conv_dtype, eps=eps)
    rows = best_local // wl
    cols_l = best_local % wl
    flat_global = rows * wc + col0 + cols_l                   # (P,)

    cand = sifinder.gather_patches(y_img_h, rows, cols_l,
                                   patch_h, patch_w)          # (P, ph, pw, 3)

    # cross-shard reduction: S*(2P scalars + P patches) over ICI
    vals_g = jax.lax.all_gather(best_val, axis)               # (S, P)
    flat_g = jax.lax.all_gather(flat_global, axis)            # (S, P)
    cand_g = jax.lax.all_gather(cand, axis)                   # (S, P, ...)
    # winner = lowest global flat index among max-valued shards — exactly
    # jnp.argmax's first-maximum rule on the unsharded row-major map
    is_max = vals_g == jnp.max(vals_g, axis=0, keepdims=True)
    winner = jnp.argmin(jnp.where(is_max, flat_g, jnp.iinfo(jnp.int32).max),
                        axis=0)                               # (P,)
    y_patches = jnp.take_along_axis(
        cand_g, winner[None, :, None, None, None], axis=0)[0]
    return assemble_patches(y_patches, x_dec.shape[0], img_w)


def build_synthesize_shmap(mesh, patch_h: int, patch_w: int,
                           img_h: int, img_w: int, use_mask: bool = True,
                           conv_dtype=None, row_chunk: Optional[int] = None):
    """Un-jitted shard_map'd (x_dec, y_img, y_dec) -> y_syn for composing
    into larger jitted programs (e.g. the spatial inference step). Inputs
    are interpreted as: batch over 'data', y width over 'spatial', x_dec
    replicated over 'spatial'; output replicated over 'spatial'.

    `conv_dtype` must match the unsharded path's `sifinder_dtype` reading
    (pass `sifinder.sifinder_conv_dtype(config)`): the bit-parity contract
    with the unsharded search holds at float32 (conv_dtype None); with a
    reduced-precision conv both paths use the same dtype but halo
    partitioning changes the conv's reduction order, so near-tie argmax
    winners may differ at bf16."""
    hc, wc = img_h - patch_h + 1, img_w - patch_w + 1
    p_count = (img_h // patch_h) * (img_w // patch_w)
    if use_mask:
        gh_np, gw_np = sifinder.gaussian_position_mask_factors(
            img_h, img_w, patch_h, patch_w)
    else:
        gh_np = np.ones((hc, p_count), np.float32)
        gw_np = np.ones((wc, p_count), np.float32)
    # pad gw rows to the sharded width so dynamic_slice at the last shard's
    # offset stays in range (padded rows are masked by the cols<wc test)
    gw_np = np.pad(gw_np, ((0, img_w - wc), (0, 0)))
    gh = jnp.asarray(gh_np)
    gw = jnp.asarray(gw_np)

    spatial = mesh.shape[SPATIAL_AXIS]
    assert img_w % spatial == 0 and img_w % patch_w == 0, (
        f"width {img_w} must divide evenly into {spatial} shards and "
        f"{patch_w}-wide patches")
    assert img_w // spatial >= patch_w - 1, (
        f"shard width {img_w // spatial} narrower than the search halo "
        f"{patch_w - 1}: windows could straddle >2 shards (halo exchange "
        f"only reaches the immediate right neighbor)")

    def per_shard(x_dec, y_img, y_dec, gh_, gw_):
        fn = partial(_local_search, gh=gh_, gw=gw_, patch_h=patch_h,
                     patch_w=patch_w, img_w=img_w, conv_dtype=conv_dtype,
                     row_chunk=row_chunk)
        return jax.vmap(fn)(x_dec, y_img, y_dec)

    shmap = shard_map(
        per_shard, mesh=mesh,
        in_specs=(P(DATA_AXIS, None, None, None),
                  P(DATA_AXIS, None, SPATIAL_AXIS, None),
                  P(DATA_AXIS, None, SPATIAL_AXIS, None),
                  P(), P()),
        out_specs=P(DATA_AXIS, None, None, None),
        check_vma=False)

    return lambda x_dec, y_img, y_dec: shmap(x_dec, y_img, y_dec, gh, gw)


def make_spatial_synthesize(mesh, patch_h: int, patch_w: int,
                            img_h: int, img_w: int,
                            use_mask: bool = True):
    """Jitted (x_dec, y_img, y_dec) -> y_syn with batch sharded over 'data'
    and y width sharded over 'spatial'. All arguments (N, H, W, 3); output
    replicated over 'spatial', sharded over 'data'.

    Bit-parity with `ops.sifinder.synthesize_side_image` (Pearson mode with
    the standard Gaussian prior, or no mask)."""
    fn = build_synthesize_shmap(mesh, patch_h, patch_w, img_h, img_w,
                                use_mask)
    x_sh = NamedSharding(mesh, P(DATA_AXIS, None, None, None))
    y_sh = NamedSharding(mesh, P(DATA_AXIS, None, SPATIAL_AXIS, None))
    return jax.jit(fn, in_shardings=(x_sh, y_sh, y_sh), out_shardings=x_sh)


def make_spatial_inference_step(model, mesh, img_h: int, img_w: int):
    """Full-model inference with the image WIDTH sharded over 'spatial' —
    the large-extent path (Cityscapes-and-beyond resolutions, SURVEY §5)
    where one chip can't hold the score map or the activations:

      * the conv stacks (encoder/decoder/probclass/siNet) run under
        jit-with-shardings — GSPMD inserts the conv halo exchanges;
      * the patch search runs through the hand-reduced shard_map
        (build_synthesize_shmap) because GSPMD would all-gather its
        score map.

    Returns jitted (state, x, y) -> dict like step.make_inference_step;
    x/y must be (N, img_h, img_w, 3), batch divisible by the 'data' axis.
    """
    from dsin_tpu.models.probclass import bitcost_to_bpp

    cfg = model.ae_config
    assert not model.ae_only, (
        "make_spatial_inference_step is the SI path; AE_only models have "
        "no siNet — use step.make_inference_step")
    ph, pw = cfg.y_patch_size
    use_mask = bool(cfg.use_gauss_mask)
    row_chunk = (sifinder.sifinder_row_chunk(cfg)
                 if getattr(cfg, "sifinder_impl", "auto") == "xla_tiled"
                 else None)
    syn = build_synthesize_shmap(mesh, ph, pw, img_h, img_w, use_mask,
                                 conv_dtype=sifinder.sifinder_conv_dtype(cfg),
                                 row_chunk=row_chunk)

    repl = NamedSharding(mesh, P())
    img_sh = NamedSharding(mesh, P(DATA_AXIS, None, SPATIAL_AXIS, None))

    def infer(state, x, y):
        params, bs = state.params, state.batch_stats
        enc_out, _ = model.encode(params, bs, x, train=False)
        x_dec, _ = model.decode(params, bs, enc_out.qbar, train=False)
        y_enc, _ = model.encode(params, bs, y, train=False)
        y_dec, _ = model.decode(params, bs, y_enc.qbar, train=False)
        y_syn = syn(x_dec, y, y_dec)
        x_with_si = model.apply_sinet(params, x_dec, y_syn)
        bc = model.bitcost(params, enc_out.qbar, enc_out.symbols)
        return {"x_dec": x_dec, "x_with_si": x_with_si, "y_syn": y_syn,
                "bpp": bitcost_to_bpp(bc, x)}

    return jax.jit(infer, in_shardings=(repl, img_sh, img_sh))
