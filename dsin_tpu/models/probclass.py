"""Causal 3-D context model ("probclass") estimating symbol entropy.

Capability parity with the reference `_ResShallow` (reference
probclass_imgcomp.py:27-221): the quantized bottleneck is treated as a 3-D
volume over (channel-depth D, H, W) with one feature channel; a stack of
VALID masked 3-D convolutions (filter DHW = (K//2+1, K, K)) predicts, for
every symbol, a distribution over the L quantizer centers from its causal
context only:

* `first_mask` zeroes the center tap and everything after it in raster order
  within the last depth slice (probclass_imgcomp.py:150-162);
* `other_mask` keeps the center tap (163-176);
* the volume is padded `pad = context//2` in front (depth), left/right and
  top/bottom — never behind in depth ("the future is not seen by any
  filter", probclass_imgcomp.py:285-292) — with `centers[0]` when
  `use_centers_for_padding` (pc config);
* residual blocks re-align the VALID-conv shrinkage by cropping the skip
  input `[2:, 2:-2, 2:-2]` (probclass_imgcomp.py:196);
* bitcost = cross-entropy(logits, symbols) * log2(e)  [bits per symbol]
  (probclass_imgcomp.py:100-106).

Layout note: framework tensors are NHWC; this module transposes to the
(N, D=C, H, W, 1) volume internally. Depth stays a real spatial axis of the
conv (that is the causality structure), H/W tiles map onto the MXU.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


def context_size(kernel_size: int, num_layers: int = 4) -> int:
    """Receptive-field width: num_layers*(K-1) + 1 (reference :43-52)."""
    return num_layers * (kernel_size - 1) + 1


def context_shape(kernel_size: int):
    """(D, H, W) receptive field (reference :18-24)."""
    cs = context_size(kernel_size)
    return cs // 2 + 1, cs, cs


def filter_shape(kernel_size: int):
    """(D, H, W) of each conv filter (reference :145-148)."""
    return kernel_size // 2 + 1, kernel_size, kernel_size


def make_mask(kernel_size: int, include_center: bool) -> np.ndarray:
    """Causality mask over the (D, H, W) filter.

    In the last depth slice: zero all rows below the center row and, in the
    center row, everything right of the center — plus the center tap itself
    for the first layer (include_center=False).
    """
    d, h, w = filter_shape(kernel_size)
    mask = np.ones((d, h, w), dtype=np.float32)
    ch, cw = kernel_size // 2, kernel_size // 2
    start = cw + 1 if include_center else cw
    mask[-1, ch, start:] = 0.0
    mask[-1, ch + 1:, :] = 0.0
    return mask


def pad_volume(vol: jnp.ndarray, kernel_size: int, pad_value) -> jnp.ndarray:
    """Pad (N, D, H, W, 1): depth front only, H/W both sides, by context//2."""
    pad = context_size(kernel_size) // 2
    assert pad >= 1
    cfg = ((0, 0), (pad, 0), (pad, pad), (pad, pad), (0, 0))
    # The pad value may be a traced scalar (centers[0]) whose gradient must
    # flow; lax.pad's transpose rule drops the padding-value cotangent, so
    # pad with zeros and add pad_value through the complement mask instead.
    pv = jnp.asarray(pad_value, dtype=vol.dtype)
    padded = jnp.pad(vol, cfg)
    interior = jnp.pad(jnp.ones_like(vol), cfg)
    return padded + (1.0 - interior) * pv


class _MaskedConv3D(nn.Module):
    """VALID 3-D conv with a fixed causality mask multiplied into the weights."""
    features: int
    kernel_size: int
    include_center: bool

    @nn.compact
    def __call__(self, x):  # x: (N, D, H, W, F)
        fs = filter_shape(self.kernel_size)
        in_feat = x.shape[-1]
        w = self.param("kernel", nn.initializers.xavier_uniform(),
                       fs + (in_feat, self.features), jnp.float32)
        b = self.param("bias", nn.initializers.zeros, (self.features,),
                       jnp.float32)
        mask = jnp.asarray(make_mask(self.kernel_size, self.include_center))
        w = w * mask[..., None, None]
        out = jax.lax.conv_general_dilated(
            x, w, window_strides=(1, 1, 1), padding="VALID",
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
        return out + b


class ResShallow(nn.Module):
    """conv0(first_mask) -> 1 residual block -> conv to L logits."""
    config: object      # pc config
    num_centers: int    # L

    @nn.compact
    def __call__(self, vol):  # vol: (N, D, H, W, 1) padded volume
        k = self.config.arch_param__k
        ks = self.config.kernel_size
        net = _MaskedConv3D(k, ks, include_center=False)(vol)
        net = nn.relu(net)
        # residual block (2 masked convs, relu between, cropped skip);
        # the skip crop undoes two VALID convs' shrinkage: depth loses K//2
        # per conv (all from the front — padding sits there), H/W lose
        # (K-1)//2 per side per conv (reference :196 hardcodes K=3's 2/2/2)
        inp = net
        net = nn.relu(_MaskedConv3D(k, ks, include_center=True)(net))
        net = _MaskedConv3D(k, ks, include_center=True)(net)
        dd, hw = 2 * (ks // 2), ks - 1
        net = net + inp[:, dd:, hw:-hw, hw:-hw, :]
        net = _MaskedConv3D(self.num_centers, ks, include_center=True)(net)
        # the reference's conv3d applies its default ReLU even to this final
        # logits layer (probclass_imgcomp.py:220,234,260) — logits are >= 0
        return nn.relu(net)  # (N, D, H, W, L) logits


def get_network_cls(pc_config):
    return {"res_shallow": ResShallow}[pc_config.arch]


def auto_pad_value(pc_config, centers: jnp.ndarray):
    """centers[0] when use_centers_for_padding else 0 (reference :59-61)."""
    return centers[0] if pc_config.use_centers_for_padding else 0.0


def logits_from_q(model: ResShallow, variables, q_nhwc: jnp.ndarray,
                  pad_value) -> jnp.ndarray:
    """q (N, H, W, C) -> causal logits (N, H, W, C, L)."""
    vol = jnp.transpose(q_nhwc, (0, 3, 1, 2))[..., None]  # (N, D=C, H, W, 1)
    vol = pad_volume(vol, model.config.kernel_size, pad_value)
    logits = model.apply(variables, vol)                  # (N, D, H, W, L)
    return jnp.transpose(logits, (0, 2, 3, 1, 4))         # (N, H, W, C, L)


def bitcost(model: ResShallow, variables, q_nhwc: jnp.ndarray,
            symbols_nhwc: jnp.ndarray, pad_value) -> jnp.ndarray:
    """Bits per symbol, shape (N, H, W, C) (reference :63-106)."""
    logits = logits_from_q(model, variables, q_nhwc, pad_value)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, symbols_nhwc[..., None].astype(jnp.int32),
                               axis=-1)[..., 0]
    return nll * np.log2(np.e)


def bitcost_to_bpp(bit_cost: jnp.ndarray, input_batch: jnp.ndarray):
    """Total bits / total image pixels (reference bits_imgcomp.py:4-21).

    bit_cost: (N, H, W, C) over bottleneck positions; input_batch: (N, H, W, 3).
    """
    num_bits = jnp.sum(bit_cost)
    num_pixels = input_batch.size // input_batch.shape[-1]
    return num_bits / num_pixels
