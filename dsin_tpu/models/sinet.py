"""siNet: dilated-convolution fusion network.

Capability parity with the reference siNet (reference siNet.py:29-41): a
context-aggregation net — nine 3x3 conv layers, 32 channels, dilation rates
1,2,4,8,16,32,64,128,1, leaky-relu(0.2), identity-initialized, *no*
normalization — followed by a 1x1 conv to 3 channels. Input is the
6-channel concat of normalized (x_dec, y_syn); output is the normalized
residual image, denormalized by the caller (reference AE.py:63-69).

NHWC layout; dilated 3x3 convs lower to efficient XLA window ops on TPU.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

_DILATIONS = (1, 2, 4, 8, 16, 32, 64, 128, 1)


def identity_kernel_init(key, shape, dtype=jnp.float32):
    """Center-tap identity over matching in/out channels
    (reference siNet.py:13-20)."""
    kh, kw, cin, cout = shape
    kernel = np.zeros(shape, dtype=np.float32)
    ch, cw = kh // 2, kw // 2
    for i in range(min(cin, cout)):
        kernel[ch, cw, i, i] = 1.0
    return jnp.asarray(kernel, dtype)


class SiNet(nn.Module):
    """(N, H, W, 6) normalized concat -> (N, H, W, 3) normalized output.

    `dtype`: conv compute dtype (bfloat16 = TPU MXU fast path); params
    stay float32 and the output is returned in float32."""
    features: int = 32
    out_features: int = 3
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        for i, rate in enumerate(_DILATIONS):
            x = nn.Conv(self.features, (3, 3), padding="SAME",
                        kernel_dilation=(rate, rate),
                        kernel_init=identity_kernel_init,
                        dtype=self.dtype,
                        name=f"g_conv{i + 1}")(x)
            x = nn.leaky_relu(x, negative_slope=0.2)
        x = nn.Conv(self.out_features, (1, 1), padding="SAME",
                    kernel_init=nn.initializers.xavier_uniform(),
                    dtype=self.dtype,
                    name="g_conv_last")(x)
        return jnp.asarray(x, jnp.float32)
