"""Soft-to-hard scalar quantizer with straight-through estimator.

Capability parity with the reference quantizer (reference
quantizer_imgcomp.py:37-100): L learned scalar centers; soft assignment
softmax(-sigma * |x - c|^2) for gradients, hard assignment argmin |x - c| for
the forward value, STE `qbar = qsoft + stop_grad(qhard - qsoft)`
(reference autoencoder_imgcomp.py:127-134).

TPU-first notes: the distance tensor broadcasts to (..., L) with L=6 — tiny
trailing axis; XLA fuses the softmax/argmax chain into the surrounding ops so
nothing materializes in HBM. No reshape to (B, C, m, 1) is needed (the
reference's reshape is a TF broadcasting workaround).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

HARD_SIGMA = 1e7  # reference quantizer_imgcomp.py:5


class QuantizerOutput(NamedTuple):
    qbar: jnp.ndarray     # STE value: hard forward, soft backward
    qsoft: jnp.ndarray    # soft (differentiable) quantization
    qhard: jnp.ndarray    # nearest-center value
    symbols: jnp.ndarray  # int32 center indices


def init_centers(rng: jax.Array, num_centers: int,
                 initial_range=(-2, 2)) -> jnp.ndarray:
    """Uniform init over `initial_range` (reference quantizer_imgcomp.py:28-31)."""
    minval, maxval = initial_range
    return jax.random.uniform(rng, (num_centers,), jnp.float32,
                              float(minval), float(maxval))


def centers_lookup(centers: jnp.ndarray,
                   symbols: jnp.ndarray) -> jnp.ndarray:
    """Map int symbols back to center values — the decoder-side inverse of
    `quantize(...).symbols` (qhard == centers_lookup(centers, symbols))."""
    return jnp.take(centers, symbols)


def quantize(x: jnp.ndarray, centers: jnp.ndarray,
             sigma: float = 1.0) -> QuantizerOutput:
    """Quantize `x` (any shape) against `centers` (L,).

    Returns qsoft/qhard/qbar of x's shape and int32 symbols.
    """
    assert centers.ndim == 1, centers.shape
    dist = jnp.square(x[..., None] - centers)          # (..., L)
    phi_soft = jax.nn.softmax(-sigma * dist, axis=-1)  # (..., L)
    symbols = jnp.argmin(dist, axis=-1)                # (...)
    qsoft = jnp.sum(phi_soft * centers, axis=-1)
    qhard = centers[symbols]
    qbar = qsoft + jax.lax.stop_gradient(qhard - qsoft)
    return QuantizerOutput(qbar=qbar, qsoft=qsoft, qhard=qhard,
                           symbols=symbols.astype(jnp.int32))


def centers_regularization(centers: jnp.ndarray, factor: float) -> jnp.ndarray:
    """L2 on the centers: factor * sum(c^2)/2 (reference quantizer_imgcomp.py:18-24)."""
    return factor * 0.5 * jnp.sum(jnp.square(centers))
