"""DSIN model bundle: autoencoder + entropy model (+ SI path).

Owns the module instances and the parameter partitioning that the whole
framework (train step, checkpointing, optimizer labeling) relies on:

    params = {'encoder': ..., 'decoder': ..., 'centers': ...,
              'probclass': ..., 'sinet': ...}          (sinet iff not AE_only)
    batch_stats = {'encoder': ..., 'decoder': ...}

This mirrors the reference's TF variable scopes ('encoder/encoder_body',
'decoder', 'imgcomp', 'siNetwork'; reference AE.py:158-175) so the 3-phase
workflow (train AE_only -> warm-start + train siNet -> inference) keeps its
partial-checkpoint semantics.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from dsin_tpu.models import autoencoder as ae_lib
from dsin_tpu.models import probclass as pc_lib
from dsin_tpu.models import quantizer as quant_lib


class DSINVariables(NamedTuple):
    params: Dict[str, Any]
    batch_stats: Dict[str, Any]


class DSIN:
    """Module bundle + pure forward helpers (no state held here)."""

    def __init__(self, ae_config, pc_config):
        self.ae_config = ae_config
        self.pc_config = pc_config
        self.encoder = ae_lib.Encoder(ae_config)
        self.decoder = ae_lib.Decoder(ae_config)
        self.probclass = pc_lib.get_network_cls(pc_config)(
            pc_config, num_centers=ae_config.num_centers)
        self.ae_only = bool(ae_config.AE_only)
        self.si_weight = 0.0 if self.ae_only else ae_config.si_weight
        if not self.ae_only:
            from dsin_tpu.models.sinet import SiNet
            self.sinet = SiNet(
                dtype=jnp.dtype(ae_config.get("compute_dtype", "float32")))
        else:
            self.sinet = None

    # -- initialization -----------------------------------------------------

    def init_variables(self, rng: jax.Array,
                       input_shape: Tuple[int, int, int, int]) -> DSINVariables:
        """Build the partitioned params/batch_stats trees for `input_shape`
        = (N, H, W, 3)."""
        k_enc, k_dec, k_pc, k_centers, k_sinet = jax.random.split(rng, 5)
        x = jnp.zeros(input_shape, jnp.float32)

        enc_vars = self.encoder.init(k_enc, x, True)
        centers = quant_lib.init_centers(
            k_centers, self.ae_config.num_centers,
            self.ae_config.centers_initial_range)
        enc_out, _ = ae_lib.encode(self.encoder, enc_vars, x, centers,
                                   train=True)
        dec_vars = self.decoder.init(k_dec, enc_out.qbar, True)

        vol = pc_lib.pad_volume(
            jnp.transpose(enc_out.qbar, (0, 3, 1, 2))[..., None],
            self.pc_config.kernel_size, 0.0)
        pc_vars = self.probclass.init(k_pc, vol)

        params = {
            "encoder": enc_vars["params"],
            "decoder": dec_vars["params"],
            "centers": centers,
            "probclass": pc_vars["params"],
        }
        batch_stats = {
            "encoder": enc_vars["batch_stats"],
            "decoder": dec_vars["batch_stats"],
        }
        if self.sinet is not None:
            si_in = jnp.zeros(input_shape[:3] + (6,), jnp.float32)
            sinet_vars = self.sinet.init(k_sinet, si_in)
            params["sinet"] = sinet_vars["params"]
        return DSINVariables(params=params, batch_stats=batch_stats)

    # -- forward pieces -----------------------------------------------------

    def encode(self, params, batch_stats, x, train: bool, mutable: bool = False):
        enc_vars = {"params": params["encoder"],
                    "batch_stats": batch_stats["encoder"]}
        return ae_lib.encode(self.encoder, enc_vars, x, params["centers"],
                             train=train, mutable=mutable)

    def decode(self, params, batch_stats, q, train: bool, mutable: bool = False):
        dec_vars = {"params": params["decoder"],
                    "batch_stats": batch_stats["decoder"]}
        return ae_lib.decode(self.decoder, dec_vars, q, train=train,
                             mutable=mutable)

    def bitcost(self, params, q, symbols):
        pad = pc_lib.auto_pad_value(self.pc_config, params["centers"])
        return pc_lib.bitcost(self.probclass, {"params": params["probclass"]},
                              q, symbols, pad_value=pad)

    def apply_sinet(self, params, x_dec, y_syn):
        """Fuse the decoded image with the synthesized side image
        (reference AE.py:63-69): 6-channel normalized concat, stop-gradient
        on the y_syn branch, denormalized 3-channel output."""
        style = self.ae_config.normalization
        concat = jnp.concatenate(
            [ae_lib.normalize_image(x_dec, style),
             jax.lax.stop_gradient(ae_lib.normalize_image(y_syn, style))],
            axis=-1)
        out = self.sinet.apply({"params": params["sinet"]}, concat)
        return ae_lib.denormalize_image(out, style)
