"""CVPR-style convolutional autoencoder (flax, NHWC, TPU-first).

Capability parity with the reference `_CVPR` architecture (reference
autoencoder_imgcomp.py:214-269): encoder = two stride-2 5x5 convs
(n/2 then n=128) -> B groups of three 2-conv residual blocks with a group
skip -> one final residual block + outer skip -> stride-2 5x5 conv to the
bottleneck (C channels + 1 learned heatmap channel); decoder mirrors it with
stride-2 transposed convs. Batch norm (decay .9, eps 1e-5, scaled) follows
every conv, including the bottleneck and output convs, exactly as slim's
arg_scope applies it in the reference (autoencoder_imgcomp.py:106-125).
Subsampling factor 8 (autoencoder_imgcomp.py:216-217).

The bottleneck heatmap gating (autoencoder_imgcomp.py:172-201): channel 0 ->
sigmoid * C -> per-channel ramp mask clip(h - c, 0, 1) multiplied into the
remaining C channels, letting the network spend bits only where needed.

Layout is NHWC (TPU native); the reference's NCHW is a GPU-era choice.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from dsin_tpu.models import quantizer as quantizer_lib

ARCH_PARAM_N = 128  # reference autoencoder_imgcomp.py:211 (default; a
# config may override with `arch_param_N` for reduced-scale corpora)

# KITTI RGB statistics (reference autoencoder_imgcomp.py:160-170)
KITTI_MEAN = np.array([93.70454143384742, 98.28243432206516,
                       94.84678088809876], dtype=np.float32)
KITTI_VAR = np.array([5411.79935676, 5758.60456747, 5890.31451232],
                     dtype=np.float32)


class EncoderOutput(NamedTuple):
    qbar: jnp.ndarray                 # quantized bottleneck (STE)
    qhard: jnp.ndarray
    symbols: jnp.ndarray              # int32 (N, Hb, Wb, C)
    z: jnp.ndarray                    # pre-quantization bottleneck
    heatmap: Optional[jnp.ndarray]    # (N, Hb, Wb, C) in [0, 1] or None


def normalize_image(x: jnp.ndarray, style: str) -> jnp.ndarray:
    if style == "OFF":
        return x
    if style == "FIXED":
        return (x - KITTI_MEAN) / np.sqrt(KITTI_VAR + 1e-10)
    raise ValueError(f"invalid normalization style {style!r}")


def denormalize_image(x: jnp.ndarray, style: str) -> jnp.ndarray:
    if style == "OFF":
        return x
    if style == "FIXED":
        return x * np.sqrt(KITTI_VAR + 1e-10) + KITTI_MEAN
    raise ValueError(f"invalid normalization style {style!r}")


def heatmap3d(bottleneck: jnp.ndarray) -> jnp.ndarray:
    """Per-channel ramp mask from the heatmap channel (channel 0).

    bottleneck: (N, H, W, C+1) -> mask (N, H, W, C) with
    mask[..., c] = clip(sigmoid(b[..., 0]) * C - c, 0, 1).
    """
    c_total = bottleneck.shape[-1] - 1
    heat2d = jax.nn.sigmoid(bottleneck[..., 0]) * c_total        # (N, H, W)
    ramp = jnp.arange(c_total, dtype=jnp.float32)                # (C,)
    return jnp.clip(heat2d[..., None] - ramp, 0.0, 1.0)


_BN_KW = dict(momentum=0.9, epsilon=1e-5, use_scale=True, use_bias=True)


class _ConvBN(nn.Module):
    """Conv + batch norm (+ optional relu), slim-arg_scope style.

    `dtype` is the conv COMPUTE dtype (TPU mixed precision: bfloat16 puts
    the matmul-conv on the MXU fast path). Params stay float32
    (param_dtype default), and BatchNorm's type promotion (bf16 input +
    f32 scale/bias -> f32) returns the activation to float32, so
    statistics, residual adds, and everything downstream of each conv are
    full precision — only the conv itself drops to bf16."""
    features: int
    kernel: int
    stride: int = 1
    relu: bool = True
    transpose: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool):
        conv_cls = nn.ConvTranspose if self.transpose else nn.Conv
        x = conv_cls(self.features, (self.kernel, self.kernel),
                     strides=(self.stride, self.stride), padding="SAME",
                     use_bias=False, dtype=self.dtype,
                     kernel_init=nn.initializers.xavier_uniform())(x)
        x = nn.BatchNorm(use_running_average=not train, **_BN_KW)(x)
        if self.relu:
            x = nn.relu(x)
        return x


class _ResBlock(nn.Module):
    """Two 3x3 conv+BN; relu after the first only (unless relu_first=False);
    residual add (reference autoencoder_imgcomp.py:275-288)."""
    features: int
    relu_first: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool):
        inp = x
        x = _ConvBN(self.features, 3, relu=self.relu_first,
                    dtype=self.dtype)(x, train)
        x = _ConvBN(self.features, 3, relu=False, dtype=self.dtype)(x, train)
        return x + inp


class _ResGroupStack(nn.Module):
    """B groups of three residual blocks, each group with its own skip,
    followed by a no-activation residual block and an outer skip
    (reference autoencoder_imgcomp.py:226-235, 253-263).

    `remat=True` rematerializes each residual block in the backward pass
    (jax.checkpoint via nn.remat): activations inside the block are not
    stored, trading ~1 extra forward's FLOPs for the trunk's activation
    HBM traffic — the backward is the step's largest consumer
    (artifacts/PERF_ANALYSIS.md). Numerics are unchanged."""
    features: int
    num_groups: int
    dtype: Any = jnp.float32
    remat: bool = False

    @nn.compact
    def __call__(self, x, train: bool):
        # Rematted blocks are explicitly named with the baseline's
        # auto-generated names so the param tree (and every existing
        # checkpoint) is IDENTICAL across remat on/off — toggling a
        # memory knob must never invalidate trained weights.
        block_cls = (nn.remat(_ResBlock, static_argnums=(2,))
                     if self.remat else _ResBlock)
        idx = 0
        outer = x
        for _ in range(self.num_groups):
            inner = x
            for _ in range(3):
                x = block_cls(self.features, dtype=self.dtype,
                              name=f"_ResBlock_{idx}")(x, train)
                idx += 1
            x = x + inner
        x = block_cls(self.features, relu_first=False, dtype=self.dtype,
                      name=f"_ResBlock_{idx}")(x, train)
        return x + outer


class Encoder(nn.Module):
    """Image (N, H, W, 3) in [0,255] -> bottleneck (N, H/8, W/8, C(+1))."""
    config: object  # ae config

    @nn.compact
    def __call__(self, x, train: bool):
        cfg = self.config
        n = cfg.get("arch_param_N", ARCH_PARAM_N)
        dt = jnp.dtype(cfg.get("compute_dtype", "float32"))
        x = normalize_image(x, cfg.normalization)
        x = _ConvBN(n // 2, 5, stride=2, dtype=dt)(x, train)
        x = _ConvBN(n, 5, stride=2, dtype=dt)(x, train)
        x = _ResGroupStack(n, cfg.arch_param_B, dtype=dt,
                          remat=bool(cfg.get("remat", False)))(x, train)
        c_out = cfg.num_chan_bn + 1 if cfg.heatmap else cfg.num_chan_bn
        x = _ConvBN(c_out, 5, stride=2, relu=False, dtype=dt)(x, train)
        return x


class Decoder(nn.Module):
    """Quantized bottleneck (N, H/8, W/8, C) -> image (N, H, W, 3) in [0,255]."""
    config: object

    @nn.compact
    def __call__(self, q, train: bool):
        cfg = self.config
        n = cfg.get("arch_param_N", ARCH_PARAM_N)
        dt = jnp.dtype(cfg.get("compute_dtype", "float32"))
        x = _ConvBN(n, 3, stride=2, transpose=True, dtype=dt)(q, train)
        x = _ResGroupStack(n, cfg.arch_param_B, dtype=dt,
                          remat=bool(cfg.get("remat", False)))(x, train)
        x = _ConvBN(n // 2, 5, stride=2, transpose=True, dtype=dt)(x, train)
        x = _ConvBN(3, 5, stride=2, transpose=True, relu=False,
                    dtype=dt)(x, train)
        x = jnp.asarray(x, jnp.float32)
        x = denormalize_image(x, cfg.normalization)
        return jnp.clip(x, 0.0, 255.0)


SUBSAMPLING_FACTOR = 8


def encode(encoder: Encoder, variables, x, centers, train: bool,
           mutable=False):
    """Run the encoder + heatmap gating + quantization.

    Returns (EncoderOutput, new_batch_stats_or_None).
    """
    if train:
        # train-mode BN always computes batch stats and proposes updated
        # running averages; the caller decides whether to keep them
        # (bn_stats='frozen' replicates the reference's never-updated stats)
        bottleneck, mut = encoder.apply(variables, x, train,
                                        mutable=["batch_stats"])
        if not mutable:
            mut = None
    else:
        bottleneck, mut = encoder.apply(variables, x, train), None

    cfg = encoder.config
    if cfg.heatmap:
        heat = heatmap3d(bottleneck)
        z = heat * bottleneck[..., 1:]
    else:
        heat = None
        z = bottleneck
    qout = quantizer_lib.quantize(z, centers, sigma=1.0)
    return EncoderOutput(qbar=qout.qbar, qhard=qout.qhard,
                         symbols=qout.symbols, z=z, heatmap=heat), mut


def decode(decoder: Decoder, variables, q, train: bool, mutable=False):
    """Run the decoder. Returns (x_out, new_batch_stats_or_None)."""
    if train:
        out, mut = decoder.apply(variables, q, train, mutable=["batch_stats"])
        return out, (mut if mutable else None)
    return decoder.apply(variables, q, train), None
