"""Optimizers: per-subsystem learning rates over one param tree.

Capability parity with the reference's two-optimizer split (reference
AE.py:177-191 + fjcommon `create_train_op_with_different_lrs`): the entropy
model ("pc") trains under its own optimizer + LR schedule; everything else
under the default AE optimizer. Optionally the quantizer centers get a scaled
AE LR (`lr_centers_factor`, reference ae config:34), and the
`train_autoencoder` / `train_probclass` switches freeze whole partitions.

TPU-first: instead of two apply_gradients ops, one `optax.multi_transform`
over labeled partitions — a single fused update inside the jitted step.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import optax


def iterations_per_epoch(num_crops_per_img: int, batch_size: int,
                         num_training_imgs: int, ae_only: bool) -> int:
    """Reference training_helpers_imgcomp.py:51-60 (incl. the hardcoded
    1,281,000-image "ImageNet epoch" when AE_only)."""
    num_unique_imgs_per_batch = max(batch_size // num_crops_per_img, 1)
    if ae_only:
        num_training_imgs = 1281000
    return max(num_training_imgs // num_unique_imgs_per_batch, 1)


def learning_rate_schedule(config, num_crops_per_img: int,
                           num_training_imgs: int, batch_size: int,
                           ae_only: bool) -> optax.Schedule:
    """FIXED or (staircase) exponential DECAY with epoch-based interval
    (reference training_helpers_imgcomp.py:22-35)."""
    lr = config.lr_initial
    if config.lr_schedule == "FIXED":
        return optax.constant_schedule(lr)
    if config.lr_schedule == "DECAY":
        decay_steps = (iterations_per_epoch(num_crops_per_img, batch_size,
                                            num_training_imgs, ae_only)
                       * config.lr_schedule_decay_interval)
        return optax.exponential_decay(
            init_value=lr, transition_steps=decay_steps,
            decay_rate=config.lr_schedule_decay_rate,
            staircase=config.lr_schedule_decay_staircase)
    raise ValueError(f"invalid lr_schedule {config.lr_schedule!r}")


def _base_optimizer(config, schedule: optax.Schedule) -> optax.GradientTransformation:
    kind = config.optimizer
    if kind == "ADAM":
        return optax.adam(schedule)
    if kind == "SGD":
        return optax.sgd(schedule)
    if kind == "MOMENTUM":
        return optax.sgd(schedule, momentum=config.optimizer_momentum,
                         nesterov=True)
    raise ValueError(f"invalid optimizer {kind!r}")


def _label_tree(params: Dict[str, Any], ae_config) -> Dict[str, Any]:
    """Label each top-level partition with its optimizer group."""
    use_centers_group = ae_config.get("lr_centers_factor") is not None

    def label_for(part: str) -> str:
        if part == "probclass":
            return "pc" if ae_config.get("train_probclass", True) else "frozen"
        if part in ("encoder", "decoder", "centers"):
            if not ae_config.get("train_autoencoder", True):
                return "frozen"  # freezing the AE freezes the centers too
            if part == "centers" and use_centers_group:
                return "centers"
            return "ae"
        return "ae"  # sinet and anything else trains under the AE optimizer

    return {part: jax.tree_util.tree_map(lambda _: label_for(part), sub)
            for part, sub in params.items()}


def build_optimizer(params: Optional[Dict[str, Any]], ae_config, pc_config,
                    num_training_imgs: int) -> optax.GradientTransformation:
    """`params` may be None: labels are then computed lazily at tx.init."""
    batch = ae_config.batch_size
    crops = ae_config.num_crops_per_img
    ae_only = ae_config.AE_only

    ae_sched = learning_rate_schedule(ae_config, crops, num_training_imgs,
                                      batch, ae_only)
    pc_sched = learning_rate_schedule(pc_config, crops, num_training_imgs,
                                      batch, ae_only)

    transforms = {
        "ae": _base_optimizer(ae_config, ae_sched),
        "pc": _base_optimizer(pc_config, pc_sched),
        "frozen": optax.set_to_zero(),
    }
    factor = ae_config.get("lr_centers_factor")
    if factor is not None:
        centers_sched = lambda step: ae_sched(step) * factor  # noqa: E731
        transforms["centers"] = _base_optimizer(ae_config, centers_sched)

    labels = (_label_tree(params, ae_config) if params is not None
              else lambda p: _label_tree(p, ae_config))
    return optax.multi_transform(transforms, labels)
