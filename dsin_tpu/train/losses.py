"""Rate-distortion loss assembly.

Capability parity with the reference `get_loss` (reference
Distortions_imgcomp.py:113-146) and the AE-level combination
(reference AE.py:80-99):

  H_real  = mean(bitcost)
  H_mask  = mean(bitcost * heatmap)         (heatmap gates where bits count)
  H_soft  = (H_mask + H_real) / 2
  pc_loss = beta * max(H_soft - H_target, 0)
  total   = d_loss_scaled + pc_loss + L2(enc) + L2(dec) + L2(centers) + L2(pc)
  loss    = total + si_weight * L1(x, x_with_si)     [/ batch_size if SI batch>1]

where d_loss_scaled already carries the (1 - si_weight) factor.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp


class RateLoss(NamedTuple):
    pc_loss: jnp.ndarray
    H_real: jnp.ndarray
    H_mask: jnp.ndarray
    H_soft: jnp.ndarray


def rate_loss(bitcost: jnp.ndarray, heatmap: Optional[jnp.ndarray],
              H_target: float, beta: float) -> RateLoss:
    H_real = jnp.mean(bitcost)
    if heatmap is not None:
        H_mask = jnp.mean(bitcost * heatmap)
    else:
        H_mask = H_real
    H_soft = 0.5 * (H_mask + H_real)
    pc_loss = beta * jnp.maximum(H_soft - H_target, 0.0)
    return RateLoss(pc_loss=pc_loss, H_real=H_real, H_mask=H_mask,
                    H_soft=H_soft)


def _l2_of_kernels(params: Any) -> jnp.ndarray:
    """Sum of ||w||^2/2 over conv kernels only — slim regularizes conv
    weights, not biases or norm scales (reference autoencoder_imgcomp.py:101-103)."""
    total = jnp.float32(0.0)
    for path, leaf in jax.tree_util.tree_leaves_with_path(params):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if name == "kernel":
            total = total + 0.5 * jnp.sum(jnp.square(leaf))
    return total


def regularization_losses(params: Dict[str, Any], ae_config,
                          pc_config) -> Dict[str, jnp.ndarray]:
    """L2 terms per partition. `params` holds top-level keys
    'encoder', 'decoder', 'centers', 'probclass' (and optionally 'sinet',
    which the reference never regularizes)."""
    out = {}
    factor = ae_config.regularization_factor
    out["enc"] = factor * _l2_of_kernels(params["encoder"])
    out["dec"] = factor * _l2_of_kernels(params["decoder"])
    out["centers"] = (ae_config.regularization_factor_centers *
                      0.5 * jnp.sum(jnp.square(params["centers"])))
    pc_factor = pc_config.regularization_factor
    out["pc"] = (pc_factor * _l2_of_kernels(params["probclass"])
                 if pc_factor is not None else jnp.float32(0.0))
    return out


def total_loss(d_loss_scaled: jnp.ndarray, rate: RateLoss,
               regs: Dict[str, jnp.ndarray]) -> jnp.ndarray:
    reg = regs["enc"] + regs["dec"] + regs["centers"] + regs["pc"]
    return d_loss_scaled + rate.pc_loss + reg


def si_l1_loss(x: jnp.ndarray, x_with_si: jnp.ndarray) -> jnp.ndarray:
    """tf.losses.absolute_difference default: mean |x - y| (reference AE.py:94)."""
    return jnp.mean(jnp.abs(x - x_with_si))
