"""Jitted train / eval steps for DSIN.

The reference runs every SI training iteration as three `sess.run` round
trips (reference AE.py:108-118: an extra full AE forward on `y` to make
`y_dec`, then the train fetch; plus the data-session fetch). Here the whole
thing — including the `y_dec` synthesis — is ONE jitted XLA program: no
host round trips, no feed_dicts, fully fused on TPU.

Semantics preserved from the reference graph:
  * `y_dec` is computed with eval-mode BN under stop_gradient
    (reference AE.py:150-152 runs it as inference);
  * the train-branch bitcost sees stop_gradient(qbar) so the heatmap only
    receives the rate gradient through the H_mask product (AE.py:73-76);
  * the eval loss uses the *train* distortion cast rules (the reference
    builds `Distortions(..., is_training=True)` once and reuses
    `d_train.d_loss_scaled` in loss_test — AE.py:89-91), while BN runs in
    eval mode;
  * `loss = total + si_weight * L1(x, x_si)`, divided by batch_size when the
    SI path trains with batch > 1 (AE.py:93-99).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import optax

from dsin_tpu.models import probclass as pc_lib
from dsin_tpu.models.dsin import DSIN
from dsin_tpu.ops import metrics as metrics_lib
from dsin_tpu.train import losses as loss_lib


@flax.struct.dataclass
class TrainState:
    params: Dict[str, Any]
    batch_stats: Dict[str, Any]
    opt_state: Any
    step: jnp.ndarray


def create_train_state(model: DSIN, rng: jax.Array, input_shape,
                       tx: optax.GradientTransformation) -> TrainState:
    variables = model.init_variables(rng, input_shape)
    return TrainState(
        params=variables.params,
        batch_stats=variables.batch_stats,
        opt_state=tx.init(variables.params),
        step=jnp.zeros((), jnp.int32),
    )


def _forward_losses(model: DSIN, params, batch_stats, x, y,
                    si_mask: Optional[jnp.ndarray], train: bool,
                    collect_mutations: bool,
                    synthesize_fn=None):
    """Shared forward pass. Returns (loss, aux dict).

    `synthesize_fn`: optional (x_dec, y_img, y_dec) -> y_syn override of the
    default `ops.sifinder.synthesize_side_image` dispatch — the
    width-sharded trainer injects its shard_map'd search here (the search
    is fully stop-gradiented, so the override never needs a VJP)."""
    ae_cfg = model.ae_config

    enc_out, enc_mut = model.encode(params, batch_stats, x, train=train,
                                    mutable=collect_mutations)
    x_dec, dec_mut = model.decode(params, batch_stats, enc_out.qbar,
                                  train=train, mutable=collect_mutations)

    if model.ae_only:
        x_with_si = jnp.zeros_like(x)
        y_syn = None
        si_l1 = jnp.float32(0.0)
    else:
        from dsin_tpu.ops.sifinder import synthesize_side_image
        # y_dec: inference-mode AE reconstruction of the side image,
        # no gradients (reference AE.py:150-152)
        stop = jax.lax.stop_gradient
        y_enc, _ = model.encode(stop(params), batch_stats, y, train=False)
        y_dec, _ = model.decode(stop(params), batch_stats, y_enc.qbar,
                                train=False)
        if synthesize_fn is not None:
            y_syn = synthesize_fn(stop(x_dec), y, stop(y_dec))
        else:
            y_syn = synthesize_side_image(
                x_dec=stop(x_dec), y_img=y, y_dec=stop(y_dec), mask=si_mask,
                patch_h=ae_cfg.y_patch_size[0],
                patch_w=ae_cfg.y_patch_size[1], config=ae_cfg)
        x_with_si = model.apply_sinet(params, x_dec, y_syn)
        si_l1 = loss_lib.si_l1_loss(x, x_with_si)

    # distortion: train cast rules even at eval (see module docstring)
    dist = metrics_lib.compute_distortions(ae_cfg, x, x_dec, is_training=True)
    d_scaled = (1.0 - model.si_weight) * dist.d_loss_scaled

    pc_in = enc_out.qbar if not train else jax.lax.stop_gradient(enc_out.qbar)
    bc = model.bitcost(params, pc_in, enc_out.symbols)
    bpp = pc_lib.bitcost_to_bpp(bc, x)
    rate = loss_lib.rate_loss(bc, enc_out.heatmap, ae_cfg.H_target,
                              ae_cfg.beta)
    regs = loss_lib.regularization_losses(params, ae_cfg, model.pc_config)
    total = loss_lib.total_loss(d_scaled, rate, regs)

    loss = total + model.si_weight * si_l1
    if (not model.ae_only) and ae_cfg.batch_size > 1 and train:
        loss = loss / float(ae_cfg.batch_size)

    aux = {
        "symbols": enc_out.symbols,
        "bpp": bpp,
        "H_real": rate.H_real,
        "H_soft": rate.H_soft,
        "pc_loss": rate.pc_loss,
        "d_loss": dist.d_loss_scaled,
        "mae": dist.mae,
        "psnr": dist.psnr,
        "si_l1": si_l1,
        "x_dec": x_dec,
        "x_with_si": x_with_si,
        "y_syn": y_syn,
        "enc_mut": enc_mut,
        "dec_mut": dec_mut,
    }
    return loss, aux


SCALAR_METRICS = ("bpp", "H_real", "H_soft", "pc_loss", "d_loss", "mae",
                  "psnr", "si_l1")


def _scalar_metrics(loss, aux):
    metrics = {k: aux[k] for k in SCALAR_METRICS}
    metrics["loss"] = loss
    return metrics


def build_train_step_fn(model: DSIN, tx: optax.GradientTransformation,
                        si_mask: Optional[jnp.ndarray] = None,
                        synthesize_fn=None, grad_accum: int = 1):
    """The un-jitted train step (state, x, y) -> (state, metrics) — callers
    wrap it in `jax.jit` (single chip) or jit-with-shardings (mesh).

    `grad_accum > 1` splits the leading batch axis into that many
    micro-batches, accumulates their gradients in a `lax.scan`, and applies
    ONE optimizer update — peak activation memory scales with the
    micro-batch while the update sees the accumulated gradient. The loss's
    batch reductions are means (and the SI /batch rule divides by the
    *static* config batch size, losses.py), so the averaged micro
    gradients equal the full-batch gradient exactly whenever the loss is a
    mean of per-example terms. Two terms are not: BatchNorm in train mode
    (it normalizes by the micro-batch's own statistics; the usual
    grad-accum caveat in every framework), and the rate hinge
    pc_loss = beta * max(H_soft - H_target, 0) (losses.py) — H_soft is a
    batch mean before the max, so when micro-batch H_soft values straddle
    the target, some micros contribute zero penalty gradient where the
    full batch would contribute a scaled-down nonzero one. BN batch_stats
    chain sequentially through the micro-batches (same semantics as
    running the micros as consecutive reference steps); metrics are
    averaged."""
    update_bn = model.ae_config.get("bn_stats", "update") == "update"

    def grads_and_aux(params, batch_stats, x, y):
        def loss_fn(p):
            return _forward_losses(model, p, batch_stats, x, y,
                                   si_mask, train=True,
                                   collect_mutations=update_bn,
                                   synthesize_fn=synthesize_fn)
        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params)
        return loss, aux, grads

    def new_stats(aux, batch_stats):
        if update_bn:
            return {"encoder": aux["enc_mut"]["batch_stats"],
                    "decoder": aux["dec_mut"]["batch_stats"]}
        return batch_stats

    def train_step(state: TrainState, x, y):
        if grad_accum == 1:
            loss, aux, grads = grads_and_aux(state.params, state.batch_stats,
                                             x, y)
            batch_stats = new_stats(aux, state.batch_stats)
            metrics = _scalar_metrics(loss, aux)
        else:
            b = x.shape[0]
            assert b % grad_accum == 0, (
                f"batch {b} not divisible by grad_accum {grad_accum}")
            micro = b // grad_accum
            # STRIDED micro-batches (micro k = rows k::grad_accum), not
            # contiguous slices: under data-parallel sharding the batch
            # axis is block-sharded across devices, so contiguous micros
            # would each live on a fraction of the mesh and force a
            # per-step reshard; strided micros keep every micro spread
            # over all shards
            xs = jnp.swapaxes(x.reshape(micro, grad_accum, *x.shape[1:]),
                              0, 1)
            ys = jnp.swapaxes(y.reshape(micro, grad_accum, *y.shape[1:]),
                              0, 1)

            def body(carry, xy):
                stats, grad_sum, metric_sum = carry
                loss, aux, grads = grads_and_aux(state.params, stats, *xy)
                grad_sum = jax.tree_util.tree_map(jnp.add, grad_sum, grads)
                m = _scalar_metrics(loss, aux)
                metric_sum = {k: metric_sum[k] + m[k] for k in metric_sum}
                return (new_stats(aux, stats), grad_sum, metric_sum), None

            zero_grads = jax.tree_util.tree_map(jnp.zeros_like, state.params)
            zero_metrics = {k: jnp.float32(0.0)
                            for k in list(SCALAR_METRICS) + ["loss"]}
            (batch_stats, grad_sum, metric_sum), _ = jax.lax.scan(
                body, (state.batch_stats, zero_grads, zero_metrics),
                (xs, ys))
            inv = 1.0 / grad_accum
            grads = jax.tree_util.tree_map(lambda g: g * inv, grad_sum)
            metrics = {k: v * inv for k, v in metric_sum.items()}

        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        new_state = TrainState(params=params, batch_stats=batch_stats,
                               opt_state=opt_state, step=state.step + 1)
        return new_state, metrics

    return train_step


def make_train_step(model: DSIN, tx: optax.GradientTransformation,
                    si_mask: Optional[jnp.ndarray] = None,
                    donate: bool = True, grad_accum: int = 1):
    """Build the jitted single-chip train step: (state, x, y) -> (state, metrics)."""
    train_step = build_train_step_fn(model, tx, si_mask,
                                     grad_accum=grad_accum)
    return jax.jit(train_step, donate_argnums=(0,) if donate else ())


def build_eval_step_fn(model: DSIN, si_mask: Optional[jnp.ndarray] = None,
                       synthesize_fn=None):
    """The un-jitted eval step (state, x, y) -> metrics — callers wrap it in
    `jax.jit` (single chip) or jit-with-shardings (mesh)."""

    def eval_step(state: TrainState, x, y):
        loss, aux = _forward_losses(model, state.params, state.batch_stats,
                                    x, y, si_mask, train=False,
                                    collect_mutations=False,
                                    synthesize_fn=synthesize_fn)
        return _scalar_metrics(loss, aux)

    return eval_step


def make_eval_step(model: DSIN, si_mask: Optional[jnp.ndarray] = None):
    """Build the jitted eval step: (state, x, y) -> metrics (incl. loss)."""
    return jax.jit(build_eval_step_fn(model, si_mask))


def make_inference_step(model: DSIN, si_mask: Optional[jnp.ndarray] = None):
    """Full reconstruction fetch (reference AE.py:132-148):
    (state, x, y) -> dict with x_dec, x_with_si, y_syn, bpp."""

    def infer(state: TrainState, x, y):
        loss, aux = _forward_losses(model, state.params, state.batch_stats,
                                    x, y, si_mask, train=False,
                                    collect_mutations=False)
        return {"x_dec": aux["x_dec"], "x_with_si": aux["x_with_si"],
                "y_syn": aux["y_syn"], "bpp": aux["bpp"], "loss": loss,
                "psnr": aux["psnr"], "mae": aux["mae"],
                "symbols": aux["symbols"]}

    return jax.jit(infer)
