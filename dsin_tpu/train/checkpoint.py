"""Partitioned checkpointing with partial-restore semantics.

Capability parity with the reference's scope-filtered `tf.train.Saver`
workflow (reference AE.py:154-175 + main.py:141-165), which enables the
3-phase DSIN recipe:
  (a) train AE_only              -> save ae partitions
  (b) fresh siNet, frozen-ish AE -> restore ae only, train SI
  (c) inference                  -> restore ae + sinet
and `load_train_step` additionally restores optimizer state + step counter.

Design: each partition is serialized independently (flax msgpack) inside a
checkpoint directory, so a restore can pick any subset; a `meta.json`
records step/best-val, and the config snapshot + `last_saved` sidecars match
the reference's text files. Directory layout:

    <dir>/
      params_encoder.msgpack     params_decoder.msgpack
      params_centers.msgpack     params_probclass.msgpack
      params_sinet.msgpack       batch_stats.msgpack
      opt_state.msgpack          meta.json

Durability (ISSUE 3): `save_checkpoint` never touches the live directory.
Everything is staged into a fsynced `<dir>.tmp-<pid>` sibling, the live
dir is rotated aside to `<dir>.prev-NNNNNN`, and the staged dir takes its
place — both steps are single atomic renames, so a kill at ANY point
leaves either the old or the new checkpoint complete (and
`latest_checkpoint` resolves whichever survives). Transient OSErrors on
the staging writes retry with the shared bounded policy (utils/retry.py);
`keep_last` bounds the rotated history. Fault-injection sites
`ckpt.write` (every staged file write) and `ckpt.swap` (the window
between the two renames) let the chaos tests kill a save at every
crash point (utils/faults.py).

Versioned manifests (ISSUE 9): every save also writes a `manifest.json`
through the same staged-atomic path (before `meta.json`, so a dir with
meta always has its manifest): format version, per-partition content
digests + a whole-tree `params_digest` (coding/loader.py's digest — the
multi-replica fleet handshake compares the same value), per-file CRC32s
and sizes, and whatever identity the caller threads through
`manifest_extra` (canonical pc-config hash, init seed, serve bucket
ladder). Loaders verify the manifest against what they actually
restored and refuse mismatches with a typed `ManifestMismatch`;
checkpoints from before the manifest era load with a recorded
`UserWarning`. A corrupt/truncated manifest (or `meta.json`) raises a
typed `IntegrityError` instead of a raw JSONDecodeError from deep
inside restore. `replicate_checkpoint` copies the resolved latest
checkpoint to a peer-visible destination with every byte CRC-verified
against the manifest on BOTH sides of the copy, so a second host can
adopt the exact versioned checkpoint (the `.prev-*` follow-up from
ISSUE 3). The `ckpt.manifest` fault site corrupts manifest bytes as a
loader reads them — the chaos corrupt-incoming-manifest scenario.
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any, Dict, Iterable, List, Optional

import flax.serialization
import jax
import numpy as np

from dsin_tpu.utils import faults
from dsin_tpu.utils.integrity import IntegrityError, frame_crc
from dsin_tpu.utils.retry import RetryPolicy, call_with_retry

AE_PARTITIONS = ("encoder", "decoder", "centers", "probclass")

MANIFEST_NAME = "manifest.json"
#: bump when the manifest SCHEMA changes incompatibly; loaders refuse a
#: manifest from a future version (they cannot know what it promises)
MANIFEST_VERSION = 1


class ManifestMismatch(ValueError):
    """A checkpoint's manifest disagrees with what a loader built or
    restored (wrong params bytes, different pc config, different bucket
    ladder, future format). ValueError subclass so generic bad-input
    handlers route it; typed so swap/serve paths can refuse it
    specifically — the whole point is refusing a mismatched model
    BEFORE it serves a single request."""

#: bounded retry for transient write failures (EIO on flaky NFS, EAGAIN);
#: persistent failures still propagate after the third attempt
WRITE_RETRY = RetryPolicy(max_attempts=3, base_delay_s=0.05,
                          max_delay_s=0.5)


def _to_host(tree):
    return jax.tree_util.tree_map(lambda a: np.asarray(a), tree)


def _fsync_dir(path: str) -> None:
    """Flush a directory's entry table; best-effort where dirs can't be
    opened (non-POSIX filesystems)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _write_bytes_durable(path: str, data: bytes) -> None:
    """write + flush + fsync, with bounded retry on transient OSError.
    Each attempt revisits the `ckpt.write` fault site."""

    def _attempt():
        faults.inject("ckpt.write")
        with open(path, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())

    call_with_retry(_attempt, WRITE_RETRY, retry_on=(OSError,))


def _write_msgpack(path: str, tree) -> Dict[str, int]:
    # to_state_dict first: opt_state holds optax NamedTuple/dataclass nodes
    # (e.g. multi_transform's PartitionState) that msgpack can't serialize raw
    state = flax.serialization.to_state_dict(_to_host(tree))
    data = flax.serialization.msgpack_serialize(state)
    _write_bytes_durable(path, data)
    return {"bytes": len(data), "crc32": frame_crc(data)}


def _tree_digest(tree) -> str:
    """The repo's ONE parameter digest (coding/loader.py): the manifest
    records the same value the serve fleet handshake and the hot-swap
    two-phase commit compare, so 'this checkpoint' means the same 16 hex
    chars everywhere. Imported lazily: coding.loader pulls jax/numpy
    only at module level, but keeping train/ import-light matters for
    the one-shot CLI."""
    from dsin_tpu.coding.loader import params_digest
    return params_digest(tree)


def config_sha256(config) -> str:
    """Canonical-text hash of a Config (str() round-trips through
    config.parse_config, so equal semantics hash equal)."""
    import hashlib
    return hashlib.sha256(str(config).encode()).hexdigest()[:16]


def build_manifest(state, files: Optional[Dict[str, Dict[str, int]]] = None,
                   extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """The identity a checkpoint carries: format version, per-partition
    content digests (a loader restoring a SUBSET can still verify what
    it took), the whole-tree `params_digest`, and per-file CRC32s for
    byte-level replication checks. `extra` threads caller identity in —
    the trainer's pc-config hash + seed, a serve-side bucket ladder."""
    manifest: Dict[str, Any] = {
        "manifest_version": MANIFEST_VERSION,
        "step": int(state.step),
        "partitions": sorted(state.params.keys()),
        "partition_digests": {part: _tree_digest(sub)
                              for part, sub in state.params.items()},
        "batch_stats_digest": _tree_digest(state.batch_stats),
        "params_digest": _tree_digest((state.params, state.batch_stats)),
    }
    if files is not None:
        manifest["files"] = dict(sorted(files.items()))
    if extra:
        if "canary" in extra:
            # golden canary digests (ISSUE 13, serve/quality.py): the
            # serving fleet REFUSES a swap whose staged outputs do not
            # match these, so publishing a malformed entry would brick
            # every future swap of this checkpoint — validate at save,
            # where the publisher can still fix it
            from dsin_tpu.serve.quality import validate_goldens
            bad = validate_goldens(extra["canary"])
            if bad is not None:
                raise ValueError(
                    f"manifest_extra['canary'] is malformed ({bad}) — "
                    f"record the structure serve/quality.py "
                    f"goldens_struct builds (CompressionService"
                    f".canary_goldens returns it)")
        manifest.update(extra)
    return manifest


def _read_msgpack(path: str):
    with open(path, "rb") as f:
        return flax.serialization.msgpack_restore(f.read())


def _restore_like(template, loaded):
    """Shape the raw msgpack dict back into the template's pytree types."""
    return flax.serialization.from_state_dict(template, loaded)


def _prev_dirs(parent: str, name: str) -> List[str]:
    """Rotated `<name>.prev-NNNNNN` siblings, oldest first."""
    prefix = f"{name}.prev-"
    try:
        entries = os.listdir(parent)
    except OSError:
        return []
    return sorted(os.path.join(parent, e) for e in entries
                  if e.startswith(prefix))


def _rescue_nested_dirs(src_dir: str, live_dir: str) -> None:
    """Move foreign SUBDIRECTORIES (nested periodic/emergency
    checkpoints — a checkpoint's own payload is files-only) out of a
    rotated-aside dir into the live dir. The live dir's copy, when one
    exists, is newer (it was carried at swap time) and wins."""
    try:
        entries = os.listdir(src_dir)
    except OSError:
        return
    moved = False
    for entry in entries:
        src = os.path.join(src_dir, entry)
        dst = os.path.join(live_dir, entry)
        if os.path.isdir(src) and not os.path.exists(dst):
            try:
                os.rename(src, dst)
                moved = True
            except OSError:
                pass   # cross-device or racing saver: leave it in place
    if moved:
        _fsync_dir(live_dir)


def save_checkpoint(ckpt_dir: str, state, *, best_val: Optional[float] = None,
                    extra_meta: Optional[Dict[str, Any]] = None,
                    manifest_extra: Optional[Dict[str, Any]] = None,
                    keep_last: int = 1) -> None:
    """Save a TrainState (params/batch_stats/opt_state/step) partitioned,
    durably: the live dir is replaced only by a complete, fsynced copy.

    The v0 scheme overwrote the live dir in place (meta removed first,
    rewritten last) — a torn write was non-DISCOVERABLE, but a kill
    mid-save still destroyed the only resumable state of a long run.
    Now every kill point keeps a complete checkpoint on disk:

      kill during staging   -> live dir untouched (the stale tmp sibling
                               is swept by the next save);
      kill between renames  -> live dir briefly absent, but the newest
                               `<dir>.prev-*` is complete —
                               `latest_checkpoint` resolves it;
      kill after the swap   -> new live dir complete.

    `keep_last` bounds how many rotated `.prev-*` dirs survive.
    Concurrent saves into one `ckpt_dir` are not supported (they never
    were); distinct dirs are independent."""
    ckpt_dir = os.path.abspath(ckpt_dir)
    parent, name = os.path.split(ckpt_dir)
    os.makedirs(parent or ".", exist_ok=True)
    # sweep stale tmp dirs from earlier killed saves (any pid — a live
    # concurrent saver to the same dir is unsupported, see docstring)
    for entry in os.listdir(parent):
        if entry.startswith(f"{name}.tmp-"):
            shutil.rmtree(os.path.join(parent, entry), ignore_errors=True)

    tmp = os.path.join(parent, f"{name}.tmp-{os.getpid()}")
    os.makedirs(tmp)
    files: Dict[str, Dict[str, int]] = {}
    for part, sub in state.params.items():
        fname = f"params_{part}.msgpack"
        files[fname] = _write_msgpack(os.path.join(tmp, fname), sub)
    files["batch_stats.msgpack"] = _write_msgpack(
        os.path.join(tmp, "batch_stats.msgpack"), state.batch_stats)
    files["opt_state.msgpack"] = _write_msgpack(
        os.path.join(tmp, "opt_state.msgpack"), state.opt_state)
    # manifest BEFORE meta: meta.json is the completeness marker
    # (latest_checkpoint resolves on it), so any dir with meta is
    # guaranteed to carry its manifest too
    manifest = build_manifest(state, files=files, extra=manifest_extra)
    _write_bytes_durable(os.path.join(tmp, MANIFEST_NAME),
                         json.dumps(manifest, indent=2).encode())
    meta = {"step": int(state.step),
            "partitions": sorted(state.params.keys())}
    if best_val is not None:
        meta["best_val"] = float(best_val)
    if extra_meta:
        meta.update(extra_meta)
    _write_bytes_durable(os.path.join(tmp, "meta.json"),
                         json.dumps(meta, indent=2).encode())
    _fsync_dir(tmp)

    if os.path.isdir(ckpt_dir):
        prevs = _prev_dirs(parent, name)
        next_idx = (int(os.path.basename(prevs[-1]).rsplit("-", 1)[1]) + 1
                    if prevs else 1)
        os.rename(ckpt_dir, os.path.join(parent,
                                         f"{name}.prev-{next_idx:06d}"))
        faults.inject("ckpt.swap")    # the kill window between renames
    os.rename(tmp, ckpt_dir)
    _fsync_dir(parent)
    # a checkpoint's own payload is files-only, so any SUBDIRECTORY in a
    # rotated-aside dir is foreign nested content — e.g. the periodic/
    # and emergency/ checkpoints main.py keeps inside ckpt_dir. Carry it
    # from the NEWEST .prev into the fresh live dir: leaving it there
    # would strand it and the keep_last prune below would silently
    # delete the very saves that bound crash loss. The newest prev is
    # consulted even when THIS save found no live dir to rotate — that
    # is exactly the resume-after-a-kill-in-the-swap-window state, where
    # the nested content sits in a prev that may be KEPT (not pruned)
    # for several more saves. EVERY prev is swept, newest first (newest
    # copy wins — _rescue only fills absences): with keep_last >= 2 a
    # kill before a previous save's rescue leaves the content in a prev
    # that is neither the newest nor due for pruning.
    for prev in reversed(_prev_dirs(parent, name)):
        _rescue_nested_dirs(prev, ckpt_dir)

    for old in _prev_dirs(parent, name)[:-keep_last if keep_last else None]:
        # rescue again right before deleting: a kill between the swap
        # above and its carry-over leaves nested content only in a
        # .prev dir — the prune must never be the thing that destroys
        # the last copy of a periodic/emergency checkpoint
        _rescue_nested_dirs(old, ckpt_dir)
        shutil.rmtree(old, ignore_errors=True)


def latest_checkpoint(ckpt_dir: str) -> Optional[str]:
    """Resolve the most recent COMPLETE checkpoint for `ckpt_dir`: the
    dir itself when its meta.json exists, else the newest rotated
    `<dir>.prev-*` that has one (the kill-between-renames window), else
    None. Completeness == meta.json present: the staged-swap protocol
    guarantees a dir with meta has every msgpack it names."""
    ckpt_dir = os.path.abspath(ckpt_dir)
    if os.path.exists(os.path.join(ckpt_dir, "meta.json")):
        return ckpt_dir
    parent, name = os.path.split(ckpt_dir)
    for prev in reversed(_prev_dirs(parent, name)):
        if os.path.exists(os.path.join(prev, "meta.json")):
            return prev
    return None


def load_meta(ckpt_dir: str) -> Dict[str, Any]:
    """Parse `meta.json`; corruption/truncation raises a typed
    `IntegrityError` (a ValueError, so every existing skip-this-
    candidate handler keeps working) instead of a raw JSONDecodeError
    surfacing from deep inside a restore."""
    path = os.path.join(ckpt_dir, "meta.json")
    with open(path, "rb") as f:
        raw = f.read()
    try:
        return json.loads(raw.decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        raise IntegrityError(
            f"checkpoint meta {path} is corrupt or truncated "
            f"({len(raw)} bytes): {e} — the save was torn or the file "
            f"rotted; resolve a complete checkpoint via "
            f"latest_checkpoint() instead") from e


def load_manifest(ckpt_dir: str) -> Optional[Dict[str, Any]]:
    """Parse `manifest.json`, or None for a pre-manifest checkpoint.
    The bytes pass through the `ckpt.manifest` fault site (the chaos
    corrupt-incoming-manifest scenario); a manifest that does not parse
    raises typed IntegrityError — never a raw JSONDecodeError."""
    path = os.path.join(ckpt_dir, MANIFEST_NAME)
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except FileNotFoundError:
        return None
    raw = faults.corrupt("ckpt.manifest", raw)
    try:
        manifest = json.loads(raw.decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        raise IntegrityError(
            f"checkpoint manifest {path} is corrupt or truncated "
            f"({len(raw)} bytes): {e} — refusing to trust this "
            f"checkpoint's identity") from e
    if not isinstance(manifest, dict):
        raise IntegrityError(
            f"checkpoint manifest {path} is not a JSON object "
            f"({type(manifest).__name__})")
    return manifest


def verify_manifest(ckpt_dir: str, state, partitions: Iterable[str], *,
                    batch_stats_loaded: bool = True,
                    pc_config=None,
                    buckets=None) -> Dict[str, Any]:
    """Check a RESTORED state against the checkpoint's manifest.

    Verifies manifest format version, the content digest of every
    partition in `partitions` (computed over the restored values — a
    msgpack roundtrip is bit-exact, so any difference is real), the
    batch_stats digest when it was loaded, and — when BOTH sides state
    them — the canonical pc-config hash and the serve bucket ladder.
    Returns {"status": "verified", "manifest": {...}} or
    {"status": "legacy", "manifest": None} for a pre-manifest
    checkpoint (the caller records the warning); any disagreement
    raises typed ManifestMismatch."""
    manifest = load_manifest(ckpt_dir)
    if manifest is None:
        return {"status": "legacy", "manifest": None}
    version = manifest.get("manifest_version")
    if not isinstance(version, int) or version < 1 \
            or version > MANIFEST_VERSION:
        raise ManifestMismatch(
            f"checkpoint {ckpt_dir} has manifest_version {version!r}; "
            f"this loader understands 1..{MANIFEST_VERSION} — refusing "
            f"to guess what a different format promises")
    part_digests = manifest.get("partition_digests", {})
    for part in partitions:
        want = part_digests.get(part)
        if want is None:
            raise ManifestMismatch(
                f"checkpoint {ckpt_dir} manifest records no digest for "
                f"restored partition {part!r} (has: "
                f"{sorted(part_digests)})")
        got = _tree_digest(state.params[part])
        if got != want:
            raise ManifestMismatch(
                f"checkpoint {ckpt_dir} partition {part!r} digest "
                f"mismatch: manifest {want}, restored {got} — the "
                f"restored bytes are not the bytes this manifest "
                f"describes")
    if batch_stats_loaded and "batch_stats_digest" in manifest:
        got = _tree_digest(state.batch_stats)
        if got != manifest["batch_stats_digest"]:
            raise ManifestMismatch(
                f"checkpoint {ckpt_dir} batch_stats digest mismatch: "
                f"manifest {manifest['batch_stats_digest']}, restored "
                f"{got}")
    if pc_config is not None and "pc_config_sha256" in manifest:
        got = config_sha256(pc_config)
        if got != manifest["pc_config_sha256"]:
            raise ManifestMismatch(
                f"checkpoint {ckpt_dir} was trained with a different "
                f"probability-model config (manifest pc hash "
                f"{manifest['pc_config_sha256']}, loader built {got}) — "
                f"its entropy streams would not decode against this "
                f"model")
    if buckets is not None and manifest.get("buckets") is not None:
        want_b = [list(b) for b in manifest["buckets"]]
        got_b = [list(b) for b in buckets]
        if want_b != got_b:
            raise ManifestMismatch(
                f"checkpoint {ckpt_dir} was published for bucket ladder "
                f"{want_b}, this service runs {got_b} — a swapped-in "
                f"model must serve the SAME ladder or routed streams "
                f"break")
    return {"status": "verified", "manifest": manifest}


def verify_files(ckpt_dir: str,
                 manifest: Dict[str, Any]) -> Dict[str, int]:
    """CRC-check every payload file the manifest lists against the bytes
    on disk at `ckpt_dir`. Returns {"files": n, "bytes": total}; any
    size/CRC disagreement raises typed IntegrityError."""
    files = manifest.get("files") or {}
    total = 0
    for fname, want in files.items():
        path = os.path.join(ckpt_dir, fname)
        try:
            with open(path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            raise IntegrityError(
                f"checkpoint {ckpt_dir} is missing {fname!r} that its "
                f"manifest lists") from None
        if len(data) != want.get("bytes") or \
                frame_crc(data) != want.get("crc32"):
            raise IntegrityError(
                f"checkpoint file {path} does not match its manifest "
                f"entry (got {len(data)} bytes crc 0x{frame_crc(data):08x}, "
                f"manifest says {want}) — rotted or torn; refusing it")
        total += len(data)
    return {"files": len(files), "bytes": total}


def replicate_checkpoint(ckpt_dir: str, dest_dir: str, *,
                         keep_last: int = 1) -> Dict[str, Any]:
    """Copy the resolved latest checkpoint for `ckpt_dir` (the live dir,
    or the newest complete `.prev-*` after a kill in the swap window) to
    `dest_dir` — a peer-visible path (NFS mount, object-store fuse) a
    second host adopts the SAME versioned model from.

    Every payload byte is CRC-verified against the manifest on BOTH
    sides: the source read (bit rot on the origin) and a read-back of
    the staged copy (corruption in transit / on the destination
    filesystem). The staged dir swaps in with the same rotate+rename
    protocol as save_checkpoint, so a kill mid-replication never leaves
    a torn destination. A manifest-less source is refused typed — an
    unversioned replica defeats the point of replicating."""
    src = latest_checkpoint(ckpt_dir)
    if src is None:
        raise FileNotFoundError(
            f"no complete checkpoint to replicate at {ckpt_dir}")
    manifest = load_manifest(src)
    if manifest is None:
        raise ManifestMismatch(
            f"checkpoint {src} has no manifest — refusing to replicate "
            f"an unversioned checkpoint (a peer host could never verify "
            f"what it adopted)")
    verify_files(src, manifest)

    dest_dir = os.path.abspath(dest_dir)
    parent, name = os.path.split(dest_dir)
    os.makedirs(parent or ".", exist_ok=True)
    for entry in os.listdir(parent):
        if entry.startswith(f"{name}.tmp-"):
            shutil.rmtree(os.path.join(parent, entry), ignore_errors=True)
    tmp = os.path.join(parent, f"{name}.tmp-{os.getpid()}")
    os.makedirs(tmp)
    total = 0
    for fname, want in (manifest.get("files") or {}).items():
        with open(os.path.join(src, fname), "rb") as f:
            data = f.read()
        if frame_crc(data) != want.get("crc32"):
            raise IntegrityError(
                f"source file {os.path.join(src, fname)} changed under "
                f"the replication (crc mismatch vs manifest)")
        dst_path = os.path.join(tmp, fname)
        _write_bytes_durable(dst_path, data)
        with open(dst_path, "rb") as f:
            back = f.read()
        if frame_crc(back) != want.get("crc32"):
            raise IntegrityError(
                f"replicated file {dst_path} failed its read-back CRC — "
                f"the copy corrupted in transit")
        total += len(data)
    # manifest then meta last, mirroring save_checkpoint's completeness
    # ordering (meta present => everything it names present)
    for fname in (MANIFEST_NAME, "meta.json"):
        with open(os.path.join(src, fname), "rb") as f:
            _write_bytes_durable(os.path.join(tmp, fname), f.read())
    _fsync_dir(tmp)
    if os.path.isdir(dest_dir):
        prevs = _prev_dirs(parent, name)
        next_idx = (int(os.path.basename(prevs[-1]).rsplit("-", 1)[1]) + 1
                    if prevs else 1)
        os.rename(dest_dir,
                  os.path.join(parent, f"{name}.prev-{next_idx:06d}"))
        faults.inject("ckpt.swap")
    os.rename(tmp, dest_dir)
    _fsync_dir(parent)
    for old in _prev_dirs(parent, name)[:-keep_last if keep_last else None]:
        shutil.rmtree(old, ignore_errors=True)
    return {"src": src, "dest": dest_dir,
            "files": len(manifest.get("files") or {}), "bytes": total,
            "params_digest": manifest.get("params_digest")}


def _dir_bytes(path: str) -> int:
    total = 0
    for root, _dirs, files in os.walk(path):
        for fname in files:
            try:
                total += os.path.getsize(os.path.join(root, fname))
            except OSError:
                pass
    return total


def gc_checkpoints(root: str, referenced: Iterable[str], *,
                   keep_latest: int = 1, dry_run: bool = False,
                   refresh: Optional[Any] = None) -> Dict[str, Any]:
    """Manifest-driven checkpoint garbage collection (ISSUE 14): retire
    checkpoint directories under `root` whose `params_digest` NO fleet
    member references.

    `referenced` is the set of digests that must survive — every fleet
    member's live, staged, AND prev bundle digests (tools/ckpt_gc.py
    gathers them from a router's aggregated /metrics; the prev slot
    counts because rollback re-instates it WARM from memory but a
    restarted replica can only re-load it from disk). The contract:

    * a dir without a parseable manifest is NEVER deleted — GC only
      retires what it can positively identify (legacy/foreign dirs are
      reported, not reaped);
    * the `keep_latest` newest complete checkpoints (by meta step, then
      mtime) survive regardless of references — the operator's re-swap
      ladder;
    * `refresh`, when given, is a zero-arg callable returning the
      CURRENT referenced set, re-polled immediately before EACH
      deletion. This closes the kill window between the initial listing
      and the rm: a digest that becomes referenced mid-GC (a fleet
      prepare staging exactly the candidate this GC was about to
      delete) is re-checked at the last moment and kept. A refresh
      that RAISES or returns None means the reference source went
      unreachable at the deletion edge — the dir is KEPT (fail toward
      keeping, matching the tool's refusal to GC blind), never deleted
      against a stale set. The window is narrowed, not zero — the
      authoritative guard is that publishers never re-publish a
      retired digest path;
    * `.tmp-*` staging siblings are left alone (an in-flight
      save_checkpoint owns them; it sweeps its own stale ones);
      rotated `.prev-*` siblings ARE candidates like any other dir.

    Returns {"scanned", "kept", "retired", "unidentified",
    "bytes_freed", "dry_run"}; `dry_run` reports without deleting."""
    root = os.path.abspath(root)
    referenced_set = {d for d in referenced if d}
    try:
        entries = sorted(os.listdir(root))
    except OSError:
        return {"scanned": 0, "kept": [], "retired": [],
                "unidentified": [], "bytes_freed": 0,
                "dry_run": bool(dry_run)}
    candidates = []       # (sort_key, path, digest)
    unidentified = []
    for entry in entries:
        path = os.path.join(root, entry)
        if not os.path.isdir(path):
            continue
        if ".tmp-" in entry:
            continue      # an in-flight save owns its staging dir
        try:
            manifest = load_manifest(path)
        except IntegrityError:
            manifest = None
        digest = (manifest or {}).get("params_digest")
        if not digest:
            unidentified.append(entry)
            continue
        step = (manifest or {}).get("step", -1)
        try:
            mtime = os.path.getmtime(path)
        except OSError:
            mtime = 0.0
        candidates.append(((step, mtime), path, digest))
    candidates.sort(key=lambda c: c[0], reverse=True)   # newest first
    kept, retired = [], []
    bytes_freed = 0
    for i, (_key, path, digest) in enumerate(candidates):
        name = os.path.basename(path)
        if i < max(0, int(keep_latest)):
            kept.append({"dir": name, "digest": digest,
                         "why": "keep_latest"})
            continue
        if digest in referenced_set:
            kept.append({"dir": name, "digest": digest,
                         "why": "referenced"})
            continue
        if refresh is not None:
            # the kill-window re-check: the fleet may have staged this
            # very digest since the listing — ask again, NOW, before
            # the irreversible step. An unreachable source here KEEPS
            # the dir: deleting against a stale set is exactly the
            # blind GC the initial scrape refuses.
            try:
                fresh = refresh()
            except Exception:   # noqa: BLE001 — fail toward keeping
                fresh = None
            if fresh is None:
                kept.append({"dir": name, "digest": digest,
                             "why": "reference_source_unreachable"})
                continue
            referenced_set |= {d for d in fresh if d}
            if digest in referenced_set:
                kept.append({"dir": name, "digest": digest,
                             "why": "referenced_at_delete"})
                continue
        size = _dir_bytes(path)
        if not dry_run:
            shutil.rmtree(path, ignore_errors=True)
        retired.append({"dir": name, "digest": digest, "bytes": size})
        bytes_freed += size
    return {"scanned": len(candidates), "kept": kept,
            "retired": retired, "unidentified": unidentified,
            "bytes_freed": bytes_freed, "dry_run": bool(dry_run)}


def restore_partitions(ckpt_dir: str, state, partitions: Iterable[str],
                       *, load_opt_state: bool = False,
                       load_batch_stats: bool = True):
    """Restore the named param partitions into `state`, leaving the rest at
    their current (usually freshly-initialized) values. Returns a new state.

    Missing partition files raise FileNotFoundError — restoring 'sinet' from
    an AE_only checkpoint is a real error, as in the reference where the
    Saver would fail on absent variables.
    """
    params = dict(state.params)
    for part in partitions:
        path = os.path.join(ckpt_dir, f"params_{part}.msgpack")
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"checkpoint {ckpt_dir} has no partition {part!r}")
        params[part] = _restore_like(state.params[part], _read_msgpack(path))

    batch_stats = state.batch_stats
    if load_batch_stats:
        bs_path = os.path.join(ckpt_dir, "batch_stats.msgpack")
        if os.path.exists(bs_path):
            batch_stats = _restore_like(state.batch_stats,
                                        _read_msgpack(bs_path))

    opt_state = state.opt_state
    step = state.step
    if load_opt_state:
        opt_state = _restore_like(state.opt_state, _read_msgpack(
            os.path.join(ckpt_dir, "opt_state.msgpack")))
        step = jax.numpy.asarray(load_meta(ckpt_dir)["step"],
                                 dtype=state.step.dtype)

    return state.replace(params=params, batch_stats=batch_stats,
                         opt_state=opt_state, step=step)


def restore_for_mode(ckpt_dir: str, state, ae_config):
    """Reference AE.load_model mode logic (reference AE.py:158-175):

    * always restore the AE partitions (encoder/decoder/centers/probclass);
    * `load_train_step`  -> + optimizer state (+ siNet when not AE_only,
      i.e. resuming SI training);
    * test-only SI run   -> + siNet.
    """
    parts = list(AE_PARTITIONS)
    load_opt = bool(ae_config.load_train_step)
    ae_only = bool(ae_config.AE_only)
    if load_opt and not ae_only:
        parts.append("sinet")
    elif (ae_config.test_model and not ae_config.train_model
          and not ae_only):
        parts.append("sinet")
    return restore_partitions(ckpt_dir, state, parts,
                              load_opt_state=load_opt)


def write_sidecars(root: str, model_name: str, ae_config, pc_config,
                   iteration: int, total_iterations: int,
                   best_val: float) -> None:
    """`last_saved_*.txt` + `configs_*.txt` sidecars (reference main.py:153-163)."""
    os.makedirs(root, exist_ok=True)
    with open(os.path.join(root, f"last_saved_{model_name}.txt"), "w") as f:
        f.write(f"{os.path.join(root, model_name)}\n"
                f"last saved iteration number: {iteration}/{total_iterations}\n"
                f"last saved val loss: {best_val}")
    cfg_path = os.path.join(root, f"configs_{model_name}.txt")
    if not os.path.exists(cfg_path):
        with open(cfg_path, "w") as f:
            f.write("#  ae configs:\n" + str(ae_config))
            f.write("\n\n#  pc configs:\n" + str(pc_config))


def model_name_for(ae_config, timestamp: str) -> str:
    """'target_bpp<bpp>_<AE_only_|sinet_><ts>' (reference main.py:141-149)."""
    target_bpp = ae_config.H_target / (64.0 / ae_config.num_chan_bn)
    mode = "_AE_only_" if ae_config.AE_only else "_sinet_"
    return f"target_bpp{target_bpp}{mode}{timestamp}"
