"""Partitioned checkpointing with partial-restore semantics.

Capability parity with the reference's scope-filtered `tf.train.Saver`
workflow (reference AE.py:154-175 + main.py:141-165), which enables the
3-phase DSIN recipe:
  (a) train AE_only              -> save ae partitions
  (b) fresh siNet, frozen-ish AE -> restore ae only, train SI
  (c) inference                  -> restore ae + sinet
and `load_train_step` additionally restores optimizer state + step counter.

Design: each partition is serialized independently (flax msgpack) inside a
checkpoint directory, so a restore can pick any subset; a `meta.json`
records step/best-val, and the config snapshot + `last_saved` sidecars match
the reference's text files. Directory layout:

    <dir>/
      params_encoder.msgpack     params_decoder.msgpack
      params_centers.msgpack     params_probclass.msgpack
      params_sinet.msgpack       batch_stats.msgpack
      opt_state.msgpack          meta.json

Durability (ISSUE 3): `save_checkpoint` never touches the live directory.
Everything is staged into a fsynced `<dir>.tmp-<pid>` sibling, the live
dir is rotated aside to `<dir>.prev-NNNNNN`, and the staged dir takes its
place — both steps are single atomic renames, so a kill at ANY point
leaves either the old or the new checkpoint complete (and
`latest_checkpoint` resolves whichever survives). Transient OSErrors on
the staging writes retry with the shared bounded policy (utils/retry.py);
`keep_last` bounds the rotated history. Fault-injection sites
`ckpt.write` (every staged file write) and `ckpt.swap` (the window
between the two renames) let the chaos tests kill a save at every
crash point (utils/faults.py).
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any, Dict, Iterable, List, Optional

import flax.serialization
import jax
import numpy as np

from dsin_tpu.utils import faults
from dsin_tpu.utils.retry import RetryPolicy, call_with_retry

AE_PARTITIONS = ("encoder", "decoder", "centers", "probclass")

#: bounded retry for transient write failures (EIO on flaky NFS, EAGAIN);
#: persistent failures still propagate after the third attempt
WRITE_RETRY = RetryPolicy(max_attempts=3, base_delay_s=0.05,
                          max_delay_s=0.5)


def _to_host(tree):
    return jax.tree_util.tree_map(lambda a: np.asarray(a), tree)


def _fsync_dir(path: str) -> None:
    """Flush a directory's entry table; best-effort where dirs can't be
    opened (non-POSIX filesystems)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _write_bytes_durable(path: str, data: bytes) -> None:
    """write + flush + fsync, with bounded retry on transient OSError.
    Each attempt revisits the `ckpt.write` fault site."""

    def _attempt():
        faults.inject("ckpt.write")
        with open(path, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())

    call_with_retry(_attempt, WRITE_RETRY, retry_on=(OSError,))


def _write_msgpack(path: str, tree) -> None:
    # to_state_dict first: opt_state holds optax NamedTuple/dataclass nodes
    # (e.g. multi_transform's PartitionState) that msgpack can't serialize raw
    state = flax.serialization.to_state_dict(_to_host(tree))
    _write_bytes_durable(path, flax.serialization.msgpack_serialize(state))


def _read_msgpack(path: str):
    with open(path, "rb") as f:
        return flax.serialization.msgpack_restore(f.read())


def _restore_like(template, loaded):
    """Shape the raw msgpack dict back into the template's pytree types."""
    return flax.serialization.from_state_dict(template, loaded)


def _prev_dirs(parent: str, name: str) -> List[str]:
    """Rotated `<name>.prev-NNNNNN` siblings, oldest first."""
    prefix = f"{name}.prev-"
    try:
        entries = os.listdir(parent)
    except OSError:
        return []
    return sorted(os.path.join(parent, e) for e in entries
                  if e.startswith(prefix))


def _rescue_nested_dirs(src_dir: str, live_dir: str) -> None:
    """Move foreign SUBDIRECTORIES (nested periodic/emergency
    checkpoints — a checkpoint's own payload is files-only) out of a
    rotated-aside dir into the live dir. The live dir's copy, when one
    exists, is newer (it was carried at swap time) and wins."""
    try:
        entries = os.listdir(src_dir)
    except OSError:
        return
    moved = False
    for entry in entries:
        src = os.path.join(src_dir, entry)
        dst = os.path.join(live_dir, entry)
        if os.path.isdir(src) and not os.path.exists(dst):
            try:
                os.rename(src, dst)
                moved = True
            except OSError:
                pass   # cross-device or racing saver: leave it in place
    if moved:
        _fsync_dir(live_dir)


def save_checkpoint(ckpt_dir: str, state, *, best_val: Optional[float] = None,
                    extra_meta: Optional[Dict[str, Any]] = None,
                    keep_last: int = 1) -> None:
    """Save a TrainState (params/batch_stats/opt_state/step) partitioned,
    durably: the live dir is replaced only by a complete, fsynced copy.

    The v0 scheme overwrote the live dir in place (meta removed first,
    rewritten last) — a torn write was non-DISCOVERABLE, but a kill
    mid-save still destroyed the only resumable state of a long run.
    Now every kill point keeps a complete checkpoint on disk:

      kill during staging   -> live dir untouched (the stale tmp sibling
                               is swept by the next save);
      kill between renames  -> live dir briefly absent, but the newest
                               `<dir>.prev-*` is complete —
                               `latest_checkpoint` resolves it;
      kill after the swap   -> new live dir complete.

    `keep_last` bounds how many rotated `.prev-*` dirs survive.
    Concurrent saves into one `ckpt_dir` are not supported (they never
    were); distinct dirs are independent."""
    ckpt_dir = os.path.abspath(ckpt_dir)
    parent, name = os.path.split(ckpt_dir)
    os.makedirs(parent or ".", exist_ok=True)
    # sweep stale tmp dirs from earlier killed saves (any pid — a live
    # concurrent saver to the same dir is unsupported, see docstring)
    for entry in os.listdir(parent):
        if entry.startswith(f"{name}.tmp-"):
            shutil.rmtree(os.path.join(parent, entry), ignore_errors=True)

    tmp = os.path.join(parent, f"{name}.tmp-{os.getpid()}")
    os.makedirs(tmp)
    for part, sub in state.params.items():
        _write_msgpack(os.path.join(tmp, f"params_{part}.msgpack"), sub)
    _write_msgpack(os.path.join(tmp, "batch_stats.msgpack"),
                   state.batch_stats)
    _write_msgpack(os.path.join(tmp, "opt_state.msgpack"),
                   state.opt_state)
    meta = {"step": int(state.step),
            "partitions": sorted(state.params.keys())}
    if best_val is not None:
        meta["best_val"] = float(best_val)
    if extra_meta:
        meta.update(extra_meta)
    _write_bytes_durable(os.path.join(tmp, "meta.json"),
                         json.dumps(meta, indent=2).encode())
    _fsync_dir(tmp)

    if os.path.isdir(ckpt_dir):
        prevs = _prev_dirs(parent, name)
        next_idx = (int(os.path.basename(prevs[-1]).rsplit("-", 1)[1]) + 1
                    if prevs else 1)
        os.rename(ckpt_dir, os.path.join(parent,
                                         f"{name}.prev-{next_idx:06d}"))
        faults.inject("ckpt.swap")    # the kill window between renames
    os.rename(tmp, ckpt_dir)
    _fsync_dir(parent)
    # a checkpoint's own payload is files-only, so any SUBDIRECTORY in a
    # rotated-aside dir is foreign nested content — e.g. the periodic/
    # and emergency/ checkpoints main.py keeps inside ckpt_dir. Carry it
    # from the NEWEST .prev into the fresh live dir: leaving it there
    # would strand it and the keep_last prune below would silently
    # delete the very saves that bound crash loss. The newest prev is
    # consulted even when THIS save found no live dir to rotate — that
    # is exactly the resume-after-a-kill-in-the-swap-window state, where
    # the nested content sits in a prev that may be KEPT (not pruned)
    # for several more saves. EVERY prev is swept, newest first (newest
    # copy wins — _rescue only fills absences): with keep_last >= 2 a
    # kill before a previous save's rescue leaves the content in a prev
    # that is neither the newest nor due for pruning.
    for prev in reversed(_prev_dirs(parent, name)):
        _rescue_nested_dirs(prev, ckpt_dir)

    for old in _prev_dirs(parent, name)[:-keep_last if keep_last else None]:
        # rescue again right before deleting: a kill between the swap
        # above and its carry-over leaves nested content only in a
        # .prev dir — the prune must never be the thing that destroys
        # the last copy of a periodic/emergency checkpoint
        _rescue_nested_dirs(old, ckpt_dir)
        shutil.rmtree(old, ignore_errors=True)


def latest_checkpoint(ckpt_dir: str) -> Optional[str]:
    """Resolve the most recent COMPLETE checkpoint for `ckpt_dir`: the
    dir itself when its meta.json exists, else the newest rotated
    `<dir>.prev-*` that has one (the kill-between-renames window), else
    None. Completeness == meta.json present: the staged-swap protocol
    guarantees a dir with meta has every msgpack it names."""
    ckpt_dir = os.path.abspath(ckpt_dir)
    if os.path.exists(os.path.join(ckpt_dir, "meta.json")):
        return ckpt_dir
    parent, name = os.path.split(ckpt_dir)
    for prev in reversed(_prev_dirs(parent, name)):
        if os.path.exists(os.path.join(prev, "meta.json")):
            return prev
    return None


def load_meta(ckpt_dir: str) -> Dict[str, Any]:
    with open(os.path.join(ckpt_dir, "meta.json")) as f:
        return json.load(f)


def restore_partitions(ckpt_dir: str, state, partitions: Iterable[str],
                       *, load_opt_state: bool = False,
                       load_batch_stats: bool = True):
    """Restore the named param partitions into `state`, leaving the rest at
    their current (usually freshly-initialized) values. Returns a new state.

    Missing partition files raise FileNotFoundError — restoring 'sinet' from
    an AE_only checkpoint is a real error, as in the reference where the
    Saver would fail on absent variables.
    """
    params = dict(state.params)
    for part in partitions:
        path = os.path.join(ckpt_dir, f"params_{part}.msgpack")
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"checkpoint {ckpt_dir} has no partition {part!r}")
        params[part] = _restore_like(state.params[part], _read_msgpack(path))

    batch_stats = state.batch_stats
    if load_batch_stats:
        bs_path = os.path.join(ckpt_dir, "batch_stats.msgpack")
        if os.path.exists(bs_path):
            batch_stats = _restore_like(state.batch_stats,
                                        _read_msgpack(bs_path))

    opt_state = state.opt_state
    step = state.step
    if load_opt_state:
        opt_state = _restore_like(state.opt_state, _read_msgpack(
            os.path.join(ckpt_dir, "opt_state.msgpack")))
        step = jax.numpy.asarray(load_meta(ckpt_dir)["step"],
                                 dtype=state.step.dtype)

    return state.replace(params=params, batch_stats=batch_stats,
                         opt_state=opt_state, step=step)


def restore_for_mode(ckpt_dir: str, state, ae_config):
    """Reference AE.load_model mode logic (reference AE.py:158-175):

    * always restore the AE partitions (encoder/decoder/centers/probclass);
    * `load_train_step`  -> + optimizer state (+ siNet when not AE_only,
      i.e. resuming SI training);
    * test-only SI run   -> + siNet.
    """
    parts = list(AE_PARTITIONS)
    load_opt = bool(ae_config.load_train_step)
    ae_only = bool(ae_config.AE_only)
    if load_opt and not ae_only:
        parts.append("sinet")
    elif (ae_config.test_model and not ae_config.train_model
          and not ae_only):
        parts.append("sinet")
    return restore_partitions(ckpt_dir, state, parts,
                              load_opt_state=load_opt)


def write_sidecars(root: str, model_name: str, ae_config, pc_config,
                   iteration: int, total_iterations: int,
                   best_val: float) -> None:
    """`last_saved_*.txt` + `configs_*.txt` sidecars (reference main.py:153-163)."""
    os.makedirs(root, exist_ok=True)
    with open(os.path.join(root, f"last_saved_{model_name}.txt"), "w") as f:
        f.write(f"{os.path.join(root, model_name)}\n"
                f"last saved iteration number: {iteration}/{total_iterations}\n"
                f"last saved val loss: {best_val}")
    cfg_path = os.path.join(root, f"configs_{model_name}.txt")
    if not os.path.exists(cfg_path):
        with open(cfg_path, "w") as f:
            f.write("#  ae configs:\n" + str(ae_config))
            f.write("\n\n#  pc configs:\n" + str(pc_config))


def model_name_for(ae_config, timestamp: str) -> str:
    """'target_bpp<bpp>_<AE_only_|sinet_><ts>' (reference main.py:141-149)."""
    target_bpp = ae_config.H_target / (64.0 / ae_config.num_chan_bn)
    mode = "_AE_only_" if ae_config.AE_only else "_sinet_"
    return f"target_bpp{target_bpp}{mode}{timestamp}"
