"""Partitioned checkpointing with partial-restore semantics.

Capability parity with the reference's scope-filtered `tf.train.Saver`
workflow (reference AE.py:154-175 + main.py:141-165), which enables the
3-phase DSIN recipe:
  (a) train AE_only              -> save ae partitions
  (b) fresh siNet, frozen-ish AE -> restore ae only, train SI
  (c) inference                  -> restore ae + sinet
and `load_train_step` additionally restores optimizer state + step counter.

Design: each partition is serialized independently (flax msgpack) inside a
checkpoint directory, so a restore can pick any subset; a `meta.json`
records step/best-val, and the config snapshot + `last_saved` sidecars match
the reference's text files. Directory layout:

    <dir>/
      params_encoder.msgpack     params_decoder.msgpack
      params_centers.msgpack     params_probclass.msgpack
      params_sinet.msgpack       batch_stats.msgpack
      opt_state.msgpack          meta.json
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Iterable, Optional

import flax.serialization
import jax
import numpy as np

AE_PARTITIONS = ("encoder", "decoder", "centers", "probclass")


def _to_host(tree):
    return jax.tree_util.tree_map(lambda a: np.asarray(a), tree)


def _write_msgpack(path: str, tree) -> None:
    # to_state_dict first: opt_state holds optax NamedTuple/dataclass nodes
    # (e.g. multi_transform's PartitionState) that msgpack can't serialize raw
    state = flax.serialization.to_state_dict(_to_host(tree))
    with open(path, "wb") as f:
        f.write(flax.serialization.msgpack_serialize(state))


def _read_msgpack(path: str):
    with open(path, "rb") as f:
        return flax.serialization.msgpack_restore(f.read())


def _restore_like(template, loaded):
    """Shape the raw msgpack dict back into the template's pytree types."""
    return flax.serialization.from_state_dict(template, loaded)


def save_checkpoint(ckpt_dir: str, state, *, best_val: Optional[float] = None,
                    extra_meta: Optional[Dict[str, Any]] = None) -> None:
    """Save a TrainState (params/batch_stats/opt_state/step) partitioned.

    Overwrite ordering makes a torn write non-discoverable instead of
    silently corrupt: meta.json is removed FIRST and rewritten LAST, so a
    kill mid-overwrite (e.g. the relay watcher's kill-after escalation)
    leaves a dir without meta — which `load_meta`-driven discovery
    (resume, `_latest_resumable`) skips — never a dir whose old meta
    points at half-written msgpacks."""
    os.makedirs(ckpt_dir, exist_ok=True)
    meta_path = os.path.join(ckpt_dir, "meta.json")
    if os.path.exists(meta_path):
        os.remove(meta_path)
    for part, sub in state.params.items():
        _write_msgpack(os.path.join(ckpt_dir, f"params_{part}.msgpack"), sub)
    _write_msgpack(os.path.join(ckpt_dir, "batch_stats.msgpack"),
                   state.batch_stats)
    _write_msgpack(os.path.join(ckpt_dir, "opt_state.msgpack"),
                   state.opt_state)
    meta = {"step": int(state.step),
            "partitions": sorted(state.params.keys())}
    if best_val is not None:
        meta["best_val"] = float(best_val)
    if extra_meta:
        meta.update(extra_meta)
    with open(os.path.join(ckpt_dir, "meta.json"), "w") as f:
        json.dump(meta, f, indent=2)


def load_meta(ckpt_dir: str) -> Dict[str, Any]:
    with open(os.path.join(ckpt_dir, "meta.json")) as f:
        return json.load(f)


def restore_partitions(ckpt_dir: str, state, partitions: Iterable[str],
                       *, load_opt_state: bool = False,
                       load_batch_stats: bool = True):
    """Restore the named param partitions into `state`, leaving the rest at
    their current (usually freshly-initialized) values. Returns a new state.

    Missing partition files raise FileNotFoundError — restoring 'sinet' from
    an AE_only checkpoint is a real error, as in the reference where the
    Saver would fail on absent variables.
    """
    params = dict(state.params)
    for part in partitions:
        path = os.path.join(ckpt_dir, f"params_{part}.msgpack")
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"checkpoint {ckpt_dir} has no partition {part!r}")
        params[part] = _restore_like(state.params[part], _read_msgpack(path))

    batch_stats = state.batch_stats
    if load_batch_stats:
        bs_path = os.path.join(ckpt_dir, "batch_stats.msgpack")
        if os.path.exists(bs_path):
            batch_stats = _restore_like(state.batch_stats,
                                        _read_msgpack(bs_path))

    opt_state = state.opt_state
    step = state.step
    if load_opt_state:
        opt_state = _restore_like(state.opt_state, _read_msgpack(
            os.path.join(ckpt_dir, "opt_state.msgpack")))
        step = jax.numpy.asarray(load_meta(ckpt_dir)["step"],
                                 dtype=state.step.dtype)

    return state.replace(params=params, batch_stats=batch_stats,
                         opt_state=opt_state, step=step)


def restore_for_mode(ckpt_dir: str, state, ae_config):
    """Reference AE.load_model mode logic (reference AE.py:158-175):

    * always restore the AE partitions (encoder/decoder/centers/probclass);
    * `load_train_step`  -> + optimizer state (+ siNet when not AE_only,
      i.e. resuming SI training);
    * test-only SI run   -> + siNet.
    """
    parts = list(AE_PARTITIONS)
    load_opt = bool(ae_config.load_train_step)
    ae_only = bool(ae_config.AE_only)
    if load_opt and not ae_only:
        parts.append("sinet")
    elif (ae_config.test_model and not ae_config.train_model
          and not ae_only):
        parts.append("sinet")
    return restore_partitions(ckpt_dir, state, parts,
                              load_opt_state=load_opt)


def write_sidecars(root: str, model_name: str, ae_config, pc_config,
                   iteration: int, total_iterations: int,
                   best_val: float) -> None:
    """`last_saved_*.txt` + `configs_*.txt` sidecars (reference main.py:153-163)."""
    os.makedirs(root, exist_ok=True)
    with open(os.path.join(root, f"last_saved_{model_name}.txt"), "w") as f:
        f.write(f"{os.path.join(root, model_name)}\n"
                f"last saved iteration number: {iteration}/{total_iterations}\n"
                f"last saved val loss: {best_val}")
    cfg_path = os.path.join(root, f"configs_{model_name}.txt")
    if not os.path.exists(cfg_path):
        with open(cfg_path, "w") as f:
            f.write("#  ae configs:\n" + str(ae_config))
            f.write("\n\n#  pc configs:\n" + str(pc_config))


def model_name_for(ae_config, timestamp: str) -> str:
    """'target_bpp<bpp>_<AE_only_|sinet_><ts>' (reference main.py:141-149)."""
    target_bpp = ae_config.H_target / (64.0 / ae_config.num_chan_bn)
    mode = "_AE_only_" if ae_config.AE_only else "_sinet_"
    return f"target_bpp{target_bpp}{mode}{timestamp}"
