"""Config DSL: attribute-style configs parsed from simple text files.

Provides the capability the reference gets from `fjcommon.config_parser`
(reference main.py:13,184-185): files of ``key = <python literal>`` lines,
optional ``constrain key :: A, B, ...`` enum-validation lines, ``#`` comments,
and a text snapshot (str(config)) persisted beside checkpoints
(reference main.py:159-163).

Grammar (one statement per line):
    # comment                      -- ignored (also inline after values)
    key = <python literal>         -- evaluated with ast.literal_eval; a bare
                                      identifier on the RHS is kept as a string
                                      (the reference DSL allows e.g. `arch = CVPR`)
    constrain key :: A, B, C       -- when `key` is later assigned, its value
                                      must be one of the listed tokens
"""

from __future__ import annotations

import ast
import re
from typing import Any, Dict, Iterable, Optional, Tuple


class ConfigError(ValueError):
    pass


class Config:
    """Attribute-style config holding parsed key/value pairs.

    str(config) produces a canonical snapshot that `parse_config` can re-read.
    """

    def __init__(self, values: Optional[Dict[str, Any]] = None,
                 constraints: Optional[Dict[str, Tuple[str, ...]]] = None,
                 name: str = "config"):
        object.__setattr__(self, "_values", dict(values or {}))
        object.__setattr__(self, "_constraints", dict(constraints or {}))
        object.__setattr__(self, "_name", name)

    # -- attribute protocol ---------------------------------------------------
    def __getattr__(self, key: str) -> Any:
        try:
            return object.__getattribute__(self, "_values")[key]
        except KeyError:
            raise AttributeError(
                f"config {self._name!r} has no key {key!r}; "
                f"known keys: {sorted(self._values)}") from None

    def __setattr__(self, key: str, value: Any) -> None:
        self.set(key, value)

    def set(self, key: str, value: Any) -> None:
        allowed = self._constraints.get(key)
        if allowed is not None and value not in allowed:
            raise ConfigError(
                f"config {self._name!r}: {key} = {value!r} violates "
                f"constraint :: {', '.join(map(str, allowed))}")
        self._values[key] = value

    # -- dict-ish helpers -----------------------------------------------------
    def get(self, key: str, default: Any = None) -> Any:
        return self._values.get(key, default)

    def keys(self) -> Iterable[str]:
        return self._values.keys()

    def to_dict(self) -> Dict[str, Any]:
        return dict(self._values)

    def replace(self, **updates: Any) -> "Config":
        """Return a copy with `updates` applied (constraints enforced)."""
        out = Config(self._values, self._constraints, self._name)
        for k, v in updates.items():
            out.set(k, v)
        return out

    def __contains__(self, key: str) -> bool:
        return key in self._values

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Config) and other._values == self._values

    def __repr__(self) -> str:
        return f"Config({self._name!r}, {len(self._values)} keys)"

    def __str__(self) -> str:
        """Canonical re-parseable snapshot."""
        lines = []
        for key, allowed in sorted(self._constraints.items()):
            lines.append(f"constrain {key} :: {', '.join(map(str, allowed))}")
        for key, value in self._values.items():
            lines.append(f"{key} = {value!r}")
        return "\n".join(lines) + "\n"


_CONSTRAIN_RE = re.compile(r"^constrain\s+(\w+)\s*::\s*(.*)$")
_ASSIGN_RE = re.compile(r"^(\w+)\s*=\s*(.*)$")
_IDENT_RE = re.compile(r"^[A-Za-z_]\w*$")


def _strip_comment(line: str) -> str:
    """Remove a trailing ``#`` comment that is not inside a string literal."""
    out = []
    quote = None
    escaped = False
    for ch in line:
        if quote:
            out.append(ch)
            if escaped:
                escaped = False
            elif ch == "\\":
                escaped = True
            elif ch == quote:
                quote = None
        elif ch in "'\"":
            quote = ch
            out.append(ch)
        elif ch == "#":
            break
        else:
            out.append(ch)
    return "".join(out)


def _eval_rhs(rhs: str, key: str, lineno: int) -> Any:
    rhs = rhs.strip()
    if not rhs:
        raise ConfigError(f"line {lineno}: empty value for {key!r}")
    # trailing comma tuples like `A, B,` -> try literal_eval as-is first
    try:
        return ast.literal_eval(rhs)
    except (ValueError, SyntaxError):
        pass
    # arithmetic on literals (the reference writes `H_target = 2*0.02`)
    try:
        node = ast.parse(rhs, mode="eval")
        if _is_const_expr(node.body):
            return eval(compile(node, "<config>", "eval"), {"__builtins__": {}}, {})
    except SyntaxError:
        pass
    # non-finite floats (so snapshots of inf/nan reload with their type intact)
    low = rhs.lower()
    if low in ("inf", "-inf", "nan"):
        return float(low)
    # bare identifier -> string enum token
    if _IDENT_RE.match(rhs):
        return rhs
    raise ConfigError(f"line {lineno}: cannot parse value for {key!r}: {rhs!r}")


def _is_const_expr(node: ast.AST) -> bool:
    """True when the expression is built only from literals and arithmetic."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.Add, ast.Sub, ast.Mult, ast.Div, ast.FloorDiv,
                      ast.Pow, ast.Mod)):
        return _is_const_expr(node.left) and _is_const_expr(node.right)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, (ast.USub, ast.UAdd)):
        return _is_const_expr(node.operand)
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(_is_const_expr(e) for e in node.elts)
    return False


def parse_config(text: str, name: str = "config") -> Config:
    values: Dict[str, Any] = {}
    constraints: Dict[str, Tuple[str, ...]] = {}
    for lineno, raw in enumerate(text.splitlines(), start=1):
        line = _strip_comment(raw).strip()
        if not line:
            continue
        m = _CONSTRAIN_RE.match(line)
        if m:
            key, tokens = m.group(1), m.group(2)
            # each token is parsed like a value, so numeric enums
            # (`constrain n :: 4, 6`) compare against parsed assignments
            allowed = tuple(_eval_rhs(t.strip(), key, lineno)
                            for t in tokens.split(",") if t.strip())
            if not allowed:
                raise ConfigError(f"line {lineno}: empty constraint for {key!r}")
            constraints[key] = allowed
            if key in values and values[key] not in allowed:
                raise ConfigError(
                    f"line {lineno}: existing value {values[key]!r} for {key!r} "
                    f"violates new constraint")
            continue
        m = _ASSIGN_RE.match(line)
        if m:
            key, rhs = m.group(1), m.group(2)
            value = _eval_rhs(rhs, key, lineno)
            allowed = constraints.get(key)
            if allowed is not None and value not in allowed:
                raise ConfigError(
                    f"line {lineno}: {key} = {value!r} violates constraint "
                    f":: {', '.join(map(str, allowed))}")
            values[key] = value
            continue
        raise ConfigError(f"line {lineno}: cannot parse: {raw!r}")
    return Config(values, constraints, name)


def parse_config_file(path: str) -> Config:
    with open(path) as f:
        return parse_config(f.read(), name=path)
