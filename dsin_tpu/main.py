"""Train / validate / test orchestration + CLI.

Capability parity with the reference driver (reference main.py): parse the
two config files, build the model + datasets, run the fetch→step training
loop with periodic validation (the validation interval shrinks in the last
half of training), keep the best-val checkpoint with config/last-saved
sidecars, and on `test_model` run the test split through full inference,
dumping reconstruction PNGs and per-image score lists.

TPU-first differences from the reference:
  * one jitted train step (no 3x sess.run round trips), donated state;
  * data-parallel over every local device via a `jax.sharding.Mesh` when
    the batch is shardable (the reference is strictly single-GPU);
  * observability the reference lacks: images/sec, JSONL scalar logs,
    device memory stats (dsin_tpu.utils).

CLI (reference main.py:214-224):
    python -m dsin_tpu.main -ae_config <path> -pc_config <path> \
        [--out_root DIR] [--data_root DIR] [--max_steps N]
"""

from __future__ import annotations

import argparse
import collections
import os
import time
from typing import Dict, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dsin_tpu.config import Config, parse_config_file
from dsin_tpu.data.loader import PairDataset, Prefetcher
from dsin_tpu.data.manifest import read_pair_manifest
from dsin_tpu.models.dsin import DSIN
from dsin_tpu.ops.sifinder import gaussian_position_mask
from dsin_tpu.train import checkpoint as ckpt_lib
from dsin_tpu.train import optim as optim_lib
from dsin_tpu.train import step as step_lib
from dsin_tpu.utils import (JsonlLogger, StepProfiler, StepTimer,
                            color_print, install_interrupt_handlers)


def get_validate_every(iteration: int, total_iterations: int,
                       validate_every: int,
                       decrease_val_steps: bool) -> int:
    """Validation interval shrinks as training converges: /2 after half the
    iterations, /4 after three quarters (reference main.py:129-138) — late
    improvements are rarer, so best-val checkpointing needs finer sampling."""
    if not decrease_val_steps:
        return validate_every
    if iteration >= (3 * total_iterations) // 4:
        return max(validate_every // 4, 1)
    if iteration >= total_iterations // 2:
        return max(validate_every // 2, 1)
    return validate_every


class Experiment:
    """Owns model, train state, jitted steps, and datasets for one run."""

    def __init__(self, ae_config: Config, pc_config: Config,
                 out_root: str = ".", seed: int = 0,
                 use_mesh: Optional[bool] = None,
                 replicate_to: Optional[str] = None):
        self.ae_config = ae_config
        self.pc_config = pc_config
        self.out_root = out_root
        self.seed = seed
        #: peer-visible root for cross-host checkpoint replication
        #: (train/checkpoint.replicate_checkpoint, ISSUE 9 follow-up):
        #: every best-val save is CRC-verified-both-sides copied to
        #: <replicate_to>/<model_name>; None = off
        self.replicate_to = replicate_to
        self.model = DSIN(ae_config, pc_config)

        train_manifest = os.path.join(ae_config.root_data,
                                      ae_config.file_path_train)
        self.num_train_imgs = (
            len(read_pair_manifest(train_manifest, root=ae_config.root_data))
            if os.path.exists(train_manifest) else 1576)

        ch, cw = ae_config.crop_size
        shape = (ae_config.batch_size, ch, cw, 3)
        self.tx = optim_lib.build_optimizer(
            None, ae_config, pc_config, num_training_imgs=self.num_train_imgs)
        self.state = step_lib.create_train_state(
            self.model, jax.random.PRNGKey(seed), shape, self.tx)

        ph, pw = ae_config.y_patch_size
        self.train_mask = (jnp.asarray(gaussian_position_mask(ch, cw, ph, pw))
                           if ae_config.use_gauss_mask else None)
        eh, ew = ae_config.get("eval_crop_size", ae_config.crop_size)
        self.eval_mask = (jnp.asarray(gaussian_position_mask(eh, ew, ph, pw))
                          if ae_config.use_gauss_mask else None)

        n_dev = jax.local_device_count()
        spatial = int(ae_config.get("spatial_shards", 1))
        grad_accum = int(ae_config.get("grad_accum_steps", 1) or 1)
        if grad_accum > 1:
            color_print(
                f"grad_accum_steps={grad_accum}: BatchNorm statistics and "
                f"the rate hinge are evaluated per micro-batch (see "
                f"train/step.py docstring for when this differs from the "
                f"full-batch step)", "yellow")
        if use_mesh is None:
            use_mesh = (spatial > 1
                        or (n_dev > 1 and ae_config.batch_size % n_dev == 0))
        self.mesh = None
        if use_mesh and spatial > 1:
            if spatial > jax.device_count():
                raise ValueError(
                    f"spatial_shards={spatial} exceeds the "
                    f"{jax.device_count()} available devices")
            # width-sharded training over a (data, spatial) mesh: the
            # large-extent path — crops whose activations/score map exceed
            # one chip (SURVEY §5). Requires not AE_only (the sharded
            # search is the point) and divisibilities checked downstream.
            from dsin_tpu.parallel import data_parallel as dp
            from dsin_tpu.parallel import mesh as mesh_lib
            # data axis sized to the batch: the largest divisor of
            # batch_size that fits alongside the spatial axis
            max_data = max(jax.device_count() // spatial, 1)
            data_par = max(d for d in range(1, max_data + 1)
                           if ae_config.batch_size % d == 0)
            self.mesh = mesh_lib.make_mesh(num_devices=data_par * spatial,
                                           spatial=spatial)
            color_print(
                f"mesh: data={data_par} x spatial={spatial} "
                f"({data_par * spatial}/{jax.device_count()} devices; "
                f"data axis auto-sized to the largest divisor of "
                f"batch_size={ae_config.batch_size} that fits the "
                f"remaining devices)", "yellow")
            self.state = mesh_lib.replicate_state(self.mesh, self.state)
            self.train_step = dp.make_spatial_train_step(
                self.model, self.tx, self.mesh, ch, cw,
                grad_accum=grad_accum)
            self.val_step = dp.make_spatial_eval_step(
                self.model, self.mesh, ch, cw)
            self._put = lambda x, y: mesh_lib.shard_images(self.mesh, x, y)
        elif use_mesh:
            from dsin_tpu.parallel import data_parallel as dp
            from dsin_tpu.parallel import mesh as mesh_lib
            self.mesh = mesh_lib.make_mesh()
            self.state = mesh_lib.replicate_state(self.mesh, self.state)
            self.train_step = dp.make_sharded_train_step(
                self.model, self.tx, self.mesh, si_mask=self.train_mask,
                grad_accum=grad_accum)
            self.val_step = dp.make_sharded_eval_step(
                self.model, self.mesh, si_mask=self.train_mask)
            self._put = lambda x, y: mesh_lib.shard_batch(self.mesh, x, y)
        else:
            self.train_step = step_lib.make_train_step(
                self.model, self.tx, si_mask=self.train_mask,
                grad_accum=grad_accum)
            self.val_step = step_lib.make_eval_step(
                self.model, si_mask=self.train_mask)
            self._put = lambda x, y: (jnp.asarray(x), jnp.asarray(y))
        self.infer_step = step_lib.make_inference_step(
            self.model, si_mask=self.eval_mask)

        stamp = time.strftime("%Y%m%d_%H%M%S")
        self.model_name = ckpt_lib.model_name_for(ae_config, stamp)
        self.weights_root = os.path.join(out_root, "weights")
        self.ckpt_dir = os.path.join(self.weights_root, self.model_name)
        self.images_dir = os.path.join(out_root, "images", self.model_name)

    # -- data ---------------------------------------------------------------

    def _dataset(self, split: str, train: bool) -> PairDataset:
        cfg = self.ae_config
        manifest = os.path.join(cfg.root_data,
                                getattr(cfg, f"file_path_{split}"))
        pairs = read_pair_manifest(manifest, root=cfg.root_data)
        crop = (cfg.crop_size if train or split == "val"
                else cfg.get("eval_crop_size", cfg.crop_size))
        return PairDataset(
            pairs, crop_size=crop,
            batch_size=cfg.batch_size if train or split == "val" else 1,
            train=train, num_crops_per_img=cfg.num_crops_per_img,
            do_flips=cfg.get("do_flips", True),
            host_id=jax.process_index(), num_hosts=jax.process_count())

    # -- restore ------------------------------------------------------------

    def _manifest_extra(self) -> dict:
        """Trainer-side identity for every checkpoint manifest
        (train/checkpoint.py, ISSUE 9): the canonical pc-config hash a
        loader re-derives from its own config (a swapped-in model with
        a different context model is refused before it serves) and the
        init seed (reproducibility bookkeeping)."""
        return {"pc_config_sha256": ckpt_lib.config_sha256(self.pc_config),
                "seed": self.seed}

    def maybe_restore(self) -> None:
        cfg = self.ae_config
        self.restored_best_val = float("inf")
        if not cfg.load_model:
            return
        load_dir = os.path.join(self.weights_root, cfg.load_model_name)
        # a save killed between its swap renames leaves the live dir
        # absent but a complete rotated `.prev-*` behind — resolve
        # whichever complete checkpoint survives (train/checkpoint.py);
        # keep the caller's path when the live dir itself is complete
        if not os.path.exists(os.path.join(load_dir, "meta.json")):
            load_dir = ckpt_lib.latest_checkpoint(load_dir) or load_dir
        self.state = ckpt_lib.restore_for_mode(load_dir, self.state, cfg)
        if cfg.load_train_step:
            # true resume of the same phase: seed best-val tracking from the
            # checkpoint so the first validation isn't always "improved" and
            # doesn't overwrite the true best with a regression. (A phase
            # switch — e.g. AE_only weights warm-starting siNet training —
            # changes the loss composition, so its old best_val is
            # incomparable and stays unused.)
            self.restored_best_val = float(
                ckpt_lib.load_meta(load_dir).get("best_val", float("inf")))
        color_print(f"restored from {load_dir} "
                    f"(step {int(self.state.step)}, "
                    f"best_val {self.restored_best_val})", "green")

    # -- train --------------------------------------------------------------

    def validate(self, val_batches: Iterator, max_batches: Optional[int] = None
                 ) -> float:
        losses = []
        for i, (x, y) in enumerate(val_batches):
            if max_batches is not None and i >= max_batches:
                break
            metrics = self.val_step(self.state, *self._put(x, y))
            losses.append(float(metrics["loss"]))
        if not losses:
            # inf never "improves", so best-val checkpoints silently stop
            # being written — say why (typical cause: a val split smaller
            # than one batch)
            color_print("validation saw ZERO batches (val split smaller "
                        "than batch_size?) — val_loss=inf, no best-val "
                        "checkpoint will be saved", "red")
            return float("inf")
        return float(np.mean(losses))

    def _validate_and_maybe_save(self, i: int, iterations: int,
                                 best_val: float, val_losses, logger,
                                 max_val_batches: Optional[int],
                                 force_save: bool = False) -> float:
        """One validation pass + best-val checkpointing (the scheduled-
        validation body, shared with the rate-target early stop). Returns
        the updated best_val. `force_save=True` writes the checkpoint even
        without improvement — the early stop wants the weights that
        satisfy the rate constraint, improvement or not."""
        cfg = self.ae_config
        with self._dataset("val", train=False) as val_ds:
            val_loss = self.validate(val_ds.batches(loop=False),
                                     max_batches=max_val_batches)
        val_losses.append(val_loss)
        improved = val_loss < best_val
        color_print(f"[{i + 1}] val_loss={val_loss:.4f} "
                    f"(best {min(best_val, val_loss):.4f})",
                    "green" if improved else "yellow")
        logger.log(i + 1, {"val_loss": val_loss})
        if improved:
            best_val = val_loss
        if (improved or force_save) and cfg.get("save_model", True):
            ckpt_lib.save_checkpoint(self.ckpt_dir, self.state,
                                     best_val=best_val,
                                     manifest_extra=self._manifest_extra())
            ckpt_lib.write_sidecars(
                self.weights_root, self.model_name, cfg, self.pc_config,
                iteration=i + 1, total_iterations=iterations,
                best_val=best_val)
            if self.replicate_to:
                # cross-host replica of the just-saved best-val ckpt
                # (manifest-CRC-verified on both sides) — the peer a
                # serving fleet hot-swaps from (ISSUE 9 follow-up)
                ckpt_lib.replicate_checkpoint(
                    self.ckpt_dir,
                    os.path.join(self.replicate_to, self.model_name))
        return best_val

    def train(self, max_steps: Optional[int] = None,
              max_val_batches: Optional[int] = None,
              log_path: Optional[str] = None,
              profile_dir: Optional[str] = None,
              until_rate_target: bool = False,
              rate_window: int = 200) -> Dict[str, float]:
        """The fetch→step→validate loop (reference main.py:49-91). Returns
        summary stats. `max_steps`/`max_val_batches` bound the run (tests,
        smoke runs); None = full config iterations. `profile_dir` captures
        an XLA trace of a few warm steps there.

        `until_rate_target=True` stops early once the codec's defining
        constraint binds: the mean H_soft over the last `rate_window`
        steps <= H_target (reference Distortions_imgcomp.py:118-127 —
        the beta-weighted hinge whose whole purpose is driving H_soft to
        the target). Use for RD-sweep phase-1 runs whose step budget is
        otherwise guesswork; iterations/max_steps still cap the run.

        Metric processing lags dispatch by one step: step i+1 is dispatched
        before step i's metrics are pulled to the host, so host work (batch
        decode, logging, the device->host round trip — tens of ms over the
        axon relay) overlaps device compute instead of serializing with it.
        Consequences: the rate-target stop overshoots by exactly one
        (constrained) step, and a validation/checkpoint at boundary j reads
        the state after step j+1 — both harmless, both covered by tests."""
        if until_rate_target and rate_window < 1:
            raise ValueError(f"rate_window must be >= 1, got {rate_window}")
        # SIGINT may be inherited ignored (async-job launch) and SIGTERM
        # default-kills without unwinding — both must reach the
        # BaseException emergency save below (dsin_tpu/utils/signals.py)
        install_interrupt_handlers()
        cfg = self.ae_config
        # resume iteration numbering from a restored optimizer step — the
        # reference restarts numbering on resume (SURVEY §5); here a resumed
        # run continues the schedule and skips already-done work.
        # `max_steps` counts steps to RUN from here (not a global cap), so
        # smoke-running a restored checkpoint still does work.
        start = min(int(self.state.step), cfg.iterations)
        iterations = (min(cfg.iterations, start + max_steps)
                      if max_steps else cfg.iterations)
        train_it = Prefetcher(self._dataset("train", train=True).batches())
        logger = JsonlLogger(log_path or os.path.join(
            self.out_root, "logs", f"{self.model_name}.jsonl"))
        timer = StepTimer()
        # clamp the trace window into short/resumed runs so --profile_dir
        # always captures something (still skipping compile steps if it can)
        remaining = iterations - start
        profiler = StepProfiler(
            profile_dir, start_step=start + min(5, max(remaining - 3, 0)))
        checkpoint_every = cfg.get("checkpoint_every", None)
        best_val = getattr(self, "restored_best_val", float("inf"))
        accum: Dict[str, float] = {}
        n_accum = 0
        val_losses = []
        h_recent: "collections.deque" = collections.deque(maxlen=rate_window)
        # Divergence guard: stop when val loss sits above
        # divergence_factor x best_val for divergence_patience CONSECUTIVE
        # validations. Training past its best validation is normal noise;
        # a sustained multiple of it is divergence (observed live on the
        # 0.04 pipeline point's phase 2: best_val 24.2 at step 751,
        # 47.7 by 1500 — every post-best step there was wasted compute,
        # and only restore_best_for_test kept it out of the scores). The
        # best-val checkpoint already holds the run's artifact, so
        # stopping loses nothing; divergence_patience=0 disables. The 1.5
        # default factor is set BELOW that observed 1.97x excursion: a
        # guard calibrated at 2.0 would have slept through the exact case
        # that motivated it.
        div_factor = float(cfg.get("divergence_factor", 1.5))
        div_patience = int(cfg.get("divergence_patience", 3) or 0)
        div_bad = 0
        diverged = False

        try:
            from tqdm import trange
            rng_iter = trange(start, iterations, desc="train",
                              dynamic_ncols=True)
        except ImportError:
            rng_iter = range(start, iterations)

        def process(j, metrics):
            """Host-side handling of step j's metrics (step j+1 may already
            be in flight — see the docstring's lag-1 note). Updates
            best_val/accum via nonlocal; returns ONLY whether an early
            stop fired (rate target reached, or the divergence guard —
            distinguishable afterwards via the `diverged` flag)."""
            nonlocal accum, n_accum, best_val, div_bad, diverged
            timer.tick()
            for k in ("loss", "bpp", "H_real", "d_loss", "si_l1"):
                accum[k] = accum.get(k, 0.0) + float(metrics[k])
            n_accum += 1

            if until_rate_target:
                h_recent.append(float(metrics["H_soft"]))
                if (len(h_recent) == rate_window
                        and float(np.mean(h_recent)) <= cfg.H_target):
                    color_print(
                        f"[{j + 1}] rate target reached: mean H_soft "
                        f"over last {rate_window} steps "
                        f"{float(np.mean(h_recent)):.4f} <= "
                        f"H_target {cfg.H_target}", "green", bold=True)
                    # closing validate + FORCED save: the checkpoint
                    # must hold the weights that satisfy the rate
                    # constraint (phase 2 warm-starts from them), even
                    # if an earlier noisy validation scored lower
                    best_val = self._validate_and_maybe_save(
                        j, iterations, best_val, val_losses, logger,
                        max_val_batches, force_save=True)
                    return True

            if (j + 1) % cfg.show_every == 0 or j + 1 == iterations:
                means = {k: v / n_accum for k, v in accum.items()}
                accum, n_accum = {}, 0
                ips = timer.images_per_sec(cfg.batch_size)
                color_print(
                    f"[{j + 1}/{iterations}] loss={means['loss']:.4f} "
                    f"bpp={means['bpp']:.4f} d={means['d_loss']:.4f} "
                    f"{ips:.2f} img/s", "cyan")
                logger.log(j + 1, means, images_per_sec=ips)

            # periodic (non-best) checkpoint: bounds work lost to a
            # crash — the reference loses everything since the last
            # val improvement (SURVEY §5)
            if checkpoint_every and (j + 1) % checkpoint_every == 0:
                ckpt_lib.save_checkpoint(
                    os.path.join(self.ckpt_dir, "periodic"), self.state,
                    extra_meta={"kind": "periodic"},
                    manifest_extra=self._manifest_extra())

            ve = get_validate_every(j, iterations, cfg.validate_every,
                                    cfg.get("decrease_val_steps", True))
            if (j + 1) % ve == 0 or j + 1 == iterations:
                best_val = self._validate_and_maybe_save(
                    j, iterations, best_val, val_losses, logger,
                    max_val_batches)
                val_loss = val_losses[-1]
                # only finite-over-finite counts toward the guard: an inf
                # val_loss means the val split produced zero batches (its
                # own loud warning), not divergence
                if (div_patience and np.isfinite(val_loss)
                        and np.isfinite(best_val)
                        and val_loss > div_factor * best_val):
                    div_bad += 1
                    if div_bad >= div_patience:
                        diverged = True
                        color_print(
                            f"[{j + 1}] DIVERGENCE STOP: val_loss above "
                            f"{div_factor:g}x best_val "
                            f"({best_val:.4f}) for {div_bad} consecutive "
                            f"validations — stopping; the best-val "
                            f"checkpoint is the run's artifact "
                            f"(restore_best_for_test scores it)",
                            "red", bold=True)
                        return True
                else:
                    div_bad = 0
            return False

        pending = None   # (step index, device metrics) awaiting processing
        try:
            for i in rng_iter:
                x, y = next(train_it)
                # drain the in-flight step before the profiler would close
                # its trace window: with the lag-1 loop the final traced
                # step could otherwise still be executing at stop_trace
                if (pending is not None and profiler.active
                        and i >= profiler.stop_step):
                    if process(*pending):
                        pending = None
                        break
                    pending = None
                profiler.step(i)
                with profiler.annotation(i):
                    self.state, metrics = self.train_step(self.state,
                                                          *self._put(x, y))
                if pending is not None and process(*pending):
                    pending = None
                    break
                pending = (i, metrics)
            if pending is not None:
                process(*pending)
        except BaseException as e:
            # emergency save: preserve the in-flight state before dying.
            # BaseException, not Exception: Ctrl-C / SIGINT-driven preemption
            # (KeyboardInterrupt) and SystemExit are how long TPU runs most
            # often die, and they must reach this save too. (SIGKILL/SIGTERM
            # without a Python handler still can't — that's what
            # checkpoint_every bounds.)
            # Guarded: device-side crashes can leave self.state donated or
            # error-poisoned, in which case the save itself raises — never
            # let that mask the original error.
            # `pending is not None` counts alongside total_steps: with the
            # lag-1 loop a crash at the NEXT dispatch arrives before the
            # completed step was ever processed/ticked.
            if (cfg.get("save_model", True)
                    and (timer.total_steps > 0 or pending is not None)
                    and not isinstance(e, GeneratorExit)):
                emergency = os.path.join(self.ckpt_dir, "emergency")
                try:
                    ckpt_lib.save_checkpoint(
                        emergency, self.state,
                        extra_meta={"kind": "emergency", "error": repr(e)},
                        manifest_extra=self._manifest_extra())
                    color_print(f"crash at step {int(self.state.step)}; "
                                f"state saved to {emergency}", "red",
                                bold=True)
                except Exception as save_err:  # noqa: BLE001
                    color_print(f"crash AND emergency save failed "
                                f"({save_err!r}); state lost", "red",
                                bold=True)
            raise
        finally:
            profiler.stop()
            logger.close()

        return {"steps": timer.total_steps, "best_val": best_val,
                "last_val": val_losses[-1] if val_losses else float("inf"),
                "diverged_stop": diverged,
                "images_per_sec": timer.images_per_sec(cfg.batch_size)}

    # -- test ---------------------------------------------------------------

    def _bottleneck_codec(self):
        """BottleneckCodec over the trained context model + centers — for
        measured-bitstream bpp at test time (the reference's `--real_bpp`
        hooks are vestigial, reference probclass_imgcomp.py:361-364; here
        they work)."""
        from dsin_tpu.coding.codec import BottleneckCodec
        return BottleneckCodec.for_model(self.model,
                                         jax.device_get(self.state.params))

    def restore_best_for_test(self, extra_candidates=()) -> Optional[str]:
        """Test the state this run SHIPS, not the last training iterate.

        Training can drift past its best validation (observed live on the
        0.04 pipeline point: phase-2 best_val 24.2 at step 751, diverged
        to 47.7 by 1500 — and the closing test silently scored the
        diverged weights). The run's artifact is its best-val checkpoint.
        This intentionally diverges from the reference's combined
        train+test run (reference main.py:45-126 scores the LIVE session
        weights there) and instead matches its separate-test workflow
        (load_model=True: reference main.py:101-126 + AE.load_model
        AE.py:158-175), which restores a checkpoint before scoring.

        Candidates: this run's own ckpt_dir plus `extra_candidates`
        (e.g. a prior attempt's best-val dir when this run RESUMED from
        its periodic/emergency checkpoint — the resumed tail may never
        beat the prior best, whose dir is untouched by the new attempt).
        The candidate with the lowest recorded best_val wins; unreadable
        or torn meta.json files are skipped, not fatal (a kill mid-save
        can truncate one — same defense as synthetic_rd's
        _latest_resumable). Returns the restored dir, or None when the
        live state already is the best (or nothing restorable exists).
        """
        best_dir, best_val, best_meta = None, float("inf"), None
        for cand in (self.ckpt_dir, *extra_candidates):
            # resolve through the rotation history: a kill between swap
            # renames leaves only `<cand>.prev-*` (train/checkpoint.py);
            # keep the caller's path (identity matters for the
            # already-live check below) when cand itself is complete
            if not os.path.exists(os.path.join(cand, "meta.json")):
                cand = ckpt_lib.latest_checkpoint(cand) or cand
            try:
                meta = ckpt_lib.load_meta(cand)
                val = float(meta["best_val"])
            except (OSError, KeyError, ValueError):
                continue
            if val < best_val:
                best_dir, best_val, best_meta = cand, val, meta
        if best_dir is None:
            return None
        if (best_dir == self.ckpt_dir
                and int(best_meta.get("step", -1)) == int(self.state.step)):
            return None
        self.state = ckpt_lib.restore_partitions(
            best_dir, self.state, best_meta["partitions"])
        color_print(f"test restores the best-val checkpoint {best_dir} "
                    f"(step {best_meta.get('step')}, val {best_val}) over "
                    f"the last training iterate", "yellow", bold=True)
        return best_dir

    def test(self, max_images: Optional[int] = None,
             save_images: bool = True,
             save_plots: bool = False,
             real_bpp: bool = False) -> Dict[str, float]:
        """Test-split inference: reconstruction PNGs + per-image score lists
        (reference main.py:101-126). `real_bpp=True` additionally ENCODES
        each bottleneck with the rANS codec and reports the actual
        bitstream's bits/pixel next to the cross-entropy estimate."""
        from dsin_tpu.eval import ScoreLists
        cfg = self.ae_config
        lists = ScoreLists(self.images_dir, self.model_name)
        codec = self._bottleneck_codec() if real_bpp else None
        test_ds = self._dataset("test", train=False)
        try:
            self._run_test_loop(test_ds, lists, codec, cfg, max_images,
                                save_images, save_plots)
        finally:
            test_ds.close()
        means = lists.means()
        if means:
            color_print(f"test means: {means}", "magenta", bold=True)
        return means

    def _run_test_loop(self, test_ds, lists, codec, cfg, max_images,
                       save_images, save_plots):
        from dsin_tpu.eval import image_output_path, save_image
        for idx, (x, y) in enumerate(test_ds.batches(loop=False)):
            if max_images is not None and idx >= max_images:
                break
            out = self.infer_step(self.state, jnp.asarray(x), jnp.asarray(y))
            # jaxlint: disable=host-sync-in-loop -- ONE batched pull of the
            # whole output pytree per image: the intended host boundary of
            # the test loop (scoring/PNG writing are host work), replacing
            # six per-leaf np.asarray round trips over the device link
            out = jax.device_get(out)
            x_np = x[0]           # loader batches are already host numpy
            xsi = np.clip((out["x_with_si"] if not self.model.ae_only
                           else out["x_dec"])[0], 0, 255)
            y_syn = (np.clip(out["y_syn"][0], 0, 255)
                     if out["y_syn"] is not None else None)
            bpp = float(out["bpp"])
            measured = None
            if codec is not None:
                syms = np.transpose(out["symbols"][0], (2, 0, 1))
                stream = codec.encode(syms)
                measured = len(stream) * 8.0 / (x_np.shape[0] * x_np.shape[1])
            scores = lists.add_image(x_np, xsi, bpp=bpp, y_syn=y_syn,
                                     patch_size=cfg.y_patch_size,
                                     real_bpp=measured)
            if save_images:
                save_image(xsi, image_output_path(self.images_dir, idx, bpp))
            if save_plots:
                from dsin_tpu.eval.plots import plot_inference
                plot_inference(
                    x_np, out["x_dec"][0], xsi, y[0],
                    y_syn, os.path.join(self.images_dir, f"{idx}_panels.png"),
                    bpp=bpp)
            lists.save()
            color_print(f"test[{idx}] bpp={bpp:.4f} "
                        f"psnr={scores['psnr']:.2f} "
                        f"msssim={scores['ms_ssim']:.4f}", "blue")


def run(ae_config: Config, pc_config: Config, out_root: str = ".",
        max_steps: Optional[int] = None,
        max_val_batches: Optional[int] = None,
        max_test_images: Optional[int] = None,
        profile_dir: Optional[str] = None,
        real_bpp: bool = False,
        replicate_to: Optional[str] = None) -> Dict[str, float]:
    """Config-driven orchestration (reference main.py:21-126)."""
    exp = Experiment(ae_config, pc_config, out_root=out_root,
                     replicate_to=replicate_to)
    exp.maybe_restore()
    results: Dict[str, float] = {}
    if ae_config.train_model:
        results.update(exp.train(max_steps=max_steps,
                                 max_val_batches=max_val_batches,
                                 profile_dir=profile_dir))
    if ae_config.test_model:
        if ae_config.train_model:
            # never score the in-memory training tail (it may have
            # diverged past its best validation) — test what the run ships
            exp.restore_best_for_test()
        results.update(exp.test(max_images=max_test_images,
                                real_bpp=real_bpp))
    return results


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="dsin_tpu trainer")
    base = os.path.join(os.path.dirname(__file__), "configs")
    p.add_argument("-ae_config", default=os.path.join(base, "ae_kitti_stereo"))
    p.add_argument("-pc_config", default=os.path.join(base, "pc_default"))
    p.add_argument("--out_root", default=".")
    p.add_argument("--data_root", default=None,
                   help="override ae config root_data")
    p.add_argument("--max_steps", type=int, default=None)
    p.add_argument("--max_test_images", type=int, default=None)
    p.add_argument("--real_bpp", action="store_true",
                   help="at test time, also ENCODE each bottleneck with the "
                        "rANS codec and report measured bitstream bpp (the "
                        "reference's vestigial --real_bpp, working)")
    p.add_argument("--profile_dir", default=None,
                   help="capture an XLA trace of a few warm train steps")
    p.add_argument("--replicate_to", default=None,
                   help="peer-visible root (NFS mount, object-store "
                        "fuse) to replicate every best-val checkpoint "
                        "to via train/checkpoint.replicate_checkpoint "
                        "(manifest-CRC-verified both sides); the copy "
                        "lands at <replicate_to>/<model_name>")
    p.add_argument("--distributed", action="store_true",
                   help="multi-host: call jax.distributed.initialize() "
                        "(coordinator/host env per JAX docs); each host "
                        "loads its own manifest shard automatically")
    return p.parse_args(argv)


def main(argv=None) -> None:
    args = parse_args(argv)
    if args.distributed:
        jax.distributed.initialize()
    ae_config = parse_config_file(args.ae_config)
    pc_config = parse_config_file(args.pc_config)
    if args.data_root:
        ae_config = ae_config.replace(root_data=args.data_root)
    results = run(ae_config, pc_config, out_root=args.out_root,
                  max_steps=args.max_steps,
                  max_test_images=args.max_test_images,
                  profile_dir=args.profile_dir,
                  real_bpp=args.real_bpp,
                  replicate_to=args.replicate_to)
    color_print(f"done: {results}", "green", bold=True)


if __name__ == "__main__":
    main()
