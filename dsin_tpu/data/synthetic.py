"""Synthetic stereo corpus: documented stand-in for KITTI when the real
dataset is unavailable (this environment ships no image data).

Each scene is composed of depth layers rendered into a LEFT and RIGHT view:

  * a smooth textured background (upsampled low-resolution color grid —
    compressible structure, like real image statistics at a coarse scale);
  * K rectangles at random depths, each with its own smooth texture.
    Nearer layers get LARGER horizontal disparity, exactly the geometry a
    stereo rig produces, so the right view is the left view with
    per-object horizontal shifts + occlusion;
  * the right view additionally gets a small global brightness/contrast
    jitter and sensor noise — the photometric mismatch siFinder's Pearson
    correlation is designed to survive (affine-invariant matching).

This gives the two properties the DSIN pipeline needs to demonstrate a
rate-distortion point end-to-end: learnable image structure for the
autoencoder/entropy model, and true cross-view correlation for the
side-information path. Not a KITTI replacement for paper numbers — a
documented, reproducible corpus for pipeline-scale evidence (VERDICT r1 §4).

CLI:
    python -m dsin_tpu.data.synthetic --out_dir /tmp/synth \
        --num_train 40 --num_val 8 --num_test 8 --height 160 --width 480
writes PNGs + KITTI-format alternating-line manifests
(`synthetic_stereo_{train,val,test}.txt`).
"""

from __future__ import annotations

import argparse
import os
from typing import Tuple

import numpy as np


def _smooth_texture(rng: np.random.Generator, h: int, w: int,
                    cells: int = 8) -> np.ndarray:
    """Bilinearly-upsampled random low-res RGB grid: smooth, compressible."""
    grid = rng.uniform(0, 255, (cells, cells, 3)).astype(np.float32)
    ys = np.linspace(0, cells - 1, h)
    xs = np.linspace(0, cells - 1, w)
    y0 = np.clip(ys.astype(int), 0, cells - 2)
    x0 = np.clip(xs.astype(int), 0, cells - 2)
    fy = (ys - y0)[:, None, None]
    fx = (xs - x0)[None, :, None]
    a = grid[y0][:, x0]
    b = grid[y0][:, x0 + 1]
    c = grid[y0 + 1][:, x0]
    d = grid[y0 + 1][:, x0 + 1]
    return (a * (1 - fy) * (1 - fx) + b * (1 - fy) * fx
            + c * fy * (1 - fx) + d * fy * fx)


def make_stereo_pair(rng: np.random.Generator, height: int, width: int,
                     max_disparity: int = 24, num_objects: int = 5
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """One (left, right) uint8 pair. Layers back-to-front; each layer is
    drawn into the right view shifted LEFT by its disparity (standard
    rectified stereo: right-camera image content moves left)."""
    left = _smooth_texture(rng, height, width)
    right = np.empty_like(left)
    bg_disp = int(rng.integers(0, max(max_disparity // 4, 1)))
    right[:, : width - bg_disp] = left[:, bg_disp:]
    right[:, width - bg_disp:] = left[:, width - 1:width]

    # objects: nearer (later-drawn) layers have larger disparity
    disparities = np.sort(rng.integers(bg_disp, max_disparity + 1,
                                       num_objects))
    for disp in disparities:
        oh = int(rng.integers(height // 6, height // 2))
        ow = int(rng.integers(width // 8, width // 3))
        top = int(rng.integers(0, height - oh))
        # narrow images: a disparity can exceed the placeable range
        # (rng.integers needs low < high) — clamp to keep the object and
        # its shifted twin inside both views
        disp = min(int(disp), width - ow - 1)
        lft = int(rng.integers(disp, width - ow))
        tex = _smooth_texture(rng, oh, ow, cells=4)
        left[top:top + oh, lft:lft + ow] = tex
        right[top:top + oh, lft - disp:lft - disp + ow] = tex

    # photometric mismatch on the right view only
    gain = float(rng.uniform(0.9, 1.1))
    bias = float(rng.uniform(-8, 8))
    right = right * gain + bias
    right = right + rng.normal(0, 2.0, right.shape)
    return (np.clip(left, 0, 255).astype(np.uint8),
            np.clip(right, 0, 255).astype(np.uint8))


def write_corpus(out_dir: str, num_train: int, num_val: int, num_test: int,
                 height: int, width: int, seed: int = 0,
                 max_disparity: int = 24) -> dict:
    """Generate PNGs + alternating-line manifests (the loader's format,
    reference DataProvider.py:119-126). Returns {split: manifest_path}."""
    from PIL import Image

    rng = np.random.default_rng(seed)
    img_dir = os.path.join(out_dir, "images")
    os.makedirs(img_dir, exist_ok=True)
    manifests = {}
    counts = {"train": num_train, "val": num_val, "test": num_test}
    idx = 0
    for split, count in counts.items():
        lines = []
        for _ in range(count):
            left, right = make_stereo_pair(rng, height, width, max_disparity)
            lp = os.path.join("images", f"{idx:05d}_L.png")
            rp = os.path.join("images", f"{idx:05d}_R.png")
            Image.fromarray(left).save(os.path.join(out_dir, lp))
            Image.fromarray(right).save(os.path.join(out_dir, rp))
            lines += [lp, rp]
            idx += 1
        path = os.path.join(out_dir, f"synthetic_stereo_{split}.txt")
        with open(path, "w") as f:
            f.write("\n".join(lines) + "\n")
        manifests[split] = path
    return manifests


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="synthetic stereo corpus")
    p.add_argument("--out_dir", required=True)
    p.add_argument("--num_train", type=int, default=40)
    p.add_argument("--num_val", type=int, default=8)
    p.add_argument("--num_test", type=int, default=8)
    p.add_argument("--height", type=int, default=160)
    p.add_argument("--width", type=int, default=480)
    p.add_argument("--max_disparity", type=int, default=24)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    manifests = write_corpus(args.out_dir, args.num_train, args.num_val,
                             args.num_test, args.height, args.width,
                             args.seed, args.max_disparity)
    for split, path in manifests.items():
        print(f"{split}: {path}")


if __name__ == "__main__":
    main()
