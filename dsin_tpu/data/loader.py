"""Host-side data pipeline: paired stereo images -> device-ready batches.

Replaces the reference's tf.data + private-Session design (reference
DataProvider.py) with a plain-Python threaded loader; the output contract is
the same — batches of (x, y) float32 where x is the image to compress and y
the side-information image — but NHWC (TPU layout) instead of NCHW, and
shardable across hosts.

Pipeline (training; reference DataProvider.py:102-140 semantics):
  shuffle pair list -> decode both PNGs -> `num_crops_per_img` random
  (crop_h, crop_w) crops of the stacked 6-channel pair (+ optional LR flip)
  -> the x side is *re-cropped* to the model crop within the y crop
  (reference keeps y at full crop so the search has context; with equal
  sizes this is an identity re-crop) -> crop-level shuffle buffer -> batches
  (drop_remainder) -> prefetch thread.

Validation/test: deterministic center crops, no flip, in manifest order
(reference DataProvider.py:62-94,151-184).
"""

from __future__ import annotations

import collections
import os
import concurrent.futures
import queue
import threading
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from dsin_tpu.data.manifest import read_pair_manifest


def decode_image(path: str) -> np.ndarray:
    """PNG/JPEG -> (H, W, 3) uint8."""
    from PIL import Image
    with Image.open(path) as im:
        return np.asarray(im.convert("RGB"), dtype=np.uint8)


def random_pair_crops(pair_6ch: np.ndarray, crop_h: int, crop_w: int,
                      num_crops: int, do_flip: bool,
                      rng: np.random.Generator) -> List[np.ndarray]:
    """`num_crops` random crops of the stacked (H, W, 6) pair."""
    h, w, _ = pair_6ch.shape
    assert h >= crop_h and w >= crop_w, (pair_6ch.shape, crop_h, crop_w)
    out = []
    for _ in range(num_crops):
        top = int(rng.integers(0, h - crop_h + 1))
        left = int(rng.integers(0, w - crop_w + 1))
        crop = pair_6ch[top:top + crop_h, left:left + crop_w, :]
        if do_flip and rng.random() < 0.5:
            crop = crop[:, ::-1, :]
        out.append(np.ascontiguousarray(crop))
    return out


def center_pair_crop(pair_6ch: np.ndarray, crop_h: int,
                     crop_w: int) -> np.ndarray:
    h, w, _ = pair_6ch.shape
    top = (h - crop_h) // 2
    left = (w - crop_w) // 2
    return np.ascontiguousarray(pair_6ch[top:top + crop_h,
                                         left:left + crop_w, :])


def _split_xy(crop_6ch: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    return (crop_6ch[..., :3].astype(np.float32),
            crop_6ch[..., 3:].astype(np.float32))


class PairDataset:
    """Iterable dataset over correlated image pairs.

    Args:
      pairs: list of (x_path, y_path); usually from `read_pair_manifest`.
      crop_size: (H, W) output crop.
      batch_size: per-host batch size.
      train: random crops + shuffle (+ flips) vs deterministic center crops.
      num_crops_per_img, do_flips, shuffle_buffer: training-pipeline knobs.
      host_id/num_hosts: shard the pair list across hosts (multi-host data
        parallelism; each host sees pairs[host_id::num_hosts]).
      seed: RNG seed for shuffling/cropping.
      decode_workers: PNG-decode thread-pool size (the analog of the
        reference's `num_parallel_calls=6` tf.data maps,
        DataProvider.py:6,131-132). PIL's decoders release the GIL, so
        decodes overlap on multi-core hosts. 0/1 = inline decoding.
        Default None = min(6, cpu_count): measured on a 1-core host,
        6 threads cost ~25% vs inline (contention), while multi-core
        hosts (a TPU-VM has 100+ cores) want the overlap.
    """

    def __init__(self, pairs: Sequence[Tuple[str, str]],
                 crop_size: Tuple[int, int], batch_size: int,
                 train: bool, num_crops_per_img: int = 1,
                 do_flips: bool = True, shuffle_buffer: int = 50,
                 host_id: int = 0, num_hosts: int = 1, seed: int = 0,
                 decode_fn=decode_image,
                 decode_workers: Optional[int] = None):
        self.pairs = list(pairs)[host_id::num_hosts]
        if not self.pairs:
            raise ValueError("no pairs for this host shard")
        self.crop_h, self.crop_w = crop_size
        self.batch_size = batch_size
        self.train = train
        self.num_crops = num_crops_per_img if train else 1
        self.do_flips = do_flips and train
        self.shuffle_buffer = max(shuffle_buffer * self.num_crops, 1)
        self.rng = np.random.default_rng(seed + host_id)
        self.decode_fn = decode_fn
        if decode_workers is None:
            decode_workers = min(6, os.cpu_count() or 1)
        self.decode_workers = decode_workers
        self._pool: Optional[concurrent.futures.ThreadPoolExecutor] = None

    def __len__(self) -> int:
        return len(self.pairs)

    def close(self) -> None:
        """Shut down the decode pool. Idempotent; the dataset remains
        usable afterwards (a fresh pool is created on demand). Call this
        on short-lived datasets (per-validation/test passes) so idle
        decode threads never outlive their pass."""
        pool = getattr(self, "_pool", None)   # absent if __init__ raised
        if pool is not None:
            pool.shutdown(wait=False, cancel_futures=True)
            self._pool = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        self.close()

    def num_batches_per_epoch(self) -> int:
        return (len(self.pairs) * self.num_crops) // self.batch_size

    def _decode_pair(self, idx: int) -> np.ndarray:
        x_path, y_path = self.pairs[idx]
        return np.concatenate(
            [self.decode_fn(x_path), self.decode_fn(y_path)], axis=-1)

    def _decoded_stream(self, order) -> Iterator[np.ndarray]:
        """Decoded (H, W, 6) pairs in `order`'s order.

        Decodes run on a shared thread pool with a bounded in-flight
        window (2x workers) — epoch order and every RNG draw happen on
        the consumer side, so the stream is bit-identical to inline
        decoding, just overlapped."""
        if self.decode_workers <= 1:
            for idx in order:
                yield self._decode_pair(idx)
            return
        if self._pool is None:
            self._pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=self.decode_workers,
                thread_name_prefix="pair-decode")
        inflight: "collections.deque" = collections.deque()
        it = iter(order)
        try:
            for idx in it:
                inflight.append(self._pool.submit(self._decode_pair, idx))
                if len(inflight) >= 2 * self.decode_workers:
                    yield inflight.popleft().result()
            while inflight:
                yield inflight.popleft().result()
        finally:
            while inflight:
                inflight.popleft().cancel()

    def _crop_stream(self, loop: bool) -> Iterator[np.ndarray]:
        while True:
            order = (self.rng.permutation(len(self.pairs)) if self.train
                     else np.arange(len(self.pairs)))
            for pair in self._decoded_stream(order):
                if self.train:
                    yield from random_pair_crops(
                        pair, self.crop_h, self.crop_w, self.num_crops,
                        self.do_flips, self.rng)
                else:
                    yield center_pair_crop(pair, self.crop_h, self.crop_w)
            if not loop:
                return

    def _shuffled_stream(self, loop: bool) -> Iterator[np.ndarray]:
        if not self.train:
            yield from self._crop_stream(loop)
            return
        buf: List[np.ndarray] = []
        for crop in self._crop_stream(loop):
            buf.append(crop)
            if len(buf) >= self.shuffle_buffer:
                j = int(self.rng.integers(0, len(buf)))
                buf[j], buf[-1] = buf[-1], buf[j]
                yield buf.pop()
        self.rng.shuffle(buf)
        yield from buf

    def batches(self, loop: Optional[bool] = None
                ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        """Yield (x, y) float32 NHWC batches. Training loops forever by
        default; eval runs one epoch (drop_remainder)."""
        loop = self.train if loop is None else loop
        batch: List[np.ndarray] = []
        for crop in self._shuffled_stream(loop):
            batch.append(crop)
            if len(batch) == self.batch_size:
                stacked = np.stack(batch)
                batch = []
                yield _split_xy(stacked)


class Prefetcher:
    """Background-thread prefetch of an iterator (the tf.data `prefetch(1)`
    analog; decode/crop overlaps with device compute)."""

    _DONE = object()

    def __init__(self, iterator, depth: int = 2):
        self._q: "queue.Queue" = queue.Queue(maxsize=depth)
        self._thread = threading.Thread(
            target=self._fill, args=(iterator,), daemon=True)
        self._err: Optional[BaseException] = None
        self._thread.start()

    def _fill(self, iterator):
        try:
            for item in iterator:
                self._q.put(item)
        except BaseException as e:  # surfaced on next()
            self._err = e
        finally:
            self._q.put(self._DONE)

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is self._DONE:
            if self._err is not None:
                raise self._err
            raise StopIteration
        return item
