from dsin_tpu.data.manifest import read_pair_manifest  # noqa: F401
