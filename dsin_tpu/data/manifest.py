"""Dataset manifests: text files listing correlated image pairs.

Contract of the reference's manifest format (reference DataProvider.py:96-126):
a manifest lists relative paths, one per line, with the primary image `x` on
even lines and its side-information image `y` on the following odd line.
Paths are joined with `root` (no separator added — the reference concatenates
strings directly, so `root` usually ends with '/'; we are more forgiving and
insert one when missing).
"""

from __future__ import annotations

import os
from typing import List, Tuple


def read_pair_manifest(path: str, root: str = "") -> List[Tuple[str, str]]:
    """Read x/y alternating-line manifest into a list of (x_path, y_path)."""
    with open(path) as f:
        lines = [ln.strip() for ln in f if ln.strip()]
    if len(lines) % 2 != 0:
        raise ValueError(
            f"manifest {path} has {len(lines)} non-empty lines; expected an "
            f"even count of alternating x/y entries")
    if root and not root.endswith(os.sep):
        root = root + os.sep
    xs = [root + p for p in lines[0::2]]
    ys = [root + p for p in lines[1::2]]
    return list(zip(xs, ys))


def num_pairs(path: str) -> int:
    """Number of (x, y) pairs listed in the manifest (reference AE.py:29)."""
    with open(path) as f:
        n = sum(1 for ln in f if ln.strip())
    if n % 2 != 0:
        raise ValueError(
            f"manifest {path} has {n} non-empty lines; expected an even count "
            f"of alternating x/y entries")
    return n // 2
