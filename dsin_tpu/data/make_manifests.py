"""Generate x/y pair manifests from a KITTI multiview directory tree.

The reference ships frozen manifest files (reference data_paths/
KITTI_stereo_{train,val,test}.txt — alternating lines, image_2 = encoder
input x, image_3 = decoder side information y, both relative to the KITTI
root). Those lists can't be redistributed meaningfully without the dataset,
so this tool regenerates them from a local KITTI download:

  * stereo mode: pair image_2/SEQ_FRAME.png with image_3/SEQ_FRAME.png —
    the same instant seen by the left/right camera (the reference's
    KITTI_stereo lists);
  * general mode: pair frames of the same sequence at a small temporal
    offset, cameras chosen at random — correlated but not co-instant, the
    reference's KITTI_general lists (whose exact pairing is unpublished;
    this is a seeded approximation with the same structure).

Expected tree (any subset of the standard zips):
    <kitti_root>/data_scene_flow_multiview/{training,testing}/image_{2,3}/
    <kitti_root>/data_stereo_flow_multiview/{training,testing}/image_{2,3}/

Usage:
    python -m dsin_tpu.data.make_manifests --kitti_root /data/kitti \
        --out_dir data_paths [--mode stereo] [--val_frac .2 --test_frac .2]
"""

from __future__ import annotations

import argparse
import os
import re
from typing import Dict, List, Tuple

import numpy as np

SUBSETS = ("data_scene_flow_multiview", "data_stereo_flow_multiview")
SPLITS = ("training", "testing")
_FRAME_RE = re.compile(r"^(\d+)_(\d+)\.png$")


def _scan(kitti_root: str) -> Dict[Tuple[str, str, str], Dict[int, str]]:
    """{(subset, split, seq): {frame: relpath-of-image_2}} for frames that
    exist in BOTH cameras."""
    out: Dict[Tuple[str, str, str], Dict[int, str]] = {}
    for subset in SUBSETS:
        for split in SPLITS:
            d2 = os.path.join(kitti_root, subset, split, "image_2")
            d3 = os.path.join(kitti_root, subset, split, "image_3")
            if not (os.path.isdir(d2) and os.path.isdir(d3)):
                continue
            right = set(os.listdir(d3))
            for name in sorted(os.listdir(d2)):
                m = _FRAME_RE.match(name)
                if not m or name not in right:
                    continue
                seq, frame = m.group(1), int(m.group(2))
                rel = os.path.join(subset, split, "image_2", name)
                out.setdefault((subset, split, seq), {})[frame] = rel
    return out


def stereo_pairs(kitti_root: str) -> List[Tuple[str, str]]:
    """(x=image_2, y=image_3) same-frame stereo pairs, sorted."""
    pairs = []
    for (_, _, _), frames in sorted(_scan(kitti_root).items()):
        for _, rel2 in sorted(frames.items()):
            pairs.append((rel2, rel2.replace("image_2", "image_3")))
    return pairs


def general_pairs(kitti_root: str, max_offset: int = 2,
                  seed: int = 0) -> List[Tuple[str, str]]:
    """Same-sequence pairs at temporal offset 1..max_offset, random camera
    per side (seeded) — the KITTI_general structure."""
    rng = np.random.default_rng(seed)
    pairs = []
    for (_, _, _), frames in sorted(_scan(kitti_root).items()):
        idx = sorted(frames)
        for frame in idx:
            offset = int(rng.integers(1, max_offset + 1))
            if frame + offset not in frames:
                continue
            a, b = frames[frame], frames[frame + offset]
            cam_a, cam_b = rng.choice(["image_2", "image_3"], size=2)
            pairs.append((a.replace("image_2", cam_a),
                          b.replace("image_2", cam_b)))
    return pairs


def reference_stereo_splits(kitti_root: str) -> Dict[str, List[Tuple[str, str]]]:
    """The reference's EXACT split rule, reverse-engineered from its frozen
    lists (reference data_paths/KITTI_stereo_{train,val,test}.txt,
    1576/790/790 pairs):

      * only frames 10 and 11 of each sequence are used (the canonical
        KITTI stereo-benchmark frames; the other multiview frames 0..20
        are ignored);
      * train = the `training` split of both subsets, frames 10 AND 11;
      * val   = the `testing` split, frame 11 only;
      * test  = the `testing` split, frame 10 only;
      * every pair appears in BOTH directions — each subset contributes a
        block of forward pairs (x=image_2, y=image_3) followed by the same
        block swapped (x=image_3, y=image_2), doubling the data;
      * ordering: subset alphabetical (data_scene_flow_multiview first),
        then within a subset: forward block then swapped block, each in
        sequence-then-frame ascending order.

    On a standard KITTI multiview layout (scene_flow: 200 train + 200 test
    sequences; stereo_flow: 194 train + 195 test) this reproduces the
    reference's counts (1576/790/790) and line order exactly.
    """
    splits: Dict[str, List[Tuple[str, str]]] = {
        "train": [], "val": [], "test": []}
    by_subset: Dict[str, Dict[str, List[Tuple[str, str]]]] = {}
    for (subset, split, _), frames in sorted(_scan(kitti_root).items()):
        for frame in sorted(frames):
            if frame not in (10, 11):
                continue
            rel2 = frames[frame]
            pair = (rel2, rel2.replace("image_2", "image_3"))
            name = ("train" if split == "training"
                    else "val" if frame == 11 else "test")
            by_subset.setdefault(subset, {"train": [], "val": [],
                                          "test": []})[name].append(pair)
    for subset in sorted(by_subset):
        for name, fwd in by_subset[subset].items():
            splits[name].extend(fwd)
            splits[name].extend((y, x) for x, y in fwd)
    return splits


def split_pairs(pairs: List[Tuple[str, str]], val_frac: float,
                test_frac: float, seed: int = 0):
    """Deterministic shuffled split into train/val/test."""
    order = np.random.default_rng(seed).permutation(len(pairs))
    n_val = int(len(pairs) * val_frac)
    n_test = int(len(pairs) * test_frac)
    val = [pairs[i] for i in order[:n_val]]
    test = [pairs[i] for i in order[n_val:n_val + n_test]]
    train = [pairs[i] for i in order[n_val + n_test:]]
    return {"train": train, "val": val, "test": test}


def write_manifest(path: str, pairs: List[Tuple[str, str]]) -> None:
    """Alternating x/y lines (reference DataProvider.py:119-126)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        for x, y in pairs:
            f.write(x + "\n" + y + "\n")


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="KITTI pair-manifest generator")
    p.add_argument("--kitti_root", required=True)
    p.add_argument("--out_dir", default="data_paths")
    p.add_argument("--mode", choices=("stereo", "general"), default="stereo")
    p.add_argument("--split_rule", choices=("reference", "random"),
                   default="reference",
                   help="'reference' (stereo mode only) reproduces the "
                        "reference's frozen 1576/790/790 lists exactly; "
                        "'random' is a seeded fractional split over all "
                        "frames")
    p.add_argument("--val_frac", type=float, default=0.2)
    p.add_argument("--test_frac", type=float, default=0.2)
    p.add_argument("--max_offset", type=int, default=2)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    if args.mode == "stereo" and args.split_rule == "reference":
        splits = reference_stereo_splits(args.kitti_root)
        if not any(splits.values()):
            raise SystemExit(
                f"no image_2/image_3 pairs under {args.kitti_root}")
        for split, split_list in splits.items():
            out = os.path.join(args.out_dir, f"KITTI_stereo_{split}.txt")
            write_manifest(out, split_list)
            print(f"{out}: {len(split_list)} pairs")
        return

    pairs = (stereo_pairs(args.kitti_root) if args.mode == "stereo"
             else general_pairs(args.kitti_root, args.max_offset, args.seed))
    if not pairs:
        raise SystemExit(f"no image_2/image_3 pairs under {args.kitti_root}")
    splits = split_pairs(pairs, args.val_frac, args.test_frac, args.seed)
    for split, split_list in splits.items():
        out = os.path.join(args.out_dir,
                           f"KITTI_{args.mode}_{split}.txt")
        write_manifest(out, split_list)
        print(f"{out}: {len(split_list)} pairs")


if __name__ == "__main__":
    main()
