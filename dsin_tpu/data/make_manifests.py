"""Generate x/y pair manifests from a KITTI multiview directory tree.

The reference ships frozen manifest files (reference data_paths/
KITTI_stereo_{train,val,test}.txt — alternating lines, image_2 = encoder
input x, image_3 = decoder side information y, both relative to the KITTI
root). Those lists can't be redistributed meaningfully without the dataset,
so this tool regenerates them from a local KITTI download:

  * stereo mode: pair image_2/SEQ_FRAME.png with image_3/SEQ_FRAME.png —
    the same instant seen by the left/right camera (the reference's
    KITTI_stereo lists);
  * general mode: pair frames of the same sequence at a small temporal
    offset, cameras chosen at random — correlated but not co-instant, the
    reference's KITTI_general lists. The generating rule is derived from
    the frozen lists (see `reference_general_splits`); the reference's own
    shuffle is unrecoverable (unseeded RNG), so our lists match the
    reference's in pair universe, split sizes, and structure — but our
    seeded shuffle draws a DIFFERENT val/test membership partition, so
    metrics on these splits are not directly comparable to numbers
    computed on the reference's frozen lists (for that, point the loader
    at the frozen files themselves).

Expected tree (any subset of the standard zips):
    <kitti_root>/data_scene_flow_multiview/{training,testing}/image_{2,3}/
    <kitti_root>/data_stereo_flow_multiview/{training,testing}/image_{2,3}/

Usage:
    python -m dsin_tpu.data.make_manifests --kitti_root /data/kitti \
        --out_dir data_paths [--mode stereo] [--val_frac .2 --test_frac .2]
"""

from __future__ import annotations

import argparse
import os
import re
from typing import Dict, List, Optional, Tuple

import numpy as np

SUBSETS = ("data_scene_flow_multiview", "data_stereo_flow_multiview")
SPLITS = ("training", "testing")
_FRAME_RE = re.compile(r"^(\d+)_(\d+)\.png$")


def _scan(kitti_root: str) -> Dict[Tuple[str, str, str], Dict[int, str]]:
    """{(subset, split, seq): {frame: relpath-of-image_2}} for frames that
    exist in BOTH cameras."""
    out: Dict[Tuple[str, str, str], Dict[int, str]] = {}
    for subset in SUBSETS:
        for split in SPLITS:
            d2 = os.path.join(kitti_root, subset, split, "image_2")
            d3 = os.path.join(kitti_root, subset, split, "image_3")
            if not (os.path.isdir(d2) and os.path.isdir(d3)):
                continue
            right = set(os.listdir(d3))
            for name in sorted(os.listdir(d2)):
                m = _FRAME_RE.match(name)
                if not m or name not in right:
                    continue
                seq, frame = m.group(1), int(m.group(2))
                rel = os.path.join(subset, split, "image_2", name)
                out.setdefault((subset, split, seq), {})[frame] = rel
    return out


def stereo_pairs(kitti_root: str) -> List[Tuple[str, str]]:
    """(x=image_2, y=image_3) same-frame stereo pairs, sorted."""
    pairs = []
    for (_, _, _), frames in sorted(_scan(kitti_root).items()):
        for _, rel2 in sorted(frames.items()):
            pairs.append((rel2, rel2.replace("image_2", "image_3")))
    return pairs


def general_pairs(kitti_root: str, max_offset: int = 2,
                  seed: int = 0) -> List[Tuple[str, str]]:
    """Same-sequence pairs at temporal offset 1..max_offset, random camera
    per side (seeded) — the KITTI_general structure."""
    rng = np.random.default_rng(seed)
    pairs = []
    for (_, _, _), frames in sorted(_scan(kitti_root).items()):
        idx = sorted(frames)
        for frame in idx:
            offset = int(rng.integers(1, max_offset + 1))
            if frame + offset not in frames:
                continue
            a, b = frames[frame], frames[frame + offset]
            cam_a, cam_b = rng.choice(["image_2", "image_3"], size=2)
            pairs.append((a.replace("image_2", cam_a),
                          b.replace("image_2", cam_b)))
    return pairs


# The 20 evaluation sequences of the reference's KITTI_general val/test
# lists — every pair in the frozen lists (reference data_paths/
# KITTI_general_{val,test}.txt) draws both frames from one of these
# `testing`-split sequences.
REFERENCE_GENERAL_EVAL_SEQS: Dict[str, Tuple[str, ...]] = {
    "data_scene_flow_multiview": (
        "000029", "000079", "000085", "000100", "000105",
        "000110", "000129", "000150", "000158", "000175"),
    "data_stereo_flow_multiview": (
        "000004", "000033", "000041", "000044", "000049",
        "000052", "000122", "000158", "000166", "000167"),
}
GENERAL_MAX_OFFSET = 3
GENERAL_VAL_FRAC = 0.2
GENERAL_HOLDOUT_GAP = 41


def general_pair_universe(kitti_root: str,
                          split: str,
                          seqs: Optional[Dict[str, Tuple[str, ...]]] = None,
                          max_offset: int = GENERAL_MAX_OFFSET
                          ) -> List[Tuple[str, str]]:
    """Every ordered same-sequence pair at temporal offset ±1..max_offset,
    in both camera orientations, in canonical enumeration order (subset
    alphabetical, sequence ascending, x-frame ascending, offset -3..+3,
    orientation (x=image_2) before (x=image_3)).

    This is the pair universe underlying the reference's KITTI_general
    val/test lists: their union covers 4519 of exactly these 4560 pairs
    for the 20 eval sequences, and nothing outside it. `seqs` restricts to
    {subset: (seq, ...)}; None takes every sequence found under `split`.
    Both frames must exist in both cameras.
    """
    universe: List[Tuple[str, str]] = []
    scan = _scan(kitti_root)
    for (subset, sp, seq), frames in sorted(scan.items()):
        if sp != split:
            continue
        if seqs is not None and seq not in seqs.get(subset, ()):
            continue
        for fx in sorted(frames):
            for off in range(-max_offset, max_offset + 1):
                if off == 0 or fx + off not in frames:
                    continue
                a, b = frames[fx], frames[fx + off]
                universe.append((a, b.replace("image_2", "image_3")))
                universe.append((a.replace("image_2", "image_3"), b))
    return universe


def reference_general_splits(kitti_root: str, seed: int = 0
                             ) -> Dict[str, List[Tuple[str, str]]]:
    """The reference's KITTI_general split rule, derived from its frozen
    lists (reference data_paths/KITTI_general_{val,test}.txt, 912/3607
    pairs; KITTI_general_train.txt is stripped upstream):

      * eval pair universe = the 20 fixed `testing`-split sequences
        (REFERENCE_GENERAL_EVAL_SEQS) x ordered frame pairs at temporal
        offset ±1..3 (within the 21 frames) x both camera orientations
        = 4560 ordered pairs; verified: frozen val ∪ test is 4519 of
        exactly these pairs, and the two lists are disjoint;
      * the universe is shuffled; val = the first 20% (912 — the frozen
        val size is int(0.2 * 4560) exactly), the next 41 pairs are
        discarded (the frozen test list covers all but 41 of the
        remainder, and those 41 are uniformly spread — a small dropped
        slice, not any file- or structure-dependent filter), test = the
        remaining 3607;
      * train = the same universe construction over every
        `training`-split sequence of both subsets, shuffled.

    Reproducing the frozen lists themselves is impossible in principle:
    both the line order AND the val/test membership partition are one raw
    RNG draw that no structural rule pins down, and searches over seeded
    MT19937 / PCG64 / python-random shuffle and sampling procedures found
    no generating seed — consistent with an unseeded shuffle at creation
    time. This function's seeded shuffle therefore yields a *different*
    (equally valid) membership draw; users wanting the reference's exact
    eval sample should load the frozen files directly. Everything
    derivable — universe, sizes, split fractions, disjointness — is
    reproduced and pinned by tests (tests/test_make_manifests.py).
    """
    rng = np.random.default_rng(seed)
    universe = general_pair_universe(kitti_root, "testing",
                                     REFERENCE_GENERAL_EVAL_SEQS)
    order = rng.permutation(len(universe))
    n_val = int(len(universe) * GENERAL_VAL_FRAC)
    gap = GENERAL_HOLDOUT_GAP if len(universe) > GENERAL_HOLDOUT_GAP else 0
    val = [universe[i] for i in order[:n_val]]
    test = [universe[i] for i in order[n_val + gap:]]
    train_univ = general_pair_universe(kitti_root, "training")
    train = [train_univ[i] for i in rng.permutation(len(train_univ))]
    return {"train": train, "val": val, "test": test}


def reference_stereo_splits(kitti_root: str) -> Dict[str, List[Tuple[str, str]]]:
    """The reference's EXACT split rule, reverse-engineered from its frozen
    lists (reference data_paths/KITTI_stereo_{train,val,test}.txt,
    1576/790/790 pairs):

      * only frames 10 and 11 of each sequence are used (the canonical
        KITTI stereo-benchmark frames; the other multiview frames 0..20
        are ignored);
      * train = the `training` split of both subsets, frames 10 AND 11;
      * val   = the `testing` split, frame 11 only;
      * test  = the `testing` split, frame 10 only;
      * every pair appears in BOTH directions — each subset contributes a
        block of forward pairs (x=image_2, y=image_3) followed by the same
        block swapped (x=image_3, y=image_2), doubling the data;
      * ordering: subset alphabetical (data_scene_flow_multiview first),
        then within a subset: forward block then swapped block, each in
        sequence-then-frame ascending order.

    On a standard KITTI multiview layout (scene_flow: 200 train + 200 test
    sequences; stereo_flow: 194 train + 195 test) this reproduces the
    reference's counts (1576/790/790) and line order exactly.
    """
    splits: Dict[str, List[Tuple[str, str]]] = {
        "train": [], "val": [], "test": []}
    by_subset: Dict[str, Dict[str, List[Tuple[str, str]]]] = {}
    for (subset, split, _), frames in sorted(_scan(kitti_root).items()):
        for frame in sorted(frames):
            if frame not in (10, 11):
                continue
            rel2 = frames[frame]
            pair = (rel2, rel2.replace("image_2", "image_3"))
            name = ("train" if split == "training"
                    else "val" if frame == 11 else "test")
            by_subset.setdefault(subset, {"train": [], "val": [],
                                          "test": []})[name].append(pair)
    for subset in sorted(by_subset):
        for name, fwd in by_subset[subset].items():
            splits[name].extend(fwd)
            splits[name].extend((y, x) for x, y in fwd)
    return splits


def cityscapes_stereo_splits(root: str) -> Dict[str, List[Tuple[str, str]]]:
    """Left/right frame pairs in Cityscapes' own train/val/test partition.

    Expected tree (the standard leftImg8bit/rightImg8bit zips):
        <root>/leftImg8bit/<split>/<city>/<stem>_leftImg8bit.png
        <root>/rightImg8bit/<split>/<city>/<stem>_rightImg8bit.png

    Pairs are (left, right) paths relative to <root> — left is the encoder
    input x, right the decoder-only side information y, the same camera
    convention as the KITTI image_2/image_3 pairing above. Cityscapes
    publishes its own city-disjoint split directories, so unlike KITTI no
    split rule is applied here; frames whose right image is missing are
    skipped. Ordering is lexicographic (deterministic across hosts)."""
    splits: Dict[str, List[Tuple[str, str]]] = {}
    for split in ("train", "val", "test"):
        pairs: List[Tuple[str, str]] = []
        left_root = os.path.join(root, "leftImg8bit", split)
        for dirpath, _dirnames, files in sorted(os.walk(left_root)):
            for fname in sorted(files):
                if "_leftImg8bit." not in fname:
                    continue
                left_rel = os.path.relpath(os.path.join(dirpath, fname), root)
                # swap both occurrences (split dir + file suffix) on the
                # root-relative path so a "leftImg8bit" substring in the
                # root path itself stays untouched
                right_rel = left_rel.replace("leftImg8bit", "rightImg8bit")
                if not os.path.isfile(os.path.join(root, right_rel)):
                    continue
                pairs.append((left_rel, right_rel))
        splits[split] = pairs
    return splits


def split_pairs(pairs: List[Tuple[str, str]], val_frac: float,
                test_frac: float, seed: int = 0):
    """Deterministic shuffled split into train/val/test."""
    order = np.random.default_rng(seed).permutation(len(pairs))
    n_val = int(len(pairs) * val_frac)
    n_test = int(len(pairs) * test_frac)
    val = [pairs[i] for i in order[:n_val]]
    test = [pairs[i] for i in order[n_val:n_val + n_test]]
    train = [pairs[i] for i in order[n_val + n_test:]]
    return {"train": train, "val": val, "test": test}


def write_manifest(path: str, pairs: List[Tuple[str, str]]) -> None:
    """Alternating x/y lines (reference DataProvider.py:119-126)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        for x, y in pairs:
            f.write(x + "\n" + y + "\n")


def main(argv=None) -> None:
    p = argparse.ArgumentParser(
        description="pair-manifest generator (KITTI, Cityscapes)")
    p.add_argument("--kitti_root", required=True,
                   help="dataset root (name kept for compatibility; for "
                        "--dataset cityscapes pass the Cityscapes root "
                        "holding leftImg8bit/ and rightImg8bit/)")
    p.add_argument("--dataset", choices=("kitti", "cityscapes"),
                   default="kitti",
                   help="cityscapes uses the dataset's own city-disjoint "
                        "train/val/test directories (stereo only; no "
                        "split rule applies) and writes the manifests "
                        "configs/ae_cityscapes_stereo points at")
    p.add_argument("--out_dir", default="data_paths")
    p.add_argument("--mode", choices=("stereo", "general"), default="stereo")
    p.add_argument("--split_rule", choices=("reference", "random"),
                   default="reference",
                   help="'reference' reproduces the reference's frozen "
                        "lists: stereo mode line-for-line (1576/790/790); "
                        "general mode by derived rule (912/3607 eval pairs "
                        "over the same universe, but a different seeded "
                        "membership draw — the reference's unseeded "
                        "shuffle is unrecoverable; load the frozen files "
                        "for its exact eval sample). 'random' is a seeded "
                        "fractional split over all frames")
    p.add_argument("--val_frac", type=float, default=None,
                   help="random rule only (default 0.2); the reference "
                        "rule's splits are fixed by derivation")
    p.add_argument("--test_frac", type=float, default=None,
                   help="random rule only (default 0.2)")
    p.add_argument("--max_offset", type=int, default=None,
                   help="random general rule only (default 2); the "
                        "reference general rule is fixed at ±3")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    if args.dataset == "cityscapes":
        if args.mode != "stereo":
            raise SystemExit("--dataset cityscapes supports only "
                             "--mode stereo (no general-pairing rule "
                             "exists for it)")
        bad = [name for name, v in (("--val_frac", args.val_frac),
                                    ("--test_frac", args.test_frac),
                                    ("--max_offset", args.max_offset))
               if v is not None]
        if bad:
            raise SystemExit(
                f"{', '.join(bad)} cannot be combined with --dataset "
                "cityscapes: it ships its own train/val/test directories")
        splits = cityscapes_stereo_splits(args.kitti_root)
        if not any(splits.values()):
            raise SystemExit("no leftImg8bit/rightImg8bit pairs under "
                             f"{args.kitti_root}")
        for split, split_list in splits.items():
            out = os.path.join(args.out_dir, f"cityscapes_stereo_{split}.txt")
            write_manifest(out, split_list)
            print(f"{out}: {len(split_list)} pairs")
        return

    if args.split_rule == "reference":
        ignored = [name for name, v in (("--val_frac", args.val_frac),
                                        ("--test_frac", args.test_frac),
                                        ("--max_offset", args.max_offset))
                   if v is not None]
        if ignored:
            raise SystemExit(
                f"{', '.join(ignored)} cannot be combined with "
                "--split_rule reference (its splits are fixed by the "
                "derived rule); use --split_rule random")
        splits = (reference_stereo_splits(args.kitti_root)
                  if args.mode == "stereo"
                  else reference_general_splits(args.kitti_root, args.seed))
    else:
        max_offset = 2 if args.max_offset is None else args.max_offset
        pairs = (stereo_pairs(args.kitti_root) if args.mode == "stereo"
                 else general_pairs(args.kitti_root, max_offset, args.seed))
        splits = split_pairs(
            pairs, 0.2 if args.val_frac is None else args.val_frac,
            0.2 if args.test_frac is None else args.test_frac, args.seed)
    if not any(splits.values()):
        raise SystemExit(f"no image_2/image_3 pairs under {args.kitti_root}")
    for split, split_list in splits.items():
        out = os.path.join(args.out_dir,
                           f"KITTI_{args.mode}_{split}.txt")
        write_manifest(out, split_list)
        print(f"{out}: {len(split_list)} pairs")


if __name__ == "__main__":
    main()
