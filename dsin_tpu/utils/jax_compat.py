"""Version-compat shims for JAX APIs that move between releases.

jaxlint's `bare-experimental-import` rule points every other module here:
this is the ONE file allowed to touch `jax.experimental` directly, so the
next upstream API move is absorbed in one place instead of N call sites.

Current shims:
  * `shard_map` — `jax.shard_map` graduated out of jax.experimental (and
    renamed its replication-checker kwarg `check_rep` -> `check_vma` on
    the way). Callers use the new spelling; older jax falls back to
    `jax.experimental.shard_map.shard_map` with the kwarg mapped.
  * `pl` / `pltpu` — Pallas has no stable import path yet; import it here
    once, `None` when this jax build ships without it (CPU-only builds),
    and let `require_pallas()` raise a actionable error at use time.
"""

from __future__ import annotations

import jax

__all__ = ["shard_map", "pl", "pltpu", "require_pallas"]


if hasattr(jax, "shard_map"):
    _shard_map_impl = jax.shard_map
    _CHECK_KW = "check_vma"
else:
    from jax.experimental.shard_map import shard_map as _shard_map_impl
    _CHECK_KW = "check_rep"


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """`jax.shard_map` when this jax has it, else the jax.experimental
    ancestor. `check_vma` maps onto the older `check_rep` — both toggle
    the same replication/varying-axes validity checker."""
    return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, **{_CHECK_KW: check_vma})


try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except ImportError:          # CPU-only / minimal jax builds
    pl = None
    pltpu = None


def require_pallas() -> None:
    """Raise with a config hint when Pallas is missing from this build."""
    if pl is None:
        raise ImportError(
            f"jax.experimental.pallas is unavailable in this jax build "
            f"({jax.__version__}) — set sifinder_impl to 'xla' or "
            f"'xla_tiled' instead of 'pallas'")
