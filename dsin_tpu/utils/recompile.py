"""Runtime recompilation sentinel: count XLA compiles, fail on excess.

Silent recompilation is the JAX failure mode pytest cannot see: a step
function that retraces per call (shape drift, container captures, weak
types) still returns correct numbers — it just burns minutes of TPU time
per step. jaxlint (tools/jaxlint) catches the static patterns; this
module catches the rest at runtime by counting backend compiles through
`jax.monitoring`'s event stream and comparing against a budget.

The counter is process-global and monotonic (jax.monitoring offers no
listener removal, so ONE listener registers on first use and everything
else diffs snapshots of its count). Per-function attribution works by
snapshotting around calls — valid under the tests' single-threaded use.

Use:
    with CompilationSentinel(budget=1, label="train_step"):
        step(state, x, y)          # raises if > 1 compile happens

    step = watch(jax.jit(fn), budget=2)   # cumulative budget per wrapper

    @pytest.mark.compile_budget(2)        # via tests/conftest.py
    def test_step_compiles_once(...): ...
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax.monitoring

from dsin_tpu.utils import locks as locks_lib

#: events that mean "XLA built a new executable". jaxpr_trace fires for
#: cheap retraces that hit the executable cache; backend_compile is the
#: expensive one the budget is about. NOTE: a persistent-cache load
#: (utils/cache.py) also fires backend_compile_duration — the retrieval
#: happens inside jax's compile path — so it counts here AND in the
#: cache-hit counter below; `compiles - cache_hits` is the number of
#: executables actually built from scratch.
_COMPILE_EVENTS = frozenset({
    "/jax/core/compile/backend_compile_duration",
})

#: fired when jax's persistent compilation cache served the executable
#: instead of XLA building it (observed on jax 0.4.37).
_CACHE_HIT_EVENTS = frozenset({
    "/jax/compilation_cache/cache_retrieval_time_sec",
})

_lock = locks_lib.RankedLock("recompile.counter")
_installed = False                    # guarded-by: _lock (module)
_count = 0                            # guarded-by: _lock (module)
_cache_hits = 0                       # guarded-by: _lock (module)


def _listener(event: str, duration: float, **kwargs) -> None:
    global _count, _cache_hits
    if event in _COMPILE_EVENTS:
        with _lock:
            _count += 1
    elif event in _CACHE_HIT_EVENTS:
        with _lock:
            _cache_hits += 1


def install() -> None:
    """Register the global compile listener (idempotent)."""
    global _installed
    with _lock:
        if _installed:
            return
        _installed = True
    jax.monitoring.register_event_duration_secs_listener(_listener)


def compilation_count() -> int:
    """Backend compiles observed process-wide since install()."""
    install()
    with _lock:
        return _count


def cache_hit_count() -> int:
    """Persistent-compilation-cache hits observed since install(). Each
    hit ALSO increments compilation_count() (jax fires both events), so
    a region whose compile delta equals its cache-hit delta built zero
    new executables — the warm-restart property serve warmup reports."""
    install()
    with _lock:
        return _cache_hits


class RecompilationBudgetExceeded(AssertionError):
    """More XLA compiles than the declared budget — a hot function is
    being rebuilt instead of reused."""


class CompilationSentinel:
    """Context manager: fail when the region compiles more than `budget`
    times. `raise_on_exceed=False` turns it into a pure counter
    (`.compilations` after exit)."""

    def __init__(self, budget: int, label: str = "",
                 raise_on_exceed: bool = True):
        if budget < 0:
            raise ValueError(f"budget must be >= 0, got {budget}")
        self.budget = budget
        self.label = label
        self.raise_on_exceed = raise_on_exceed
        self.compilations: Optional[int] = None

    def __enter__(self) -> "CompilationSentinel":
        install()
        self._start = compilation_count()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.compilations = compilation_count() - self._start
        # never mask an in-flight exception with the budget report
        if exc_type is None and self.raise_on_exceed \
                and self.compilations > self.budget:
            what = f" [{self.label}]" if self.label else ""
            raise RecompilationBudgetExceeded(
                f"compilation budget exceeded{what}: {self.compilations} "
                f"XLA compiles > budget {self.budget} — a jitted function "
                f"is recompiling (shape/dtype drift, non-static capture, "
                f"or a fresh wrapper per call)")


def watch(fn: Callable, budget: int, label: Optional[str] = None
          ) -> Callable:
    """Wrap a (jitted) callable with a CUMULATIVE compile budget across
    all its calls: call #1 may compile, steady-state calls must not.
    The wrapper exposes `.compilations` for inspection."""
    name = label or getattr(fn, "__name__", repr(fn))

    @functools.wraps(fn, updated=())
    def wrapper(*args, **kwargs):
        install()
        before = compilation_count()
        result = fn(*args, **kwargs)   # an fn error propagates unmasked
        wrapper.compilations += compilation_count() - before
        if wrapper.compilations > budget:
            raise RecompilationBudgetExceeded(
                f"[{name}] compiled {wrapper.compilations} times, "
                f"budget {budget} — the step function is recompiling "
                f"instead of reusing its executable")
        return result

    wrapper.compilations = 0
    return wrapper
