"""Named, hierarchy-ranked lock wrappers — the runtime half of threadlint.

PRs 2-4 grew a threaded serving stack whose safety contracts lived in
docstrings ("the schedule cache is lock-guarded", "callbacks under the
batcher lock must stay leaf-locked"). This module turns the two
contracts a machine can check at runtime into code:

* **Lock ordering.** Every lock in `dsin_tpu/` is constructed through
  `RankedLock`/`RankedCondition` with a name from the repo-wide
  `HIERARCHY` below (raw `threading.Lock()` construction elsewhere is a
  threadlint finding, tools/jaxlint/concurrency.py). Acquires must be
  strictly rank-increasing per thread: taking a lock whose rank is <=
  any lock the thread already holds is a *lock-order inversion* — the
  shape every cross-thread deadlock needs — and raises
  `LockOrderViolation` at acquire time (long before an actual deadlock
  needs the unlucky interleaving to manifest). The check is two list
  reads behind one module flag, cheap enough to stay on in production.

* **Observability.** Per-lock acquisition / contention counts and
  hold-time totals aggregate by lock NAME (instances of the same rung,
  e.g. every `metrics.metric` leaf lock, share one ledger) and surface
  through `stats_snapshot()` — `serve/metrics.py` folds them into
  `/metrics`, and `tools/chaos_bench.py` asserts zero inversions under
  the seeded soak.

The repo lock hierarchy (rank ascending = acquire order outer->inner;
a thread holding rank r may only acquire ranks > r):

    rank  name                where
       1  serve.federation    federation member table / session pin map /
                              rollout-wave state (serve/federation.py)
                              — OUTERMOST rank of all: a federation
                              control op (wave promote, member evict,
                              reconcile) may call into a member
                              router's swap/rollback/health machinery,
                              which acquires serve.autoscale (2),
                              serve.frontdoor (4) and serve.replica (6)
       2  serve.autoscale     autoscaler control-loop state (serve/autoscale.py)
                              — OUTERMOST serve rank: one tick may hold
                              it across router.add_replica/drain_replica/
                              rollback calls, which acquire
                              serve.frontdoor (4) and serve.replica (6)
       3  serve.template      pre-warmed replica template slot
                              (serve/router.py): stock/admit/discard of
                              the paused spawn held in reserve. Below
                              serve.frontdoor because admitting the
                              template calls into the replica-table
                              machinery (4) and the template's own
                              replica lock (6); above serve.autoscale
                              because a scaler tick may drive
                              add_replica while holding 2
       4  serve.frontdoor     router replica table / per-class rr state (serve/router.py)
       6  serve.replica       per-replica pipe send + in-flight map (serve/router.py)
       7  serve.shmlane       shared-memory lane allocator free-scan
                              (serve/shmlane.py): claims/frees lanes in
                              one ring. Above serve.replica because
                              payload puts happen under the per-replica
                              send lock (6); below the batcher/future
                              rungs so a lane free in a done-callback
                              stays legal
      10  serve.batcher       MicroBatcher's condition (serve/batcher.py)
      12  serve.future        Future done-callback slot (serve/batcher.py)
      14  serve.admission     per-class outstanding counts (serve/router.py)
      15  serve.placement     bucket->device routing table (serve/placement.py)
      16  serve.session       side-information session LRU/TTL store
                              (serve/session.py)
      17  serve.model         live/prev/staged model-bundle pointers for
                              the hot-swap state machine (serve/swap.py)
      18  serve.watchdog      post-swap rollback-watchdog sample window
                              (serve/swap.py RollbackWatchdog)
      19  serve.quality       model-health telemetry state: coding-gap
                              sampler rotation, per-session SI-match
                              stats, canary baselines (serve/quality.py)
                              — above serve.session (evict hooks call
                              in from under 16) and below the trace/
                              metric leaves it reports into; never
                              nested with serve.watchdog (canary
                              verdicts are handed off outside the lock)
      20  serve.workers       worker-pool bookkeeping (serve/service.py)
      25  serve.entropy_proc  process-pool slot / child-death rebuild (serve/service.py)
      30  codec.engine        lazy incremental-engine slot (coding/codec.py)
      35  codec.schedules     per-shape schedule cache (coding/incremental.py)
      40  rans.native         native-library load (coding/rans.py)
      45  rans.counters       native-call count probe (coding/rans.py)
      50  serve.device_batch  shared device->host transfer (serve/service.py)
      60  faults.plan         fault-plan bookkeeping (utils/faults.py)
      70  recompile.counter   XLA compile listener (utils/recompile.py)
      80  metrics.registry    metric-name namespace (serve/metrics.py)
      85  serve.trace         trace-span / flight-recorder rings
                              (serve/trace.py) — near-leaf so every
                              layer can record events while holding its
                              own lock (the batcher resolves shed
                              victims whose callbacks record here), yet
                              the recorders can still bump metric
                              counters (rank 90). Ring and meta locks
                              share the rung and are never nested.
      90  metrics.metric      per-metric leaf locks (serve/metrics.py)

The leaf rungs are deliberately the metrics locks: every layer reports
into metrics (the batcher's `on_expired` callback fires under rank 10,
the supervisor increments counters under rank 20), so counters must be
acquirable while anything else is held — which is exactly "highest
rank". Growing the hierarchy: give a new lock a rank strictly between
its outermost caller and the innermost thing its critical section
touches; never reuse a rank (equal ranks cannot nest, by design).

Tests force interleavings deterministically through
`set_acquire_hook(fn)`: `fn(lock)` runs at the top of every acquire, so
a test can park one thread at a specific lock until another thread has
won the race (tests/test_serve_batcher.py's deadline-vs-drain races).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

#: the repo-wide lock hierarchy: name -> rank. See the module docstring
#: for the rationale per rung.
HIERARCHY: Dict[str, int] = {
    "serve.federation": 1,
    "serve.autoscale": 2,
    "serve.template": 3,
    "serve.frontdoor": 4,
    "serve.replica": 6,
    "serve.shmlane": 7,
    "serve.batcher": 10,
    "serve.future": 12,
    "serve.rebalance": 13,
    "serve.admission": 14,
    "serve.placement": 15,
    "serve.session": 16,
    "serve.model": 17,
    "serve.watchdog": 18,
    "serve.quality": 19,
    "serve.workers": 20,
    "serve.entropy_proc": 25,
    "codec.engine": 30,
    "codec.schedules": 35,
    "rans.native": 40,
    "rans.counters": 45,
    "serve.device_batch": 50,
    "faults.plan": 60,
    "recompile.counter": 70,
    "metrics.registry": 80,
    "serve.trace": 85,
    "metrics.metric": 90,
}


class LockOrderViolation(AssertionError):
    """A thread tried to acquire a lock at a rank <= one it already
    holds — the acquisition pattern every lock-order deadlock needs.
    Raised at acquire time so the bug surfaces deterministically instead
    of waiting for the losing interleaving in production."""


class _LockStats:
    """Per-NAME ledger (instances of a rung share it). Each ledger owns
    its own raw micro-lock so two different rungs' releases never
    serialize against each other — a single global stats mutex would
    funnel EVERY lock release in the process (including the hot
    metrics.metric leaves) through one point.

    Deliberate trade-off: same-rung instances DO share one micro-lock
    (every metrics.metric leaf updates the same ledger). The
    alternative — per-instance plain counters folded at snapshot time —
    needs a weak registry of every live lock and makes snapshots O(live
    instances) (_DeviceBatch mints one lock per batch). A shared
    uncontended raw-lock bump is ~100ns on a path that runs per
    request/batch, not per symbol; the serve/chaos relative perf gates
    hold with it in place. Revisit only if a profile shows this ledger
    contended."""

    __slots__ = ("lock", "acquisitions", "contentions", "hold_ms_total",
                 "max_hold_ms", "inversions")

    def __init__(self):
        # raw by necessity: the wrappers cannot bootstrap on themselves.
        # Leaf by construction — nothing under it touches another lock.
        # raw-lock ok: wrapper-internal per-ledger micro-lock; a RankedLock
        # here would recurse (the rule exempts lock_modules by stem)
        self.lock = threading.Lock()
        self.acquisitions = 0
        self.contentions = 0
        self.hold_ms_total = 0.0
        self.max_hold_ms = 0.0
        self.inversions = 0

    def zero_locked(self) -> None:
        self.acquisitions = 0
        self.contentions = 0
        self.hold_ms_total = 0.0
        self.max_hold_ms = 0.0
        self.inversions = 0

    def as_dict(self) -> dict:
        with self.lock:
            return {"acquisitions": self.acquisitions,
                    "contentions": self.contentions,
                    "hold_ms_total": round(self.hold_ms_total, 3),
                    "max_hold_ms": round(self.max_hold_ms, 3),
                    "inversions": self.inversions}


# registry lock: guards the _stats dict shape and the inversion log
# ONLY (never the per-ledger counters — those live under each ledger's
# own micro-lock, see _LockStats). Raw by necessity, leaf by
# construction.
# raw-lock ok: the wrapper module's own internal leaf lock; cannot be
# a RankedLock without infinite regress (rule exempts lock_modules)
_meta_lock = threading.Lock()
_stats: Dict[str, _LockStats] = {}    # guarded-by: _meta_lock (module)
_inversion_log: List[str] = []        # guarded-by: _meta_lock (module)

_tls = threading.local()            # per-thread stack of held RankedLocks

#: one module flag for every assert-style check (ordering + equal-rank
#: nesting). Default ON — the checks are two list reads per acquire.
_enforce = os.environ.get("DSIN_LOCK_CHECKS", "1") != "0"

#: test-only deterministic interleaving point: called as fn(lock) at the
#: top of every acquire when set. One None check on the hot path.
_acquire_hook: Optional[Callable[["RankedLock"], None]] = None


def set_enforcement(on: bool) -> bool:
    """Flip the lock-discipline checks; returns the previous value."""
    global _enforce
    prev = _enforce
    _enforce = bool(on)
    return prev


def enforcement_enabled() -> bool:
    return _enforce


def set_acquire_hook(fn: Optional[Callable[["RankedLock"], None]]
                     ) -> Optional[Callable]:
    """Install (or clear, with None) the deterministic acquire hook.
    Returns the previous hook so tests can restore it."""
    global _acquire_hook
    prev = _acquire_hook
    _acquire_hook = fn
    return prev


def _held_stack() -> List["RankedLock"]:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def held_locks() -> Tuple[str, ...]:
    """Names of the locks the CURRENT thread holds, outermost first
    (diagnostics and tests)."""
    return tuple(lk.name for lk in _held_stack())


def _stats_for(name: str) -> _LockStats:
    with _meta_lock:
        s = _stats.get(name)
        if s is None:
            s = _stats[name] = _LockStats()
        return s


def stats_snapshot() -> Dict[str, dict]:
    """{name: {acquisitions, contentions, hold_ms_total, max_hold_ms,
    inversions}} for every lock name seen so far."""
    with _meta_lock:
        return {name: s.as_dict() for name, s in sorted(_stats.items())}


def inversion_count() -> int:
    with _meta_lock:
        return len(_inversion_log)


def inversions() -> List[str]:
    """The recorded inversion descriptions ("held -> attempted")."""
    with _meta_lock:
        return list(_inversion_log)


def reset_stats() -> None:
    """Zero every ledger and the inversion log (benches and tests).
    Ledgers are zeroed IN PLACE — existing RankedLock instances cache a
    reference to theirs at construction, so dropping the dict would
    orphan every pre-existing lock's accounting."""
    with _meta_lock:
        for s in _stats.values():
            with s.lock:
                s.zero_locked()
        _inversion_log.clear()


class RankedLock:
    """A named `threading.Lock` with hierarchy enforcement and stats.

    `name` must appear in `HIERARCHY` unless an explicit `rank` is
    given (ad-hoc ranks are for tests; production locks belong in the
    table so the repo has ONE ordering story).
    """

    __slots__ = ("name", "rank", "_lock", "_stats", "_t_acquire")

    def __init__(self, name: str, rank: Optional[int] = None):
        if rank is None:
            rank = HIERARCHY.get(name)
            if rank is None:
                raise ValueError(
                    f"lock name {name!r} is not in the repo hierarchy — "
                    f"add it to dsin_tpu/utils/locks.HIERARCHY (or pass "
                    f"an explicit rank= in tests)")
        self.name = name
        self.rank = int(rank)
        # raw-lock ok: this IS the sanctioned wrapper; the one place raw
        # primitives are built (the rule exempts lock_modules by stem)
        self._lock = threading.Lock()
        self._stats = _stats_for(name)
        self._t_acquire = 0.0

    # -- discipline ---------------------------------------------------------

    def _check_order(self) -> None:
        stack = _held_stack()
        if not stack:
            return
        top = stack[-1]
        # the stack is rank-sorted by induction (every push passed this
        # check), so comparing against the top suffices
        if top.rank >= self.rank:
            desc = (f"{top.name}(rank {top.rank}) -> "
                    f"{self.name}(rank {self.rank})")
            with _meta_lock:
                _inversion_log.append(desc)
            with self._stats.lock:
                self._stats.inversions += 1
            raise LockOrderViolation(
                f"lock-order inversion: thread "
                f"{threading.current_thread().name!r} holds {top.name} "
                f"(rank {top.rank}) and tried to acquire {self.name} "
                f"(rank {self.rank}) — acquires must be strictly "
                f"rank-increasing (hierarchy: dsin_tpu/utils/locks.py)")

    def _note_acquired(self) -> None:
        _held_stack().append(self)
        self._t_acquire = time.monotonic()

    def _note_released(self) -> None:
        held_ms = (time.monotonic() - self._t_acquire) * 1e3
        stack = _held_stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                break
        s = self._stats
        with s.lock:
            s.acquisitions += 1
            s.hold_ms_total += held_ms
            if held_ms > s.max_hold_ms:
                s.max_hold_ms = held_ms

    # -- lock API -----------------------------------------------------------

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        hook = _acquire_hook
        if hook is not None:
            hook(self)
        if _enforce:
            self._check_order()
        if self._lock.acquire(False):
            self._note_acquired()
            return True
        with self._stats.lock:
            self._stats.contentions += 1
        if not blocking:
            return False
        if not self._lock.acquire(True, timeout):
            return False
        self._note_acquired()
        return True

    def release(self) -> None:
        self._note_released()
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "RankedLock":
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"RankedLock({self.name!r}, rank={self.rank})"


class RankedCondition:
    """A `threading.Condition` over a RankedLock: `with cond:` runs the
    ordering check and stats; `wait()` books the release/re-acquire the
    underlying condition performs, so hold-time excludes the sleep and
    the per-thread held-stack stays truthful while waiting."""

    __slots__ = ("_rlock", "_cond")

    def __init__(self, name: str, rank: Optional[int] = None):
        self._rlock = RankedLock(name, rank)
        # raw-lock ok: wrapper-internal — the Condition shares the
        # RankedLock's raw lock so wait() keeps single-lock semantics
        self._cond = threading.Condition(self._rlock._lock)

    @property
    def name(self) -> str:
        return self._rlock.name

    @property
    def rank(self) -> int:
        return self._rlock.rank

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        return self._rlock.acquire(blocking, timeout)

    def release(self) -> None:
        self._rlock.release()

    def __enter__(self) -> "RankedCondition":
        self._rlock.acquire()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self._rlock.release()

    def wait(self, timeout: Optional[float] = None) -> bool:
        if _enforce:
            stack = _held_stack()
            if not stack or stack[-1] is not self._rlock:
                # waiting while holding an INNER lock is the same
                # deadlock shape as an inverted acquire (the inner lock
                # stays held across the park, and the mid-stack pop
                # would also break _check_order's rank-sorted-stack
                # invariant) — refuse it the same way
                inner = [lk.name for lk in stack
                         if lk is not self._rlock]
                desc = f"wait on {self.name} while holding {inner}"
                with _meta_lock:
                    _inversion_log.append(desc)
                with self._rlock._stats.lock:
                    self._rlock._stats.inversions += 1
                raise LockOrderViolation(
                    f"{self.name}.wait() called while the thread holds "
                    f"inner locks {inner} — those stay locked for the "
                    f"whole park, deadlocking whoever must notify; "
                    f"release them before waiting")
        # the condition releases the raw lock internally; mirror that in
        # the wrapper's books so (a) hold-time measures the critical
        # section, not the sleep, and (b) the held-stack does not claim
        # a lock the thread does not hold while parked
        self._rlock._note_released()
        try:
            return self._cond.wait(timeout)
        finally:
            self._rlock._note_acquired()

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()

    def __repr__(self) -> str:
        return f"RankedCondition({self.name!r}, rank={self.rank})"
