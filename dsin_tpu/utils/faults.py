"""Deterministic fault injection: seeded plans over named sites.

The failure modes that kill learned-codec deployments — a worker thread
dying mid-batch, a flipped bit in an rANS payload, a kill landing in the
middle of a checkpoint save — are exactly the ones ordinary tests never
exercise, because they cannot be provoked from the public API. This
module plants named *injection sites* at those spots; a seeded
`FaultPlan` decides, deterministically per visit, whether a site raises,
delays, or corrupts bytes. tools/chaos_bench.py and the chaos-marked
tests drive the recovery paths through real failures instead of mocks.

Canonical sites (free-form strings; these are the ones wired in):

    serve.worker.batch   top of a serve worker's batch processing
    serve.rans           decode-side entropy payload bytes (worker-side)
    serve.swap           the model hot-swap windows (after the incoming
                         params load in prepare, and the commit window
                         right before the atomic bundle swap)
    ckpt.write           each durable checkpoint file write
    ckpt.swap            the window between the checkpoint swap renames
    ckpt.manifest        manifest.json bytes as a loader reads them
                         (corrupt = the torn/rotted-manifest scenario)
    io.read              CLI stream-file reads

Hot-path cost: `inject(site)` / `corrupt(site, data)` are a single
module-global read when no plan is installed — production pays one
`is None` check per site visit, nothing else. Plans are process-global
and thread-safe (serve workers visit sites concurrently); decisions come
from one seeded `random.Random`, so a (seed, visit-sequence) pair always
produces the same faults.
"""

from __future__ import annotations

import random
import time
from collections import Counter
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple

from dsin_tpu.utils import locks as locks_lib

SITES = ("serve.worker.batch", "serve.rans", "serve.swap", "serve.session",
         "serve.shm.lane", "ckpt.write", "ckpt.swap", "ckpt.manifest",
         "io.read")

ACTIONS = ("raise", "crash", "delay", "corrupt")


class InjectedFault(RuntimeError):
    """The ordinary injected failure: an `Exception`, so per-request
    isolation (`except Exception`) may answer it like any other error."""


class InjectedCrash(BaseException):
    """Deliberately NOT an `Exception`: models the conditions that must
    kill a worker thread outright (the class of errors `except
    Exception:` recovery code is required to let through — the
    supervisor, not the batch loop, owns this failure)."""


@dataclass
class FaultSpec:
    """One rule: at `site`, from visit `after + 1` on, fire `action` with
    `probability` per visit, at most `times` activations total.

    Actions: ``raise`` raises `exc()` (default InjectedFault);
    ``crash`` raises InjectedCrash; ``delay`` sleeps `delay_s`;
    ``corrupt`` flips `flips` bits of the bytes passed to `corrupt()`
    (a no-op at sites visited through bare `inject()`).
    """

    site: str
    action: str = "raise"
    probability: float = 1.0
    after: int = 0
    times: Optional[int] = None
    delay_s: float = 0.01
    flips: int = 1
    exc: Optional[Callable[[], BaseException]] = None

    def __post_init__(self):
        if self.action not in ACTIONS:
            raise ValueError(f"unknown action {self.action!r}; "
                             f"expected one of {ACTIONS}")
        if not 0.0 <= self.probability <= 1.0:
            raise ValueError(f"probability must be in [0, 1], "
                             f"got {self.probability}")


@dataclass
class Activation:
    """One fired fault, for post-run assertions (chaos_bench's ledger)."""

    site: str
    action: str
    visit: int          # 1-based visit index at the site when it fired


class FaultPlan:
    """A seeded set of FaultSpecs plus the bookkeeping to replay it.

    `visits` counts every site visit (fired or not); `activations`
    counts fired faults per site; `log` records each firing in order.
    All three are safe to read after the run for assertions.
    """

    def __init__(self, specs: Sequence[FaultSpec], seed: int = 0):
        self.specs: List[FaultSpec] = list(specs)
        self.seed = seed
        self.visits: Counter = Counter()
        self.activations: Counter = Counter()
        self.log: List[Activation] = []
        self._rng = random.Random(seed)     # guarded-by: self._lock
        self._fired = [0] * len(self.specs)  # guarded-by: self._lock
        self._lock = locks_lib.RankedLock("faults.plan")

    def _select(self, site: str) -> Optional[Tuple[FaultSpec, int]]:
        """Count one visit at `site`; return the first spec that fires
        (and the visit index), consuming one of its activations."""
        with self._lock:
            self.visits[site] += 1
            visit = self.visits[site]
            for i, spec in enumerate(self.specs):
                if spec.site != site:
                    continue
                if visit <= spec.after:
                    continue
                if spec.times is not None and self._fired[i] >= spec.times:
                    continue
                if (spec.probability < 1.0
                        and self._rng.random() >= spec.probability):
                    continue
                self._fired[i] += 1
                self.activations[site] += 1
                self.log.append(Activation(site, spec.action, visit))
                return spec, visit
        return None

    def _corrupt_bytes(self, spec: FaultSpec, data: bytes) -> bytes:
        if not data:
            return data
        out = bytearray(data)
        with self._lock:
            for _ in range(spec.flips):
                bit = self._rng.randrange(len(out) * 8)
                out[bit // 8] ^= 1 << (bit % 8)
        return bytes(out)


_ACTIVE: Optional[FaultPlan] = None


def install(plan: FaultPlan) -> FaultPlan:
    """Make `plan` the process-global active plan (replacing any)."""
    global _ACTIVE
    _ACTIVE = plan
    return plan


def uninstall() -> None:
    """Remove the active plan; every site becomes a no-op again."""
    global _ACTIVE
    _ACTIVE = None


def active() -> Optional[FaultPlan]:
    return _ACTIVE


@contextmanager
def installed(plan: FaultPlan):
    """Scoped install: restores whatever plan (or None) was active."""
    global _ACTIVE
    prev = _ACTIVE
    _ACTIVE = plan
    try:
        yield plan
    finally:
        _ACTIVE = prev


def _fire(spec: FaultSpec, site: str,
          data: Optional[bytes]) -> Optional[bytes]:
    if spec.action == "delay":
        # sleep OUTSIDE the plan lock: a delayed site must not serialize
        # the other workers' visits behind it
        time.sleep(spec.delay_s)
        return data
    if spec.action == "corrupt":
        if data is None:
            return None
        return _ACTIVE._corrupt_bytes(spec, data) if _ACTIVE else data
    if spec.action == "crash":
        raise InjectedCrash(f"injected crash at {site}")
    exc = spec.exc() if spec.exc is not None else InjectedFault(
        f"injected fault at {site}")
    raise exc


def inject(site: str) -> None:
    """Visit `site`: no-op without a plan; otherwise the plan may raise
    or delay here. `corrupt` specs never act through this entry."""
    plan = _ACTIVE
    if plan is None:
        return
    hit = plan._select(site)
    if hit is not None:
        _fire(hit[0], site, None)


def corrupt(site: str, data: bytes) -> bytes:
    """Pass `data` through `site`: returned unchanged without a plan;
    a firing spec may corrupt it, delay, or raise."""
    plan = _ACTIVE
    if plan is None:
        return data
    hit = plan._select(site)
    if hit is None:
        return data
    out = _fire(hit[0], site, data)
    return data if out is None else out
