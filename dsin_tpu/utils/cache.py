"""One shared setup for jax's persistent compilation cache.

Every measurement entry point (bench.py, tools/*, __graft_entry__)
needs the same three lines; the policy they encode is subtle enough that
the copies had already started to drift, so it lives here once:

- the cache dir is keyed by BACKEND (``.cache/jax-<backend>``): XLA:CPU
  AOT cache entries embed the compile machine's CPU features, and
  through the axon relay the compiling machine differs from this host —
  sharing one dir across backends poisons the cache (feature-mismatch
  load errors, SIGILL risk);
- ``.cache/`` is gitignored, so the driver's between-session clean
  leaves it alone and second compiles stay warm across rounds;
- the 1 s min-compile-time floor keeps thousands of trivial executables
  out of the cache.
"""

from __future__ import annotations

import os


def enable_compilation_cache(tag: str | None = None) -> str:
    """Point jax's persistent compilation cache at repo ``.cache/jax-<tag>``
    (default tag: the default backend name). Returns the directory."""
    import jax

    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    cache_dir = os.path.join(repo, ".cache",
                             f"jax-{tag or jax.default_backend()}")
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    return cache_dir
