"""One shared setup for jax's persistent compilation cache.

Every measurement entry point (bench.py, tools/*, __graft_entry__)
needs the same three lines; the policy they encode is subtle enough that
the copies had already started to drift, so it lives here once:

- the cache dir is keyed by BACKEND (``.cache/jax-<backend>``): XLA:CPU
  AOT cache entries embed the compile machine's CPU features, and
  through the axon relay the compiling machine differs from this host —
  sharing one dir across backends poisons the cache (feature-mismatch
  load errors, SIGILL risk);
- CPU-backed dirs are additionally keyed by a HOST CPU-FEATURE
  FINGERPRINT: ``.cache/`` survives the driver's between-session clean
  (gitignored), and consecutive rounds can land on hosts with different
  CPU features — an AOT entry compiled on last round's host then loads
  here with a machine-feature-mismatch error and explicit SIGILL risk
  in the tail of driver artifacts (seen in MULTICHIP_r04.json). Keying
  the dir by the feature set makes a mismatched entry unfindable
  instead of load-and-hope;
- ``.cache/`` is gitignored, so the driver's between-session clean
  leaves it alone and second compiles stay warm across rounds;
- the 1 s min-compile-time floor keeps thousands of trivial executables
  out of the cache.
"""

from __future__ import annotations

import hashlib
import os


def host_cpu_fingerprint() -> str:
    """8-hex digest of this host's CPU feature flags (/proc/cpuinfo).

    Order-normalized so kernels that list the same features differently
    still share a cache dir. Falls back to "nofp" where /proc/cpuinfo
    is unavailable (non-Linux), collapsing to the old per-backend key.
    """
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    flags = " ".join(sorted(line.split(":", 1)[1].split()))
                    return hashlib.sha256(flags.encode()).hexdigest()[:8]
    except OSError:
        pass
    return "nofp"


def enable_compilation_cache(tag: str | None = None) -> str:
    """Point jax's persistent compilation cache at repo
    ``.cache/jax-<tag>[-<host fingerprint>]`` (default tag: the default
    backend name; the fingerprint joins for CPU-executed code, where
    XLA AOT-compiles to this host's machine features). Returns the
    directory. A ``DSIN_COMPILATION_CACHE_DIR`` env var overrides the
    policy dir entirely (tests use it for stale-entry isolation)."""
    import jax

    override = os.environ.get("DSIN_COMPILATION_CACHE_DIR")
    if override:
        # Explicit dir override (tests/conftest.py points this at a
        # per-session tmpdir): cross-SESSION AOT entries stay out of
        # the run — deserializing a stale CPU executable mid-suite has
        # produced GC-time heap corruption (segfault in the training
        # tests once serve tests had enabled the shared cache in
        # process) — while cross-PROCESS warming within the run (serve
        # replicas, restart tests) still shares one dir via the
        # inherited environment.
        cache_dir = override
    else:
        repo = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        tag = tag or jax.default_backend()
        # Any cpu-tagged cache (including the dryrun's explicit
        # "dryrun-cpu") holds host-feature-specific AOT results; TPU
        # executables are compiled relay-side for the chip and are
        # host-portable.
        if "cpu" in tag:
            tag = f"{tag}-{host_cpu_fingerprint()}"
        cache_dir = os.path.join(repo, ".cache", f"jax-{tag}")
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    # jax latches the cache's initialized-ness at the FIRST backend
    # compile: if anything compiled before this call (a long-lived
    # process starting a serve instance late, a test suite), the dir
    # update above is silently ignored — no writes, no reads. Reset so
    # the new dir takes effect; a no-op when nothing compiled yet.
    try:
        from jax._src.compilation_cache import reset_cache
        reset_cache()
    except Exception:  # noqa: BLE001 — private API; cache stays best-effort
        pass
    return cache_dir
