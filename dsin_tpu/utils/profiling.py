"""XLA profiler integration (a subsystem the reference lacks entirely —
its only aid is `report_tensor_allocations_upon_oom`, reference AE.py:7).

Captures a windowed device trace of the training loop viewable in
TensorBoard / Perfetto: `StepProfiler` starts `jax.profiler` at a chosen
step and stops it N steps later; `StepTraceAnnotation` marks step boundaries
so per-step timelines line up in the viewer.
"""

from __future__ import annotations

import contextlib
import os
from typing import Optional

import jax


class StepProfiler:
    """Trace steps [start_step, start_step + num_steps) into `trace_dir`.

    Call `step(i)` once per loop iteration (before running the step).
    No-ops entirely when trace_dir is None.
    """

    def __init__(self, trace_dir: Optional[str], start_step: int = 5,
                 num_steps: int = 3):
        self.trace_dir = trace_dir
        self.start_step = start_step
        self.stop_step = start_step + num_steps
        self._active = False

    def step(self, i: int) -> None:
        if self.trace_dir is None:
            return
        if not self._active and i == self.start_step:
            os.makedirs(self.trace_dir, exist_ok=True)
            jax.profiler.start_trace(self.trace_dir)
            self._active = True
        elif self._active and i >= self.stop_step:
            self.stop()

    @property
    def active(self) -> bool:
        """True while a trace window is open (callers that pipeline device
        work must drain it before the window closes)."""
        return self._active

    def stop(self) -> None:
        if self._active:
            jax.profiler.stop_trace()
            self._active = False

    def annotation(self, i: int):
        """Step-scoped trace annotation (no-op context when disabled)."""
        if self.trace_dir is None:
            return contextlib.nullcontext()
        return jax.profiler.StepTraceAnnotation("train_step", step_num=i)
