"""Observability: step timing, scalar logging, device memory stats."""

from dsin_tpu.utils.logging import (JsonlLogger, StepTimer, color_print,
                                    device_memory_stats)

__all__ = ["JsonlLogger", "StepTimer", "color_print", "device_memory_stats"]
