"""Observability: step timing, scalar logging, device memory stats,
XLA trace capture."""

from dsin_tpu.utils.cache import enable_compilation_cache
from dsin_tpu.utils.logging import (JsonlLogger, StepTimer, color_print,
                                    device_memory_stats)
from dsin_tpu.utils.profiling import StepProfiler
from dsin_tpu.utils.recompile import (CompilationSentinel,
                                      RecompilationBudgetExceeded,
                                      compilation_count, watch)
from dsin_tpu.utils.signals import install_interrupt_handlers

__all__ = ["JsonlLogger", "StepTimer", "color_print", "device_memory_stats",
           "StepProfiler", "install_interrupt_handlers",
           "enable_compilation_cache", "CompilationSentinel",
           "RecompilationBudgetExceeded", "compilation_count", "watch"]
