"""Observability: step timing, scalar logging, device memory stats,
XLA trace capture."""

from dsin_tpu.utils.logging import (JsonlLogger, StepTimer, color_print,
                                    device_memory_stats)
from dsin_tpu.utils.profiling import StepProfiler

__all__ = ["JsonlLogger", "StepTimer", "color_print", "device_memory_stats",
           "StepProfiler"]
