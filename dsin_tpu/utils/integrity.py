"""Stream integrity: CRC32 framing checks + the typed error they raise.

The DSIN context-model coupling makes payload corruption uniquely
silent: a flipped bit in the rANS stream desynchronizes the decoder's
PMFs from the encoder's, and every symbol after the flip decodes to a
*plausible* wrong value — the output is a clean-looking garbage image,
not a crash. The rANS layer cannot detect this (any byte string is a
syntactically valid rANS stream), so integrity must live in the framing:
DSIM v3 (coding/cli.py) and DSRV v2 (serve/service.py) carry a CRC32
over header fields + payload, verified before any entropy decode.

`IntegrityError` subclasses ValueError so every existing "bad stream"
handler (the CLI's one-line exit 2, the serve worker's per-request
isolation) already routes it correctly, while callers that care can
still catch the distinct type.
"""

from __future__ import annotations

import zlib


class IntegrityError(ValueError):
    """A stream failed its CRC: corrupted in transit or on disk. The
    payload must not be entropy-decoded (it would yield a plausible but
    wrong reconstruction, silently)."""


def frame_crc(*chunks: bytes) -> int:
    """CRC32 over the concatenation of `chunks` (header fields then
    payload; the CRC field itself is never included)."""
    crc = 0
    for chunk in chunks:
        crc = zlib.crc32(chunk, crc)
    return crc & 0xFFFFFFFF


def verify_crc(expected: int, what: str, *chunks: bytes) -> None:
    """Raise IntegrityError unless `frame_crc(*chunks) == expected`."""
    got = frame_crc(*chunks)
    if got != expected:
        raise IntegrityError(
            f"{what}: CRC mismatch (stored 0x{expected:08x}, computed "
            f"0x{got:08x}) — the stream is corrupted; refusing to decode "
            f"it into a plausible wrong image")
