"""Training observability the reference never had (SURVEY §5: tqdm it/s and
an OOM flag were its only instrumentation): wall-clock step timing with
images/sec, structured scalar logging to JSONL, colored console summaries
(the `lazyme.color_print` role), and device-memory statistics.

Everything here is host-side and O(1) per step — safe on the hot loop.
"""

from __future__ import annotations

import collections
import json
import os
import sys
import time
from typing import Any, Dict, Optional

_ANSI = {"red": "\033[31m", "green": "\033[32m", "yellow": "\033[33m",
         "blue": "\033[34m", "magenta": "\033[35m", "cyan": "\033[36m"}


def color_print(msg: str, color: str = "cyan", bold: bool = False,
                file=None) -> None:
    """Colored console line; plain when not a TTY (so logs stay clean)."""
    file = file or sys.stdout
    if file.isatty() and color in _ANSI:
        prefix = _ANSI[color] + ("\033[1m" if bold else "")
        print(f"{prefix}{msg}\033[0m", file=file)
    else:
        print(msg, file=file)


class StepTimer:
    """Rolling wall-clock timing of training steps.

    Call `tick()` once per completed step (after blocking on the result);
    read `steps_per_sec` / `images_per_sec(batch)` over the window.
    """

    def __init__(self, window: int = 50):
        self._times = collections.deque(maxlen=window + 1)
        self.total_steps = 0
        self._start = time.perf_counter()

    def tick(self) -> None:
        self._times.append(time.perf_counter())
        self.total_steps += 1

    @property
    def steps_per_sec(self) -> float:
        if len(self._times) < 2:
            return 0.0
        dt = self._times[-1] - self._times[0]
        return (len(self._times) - 1) / dt if dt > 0 else 0.0

    def images_per_sec(self, batch_size: int) -> float:
        return self.steps_per_sec * batch_size

    @property
    def elapsed(self) -> float:
        return time.perf_counter() - self._start


class JsonlLogger:
    """Append-only JSONL scalar log: one {ts, step, **scalars} object per
    line. Cheap, crash-safe (line-buffered), trivially parseable."""

    def __init__(self, path: Optional[str]):
        self._f = None
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._f = open(path, "a", buffering=1)

    def log(self, step: int, scalars: Dict[str, Any], **extra: Any) -> None:
        if self._f is None:
            return
        rec = {"ts": round(time.time(), 3), "step": int(step)}
        for k, v in {**scalars, **extra}.items():
            try:
                rec[k] = float(v)
            except (TypeError, ValueError):
                rec[k] = v
        self._f.write(json.dumps(rec) + "\n")

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def device_memory_stats() -> Dict[str, Dict[str, int]]:
    """Per-device memory statistics (bytes_in_use / peak / limit) where the
    backend exposes them (TPU does; CPU returns {})."""
    import jax
    out: Dict[str, Dict[str, int]] = {}
    for dev in jax.local_devices():
        stats = None
        try:
            stats = dev.memory_stats()
        except (AttributeError, NotImplementedError, RuntimeError):
            pass
        if stats:
            out[str(dev)] = {k: int(v) for k, v in stats.items()
                             if isinstance(v, (int, float))}
    return out
