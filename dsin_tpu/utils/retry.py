"""Shared bounded-retry policy: one backoff curve for every recovery path.

Three subsystems retry transient failures — the serve supervisor
restarting crashed workers, durable checkpoint writes riding out
transient OSErrors, and the rANS native-backend loader forcing one
rebuild before falling back to pure Python. Each previously would have
grown its own ad-hoc loop; this module is the single policy object they
all share, so "capped exponential backoff" means the same thing (and is
tested once) everywhere.

Deterministic by design: no jitter. The delay for attempt k is
``min(max_delay_s, base_delay_s * backoff ** k)`` — reproducible under
the fault-injection harness (utils/faults.py), which is what makes
chaos runs replayable from a seed.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Optional, Tuple, Type


@dataclass(frozen=True)
class RetryPolicy:
    """Capped exponential backoff: attempt k (0-based) sleeps
    ``min(max_delay_s, base_delay_s * backoff ** k)`` before retrying.
    ``max_attempts`` counts total tries, not retries (1 = no retry)."""

    max_attempts: int = 3
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    backoff: float = 2.0

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, "
                             f"got {self.max_attempts}")
        if self.base_delay_s < 0 or self.max_delay_s < 0:
            raise ValueError("delays must be >= 0")
        if self.backoff < 1.0:
            raise ValueError(f"backoff must be >= 1, got {self.backoff}")

    def delay(self, attempt: int) -> float:
        """Backoff before retry number `attempt` (0-based)."""
        # cap the exponent: the serve supervisor feeds an ever-growing
        # per-slot restart count through here, and float `backoff **
        # attempt` raises OverflowError past ~2**1024 — which would kill
        # the supervisor thread mid-crash-loop. Beyond 64 doublings the
        # max_delay_s cap decides anyway (and backoff == 1 is constant).
        exponent = min(attempt, 64)
        return min(self.max_delay_s,
                   self.base_delay_s * self.backoff ** exponent)


def call_with_retry(fn: Callable, policy: RetryPolicy, *,
                    retry_on: Tuple[Type[BaseException], ...] = (Exception,),
                    on_retry: Optional[Callable[[int, BaseException],
                                                None]] = None,
                    sleep: Callable[[float], None] = time.sleep):
    """Call `fn()` up to `policy.max_attempts` times.

    Only exceptions matching `retry_on` are retried; anything else (and
    the final failure) propagates unmasked. `on_retry(attempt, exc)` runs
    before each backoff sleep — the hook recovery code uses to force a
    rebuild / reopen between attempts. `sleep` is injectable so tests
    assert the backoff curve without waiting it out.
    """
    attempt = 0
    while True:
        try:
            return fn()
        except retry_on as e:
            if attempt >= policy.max_attempts - 1:
                raise
            if on_retry is not None:
                on_retry(attempt, e)
            delay = policy.delay(attempt)
            if delay > 0:
                sleep(delay)
            attempt += 1
