"""Interrupt hardening for long training runs.

The reference is a single interactive process with no signal story
(reference main.py:21-126 — a Ctrl-C just kills it). Here, multi-hour
runs are routinely ended from outside — `timeout -s INT` watchdogs, the
relay watcher's deadline kill, driver cleanup — and the emergency
checkpoint in `Experiment.train` only fires if the signal unwinds Python
as an exception. Two launch quirks silently break that:

- A POSIX shell starting a run as an async (`&`) job with job control
  off sets SIGINT to SIG_IGN in the child (POSIX 2.11), and CPython then
  does NOT install its KeyboardInterrupt handler — `timeout -s INT`
  delivers a signal that is simply dropped, and the follow-up
  `--kill-after` SIGKILL loses everything since the last periodic
  checkpoint. Reinstalling `default_int_handler` unconditionally undoes
  the inherited ignore.
- SIGTERM's default action terminates the process without unwinding
  Python at all, so a plain `kill` (or `timeout` with its default
  signal) also skips the emergency save. Mapping it to KeyboardInterrupt
  routes it down the exact same tested path.

Installed at the top of `Experiment.train`; signal.signal is only legal
in the main thread, so installation is skipped (harmless) elsewhere —
e.g. when a test drives train() from a worker thread.
"""

from __future__ import annotations

import signal
import threading


def _raise_keyboard_interrupt(signum, frame):  # noqa: ARG001
    raise KeyboardInterrupt(f"signal {signum}")


def install_interrupt_handlers() -> bool:
    """Make SIGINT and SIGTERM unwind the process as KeyboardInterrupt.

    Returns True when handlers were installed (main thread), False when
    skipped. Idempotent; safe to call once per train() invocation.
    """
    if threading.current_thread() is not threading.main_thread():
        return False
    signal.signal(signal.SIGINT, signal.default_int_handler)
    signal.signal(signal.SIGTERM, _raise_keyboard_interrupt)
    return True


def install_drain_handlers(drain) -> bool:
    """Route SIGINT/SIGTERM to `drain()` instead of unwinding.

    The serving story (dsin_tpu/serve): a long-lived process must NOT die
    mid-batch on a deploy's SIGTERM — it stops ACCEPTING work and finishes
    what is in flight. `drain` must therefore be fast and non-blocking
    (flip a flag, close a queue); the actual wait for in-flight work
    happens in the serve loop, never inside a signal handler. A second
    signal falls back to the training handlers above, so a stuck drain can
    still be interrupted the ordinary way.

    Returns True when installed (main thread only — signal.signal is
    illegal elsewhere), False when skipped; the caller then drains via
    its own stop API instead.
    """
    if threading.current_thread() is not threading.main_thread():
        return False

    def _drain_once(signum, frame):  # noqa: ARG001
        install_interrupt_handlers()  # second signal: hard interrupt
        drain()

    signal.signal(signal.SIGINT, _drain_once)
    signal.signal(signal.SIGTERM, _drain_once)
    return True
