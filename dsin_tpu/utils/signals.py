"""Interrupt hardening for long training runs.

The reference is a single interactive process with no signal story
(reference main.py:21-126 — a Ctrl-C just kills it). Here, multi-hour
runs are routinely ended from outside — `timeout -s INT` watchdogs, the
relay watcher's deadline kill, driver cleanup — and the emergency
checkpoint in `Experiment.train` only fires if the signal unwinds Python
as an exception. Two launch quirks silently break that:

- A POSIX shell starting a run as an async (`&`) job with job control
  off sets SIGINT to SIG_IGN in the child (POSIX 2.11), and CPython then
  does NOT install its KeyboardInterrupt handler — `timeout -s INT`
  delivers a signal that is simply dropped, and the follow-up
  `--kill-after` SIGKILL loses everything since the last periodic
  checkpoint. Reinstalling `default_int_handler` unconditionally undoes
  the inherited ignore.
- SIGTERM's default action terminates the process without unwinding
  Python at all, so a plain `kill` (or `timeout` with its default
  signal) also skips the emergency save. Mapping it to KeyboardInterrupt
  routes it down the exact same tested path.

Installed at the top of `Experiment.train`; signal.signal is only legal
in the main thread, so installation is skipped (harmless) elsewhere —
e.g. when a test drives train() from a worker thread.
"""

from __future__ import annotations

import signal
import threading


def _raise_keyboard_interrupt(signum, frame):  # noqa: ARG001
    raise KeyboardInterrupt(f"signal {signum}")


def install_interrupt_handlers() -> bool:
    """Make SIGINT and SIGTERM unwind the process as KeyboardInterrupt.

    Returns True when handlers were installed (main thread), False when
    skipped. Idempotent; safe to call once per train() invocation.
    """
    if threading.current_thread() is not threading.main_thread():
        return False
    signal.signal(signal.SIGINT, signal.default_int_handler)
    signal.signal(signal.SIGTERM, _raise_keyboard_interrupt)
    return True
