"""Long-lived compression service: model loaded once, batched hot path.

Every earlier entry point (coding/cli.py, bench.py, tools/*) is one-shot
— it pays Python startup, model init, and jit compiles per image. This
module is the amortized form the ROADMAP's serving goal needs:

* model/jit state is built ONCE per process (coding/loader.py, shared
  with the CLI so the construction cannot drift);
* requests of arbitrary (h, w) are padded onto the static bucket set
  (serve/buckets.py), so the steady-state executable count is exactly
  2 * len(buckets) — warm-up compiles them all, and after that
  `CompilationSentinel(budget=0)` holds over any mixed-shape stream;
* same-bucket requests coalesce into micro-batches (serve/batcher.py)
  with backpressure and deadlines;
* SIGINT/SIGTERM drain gracefully (utils/signals.py): in-flight batches
  complete, queued requests are rejected with ServiceDraining, new
  submits are refused.

The jitted work is the batched AE encode/decode; the per-image rANS
entropy stage runs on the pure-numpy incremental engine
(coding/incremental.py), which holds no jax state and therefore never
contributes to the compile budget.

Pipelined dataplane (ISSUE 4): the two stages are heterogeneous — a
device batch and per-image CPU entropy coding — and running them
serialized on one worker thread leaves whichever side is idle (the
classic learned-codec serving bottleneck, PAPERS.md 2207.14524 /
1912.08771). With `entropy_workers > 0` each worker instead runs a
two-stage pipeline:

  encode:  [worker] assemble + dispatch jitted batch (async)
           [pool]   ONE task per micro-batch: single shared device->host
                    transfer, then one batch-native rANS call for every
                    image's lane, frame + resolve futures
  decode:  [pool]   ONE task per micro-batch: per-request CRC re-verify,
                    then the lockstep batch decode (one native call per
                    wavefront for the whole batch)
           [worker] jitted batch decode over the gathered symbols,
                    crop + resolve futures

The worker dispatches batch N+1's device stage while batch N's entropy
task runs on the pool (`pipeline_depth` bounds how many batches may be
in flight), so device and host stages genuinely overlap: nothing blocks
on a device->host transfer before the next device call is dispatched —
the transfer happens in the pool task that first needs the values.
Every pool thread owns a private codec clone (BottleneckCodec
.thread_clone) sharing the warmed, lock-guarded schedule cache. Fault
isolation is preserved inside the batch task: the `serve.rans` site and
the payload-CRC re-verify run per request, and an IntegrityError lands
on that request's future only. A worker that dies mid-pipeline (crash
between device dispatch and entropy completion) flushes its in-flight
records on the way out — completed or failed, never hung — and the
supervisor restarts it.

Batch-native entropy backend (ISSUE 7): PR 4's fan-out ran each image's
rANS pass as its own Python loop under the GIL, capping the overlap
ratio at ~0.45 (entropy_ms ~= device_ms in SERVE_BENCH.json). The
entropy stage now submits ONE task per micro-batch and codes it
batch-native — `coding/rans.py` `encode_batch` packs every image's
symbol lanes into one ctypes call whose C loop runs with the GIL
dropped, and decode advances all lanes per wavefront in one
`rans.decode_front_batch` call (streams stay bit-identical to the
per-image path; tests pin all three coders against each other). For
hosts where even that leaves the Python-side framing GIL-bound,
`ServiceConfig.entropy_backend = "process"` swaps the coding work onto
a spawn-context ProcessPoolExecutor of WORKER-RESIDENT codecs: a
picklable CodecSpec (coding/loader.py) is rebuilt once per worker
process with its schedule cache warmed there, and the entropy pool
threads become thin bridges (transfer, per-request CRC/fault
semantics, framing, future resolution). `serve_entropy_batch_ms`
times the batch coding span; the `serve_entropy_backend` info entry
records the active backend in /metrics. Per-stage observability: `serve_device_ms`,
`serve_entropy_ms` histograms, `serve_pipeline_inflight`, and
`serve_overlap_ratio` = 1 - busy/(device+entropy) where busy is the
wall time workers actually spent on batches (serialized mode pins it to
~0; at steady state a pipelined worker pays ~max(stage) per batch
instead of the sum).

Stream framing (little-endian, v2), around the BottleneckCodec payload:
    b"DSRV" | u8 version | u16 h | u16 w | u16 bh | u16 bw
            | u32 payload_len | u32 crc32 | payload
The original (h, w) drives the post-decode crop; the bucket (bh, bw) is
recorded explicitly so a decode request routes to its executable without
re-deriving policy (and fails loudly if the service lacks that bucket).
The CRC covers every header field after the magic plus the payload
(utils/integrity.py): a flipped bit anywhere in the frame raises a typed
IntegrityError instead of rANS-decoding to a plausible garbage image.
v1 frames (no CRC) remain readable.

Fault tolerance (ISSUE 3): workers that die — a non-`Exception` escaping
a batch, e.g. the fault harness's InjectedCrash or a KeyboardInterrupt —
are restarted by a supervisor thread with capped exponential backoff
(utils/retry.py). `/healthz` degrades honestly (`degraded` below the
configured pool size, `unhealthy` + 503 at zero), and submits against an
empty pool fail fast with ServiceUnavailable instead of queueing work
nobody will drain. Injection sites: `serve.worker.batch` (batch
processing) and `serve.rans` (decode payload bytes) — no-ops unless a
fault plan is installed (utils/faults.py).

Multi-device dataplane (ISSUE 6): with `devices=N` the bucket ladder is
mapped onto N devices by serve/placement.py (hot buckets get replicas
across devices, cold buckets share one; every device serves >= 1
bucket) and workers become DEVICE-AFFINE executors: slot s is pinned to
device `s % N` for its whole life (restarts included), holds that
device's replicated params (`placement.replicate`, a mesh.py sharding
spec — not a hand-rolled device_put), and pops only batches for buckets
placed on its device (`MicroBatcher.next_batch(accept=…)`). A hot
bucket's replica executors drain one shared queue concurrently — data
parallelism at micro-batch granularity, which keeps results bit-
identical to the single-device path because each batch still runs whole
through one (identical) executable. Warmup compiles per (bucket,
device) census pair, so `CompilationSentinel(budget=0)` holds at any N;
`rebalance_placement()` re-plans routing from observed per-bucket
traffic, warming any pair new to the plan BEFORE the atomic table swap.
Per-device observability: `serve_devices`, `serve_device_batches_d<i>`,
`serve_device_busy_ms_d<i>`, `serve_placement_rebalances`, and the
`serve_device_assignments` census in the /metrics info section.

Side-information serving (ISSUE 10): `enable_si=True` loads the FULL
DSIN (siNet included) and opens the session dataplane — the paper's
actual product behind the front door. A client registers a side image
once (`open_session`): the service runs the jitted per-bucket prep
executable (AE-reconstruct y, color-transform, window statistics,
Gaussian prior factors, and on TPU the padded tensor the fused Pallas
kernel slices) into an immutable `ops.sifinder.SidePrep`, cached
device-resident in the LRU/TTL/byte-bounded `serve/session.py` store.
`submit_decode_si(stream, session_id)` then decodes THROUGH the SI
path: one jitted executable per bucket runs decode → siFinder (against
the cached prep, passed as traced arguments — executables stay
shape-keyed, so sessions churn with ZERO steady-state compiles) →
siNet. Requests sharing a session coalesce into one micro-batch
(`Request.session` narrows the batcher key), so a burst against one
side image rides one executable call and one VMEM-resident y. Sessions
are model-versioned: a hot swap or rollback invalidates the store
(`SessionExpired` — the prep embeds the OLD params' ŷ), and every miss
mode (evicted, TTL, swap, dead replica) answers the same typed error.
Observability: `serve_sessions_live`, `serve_session_bytes`,
`serve_session_evictions[_<reason>]`, `serve_si_prep_ms`,
`serve_si_search_ms`; fault site `serve.session` on every lookup.

Live model operations (ISSUE 9): the model is no longer frozen at
start(). Everything a batch reads about "the model" — per-device
replicated params, the host codec, per-thread codec clones, the
process-backend worker pool — lives in ONE immutable `ModelBundle`
(serve/swap.py), captured once per batch, so a batch is version-
coherent by construction. `swap_model(ckpt_dir)` loads the incoming
checkpoint (manifest-verified: typed `ManifestMismatch` on wrong
params/pc-config/bucket ladder), warms it against the live executable
census in the BACKGROUND of serving traffic (executables are
shape-keyed, params are arguments — the warm re-uses every compiled
program, so `CompilationSentinel(budget=0)` holds through and after
the swap), then commits with an O(1) pointer swap under the ranked
`serve.model` lock while in-flight batches finish on the bundle they
started with. The displaced model stays WARM in the `prev` slot:
`rollback()` re-instates it in milliseconds with zero compiles. Swap
observability: `serve_swaps` / `serve_rollbacks` / `serve_swap_errors`
counters, the `serve_swap_state` gauge (0 idle / 1 preparing /
2 staged), the `serve_model_digest` info entry, and a `model` section
in /healthz. The `serve.swap` fault site (prepare + commit windows)
lets chaos_bench kill a swap at its narrowest points and assert the
service keeps serving the old params.

Observability (ISSUE 11): every request carries a `TraceContext`
(serve/trace.py) minted at `_submit` (or forwarded by the front door,
keeping ITS head-sampling decision), and every pipeline stage records a
span against the batch's sampled contexts — queue wait at batch seal,
device dispatch->host, the entropy task (both backends; the process
backend serializes the contexts with the pool task and bit-checks the
echo), SI session lookup and the fused search executable. Spans wrap
dispatch boundaries only (never jitted code), so tracing holds
`CompilationSentinel(budget=0)`; serve_bench's --trace leg gates the
enabled-vs-disabled overhead and cross-checks span totals against the
`serve_*_ms` accumulators. A typed error resolving any future counts
into `serve_typed_errors`, tags the trace (always-on error spans), and
triggers the FlightRecorder — an always-on ring of admission/shed/
batch-seal/swap/session/worker events that auto-dumps a JSONL timeline
on typed errors and worker deaths. The post-swap `RollbackWatchdog`
(serve/swap.py) compares typed-error-rate windows around every
`commit_swap` and calls `rollback(expect_current=...)` itself past the
configured threshold — the ROADMAP's health-triggered rollback loop.

Model health (ISSUE 13, serve/quality.py): every ops metric above stays
green while the fleet silently ships WORSE COMPRESSION, so the paper's
own quantities are production signals too. Encode lanes export
per-bucket payload/wire bpp histograms and a head-sampled coding gap
(realized payload bits vs `BottleneckCodec.ideal_bits` — the extra pass
runs on the entropy-pool thread after the future resolved, pure numpy,
never under a lock or in jit); SI decodes carry the winning siFinder
match score per patch (an optional executable output — the argmax path
is bit-identical) summarized per session with a floor alarm; and a
golden canary prober drives pinned per-bucket inputs through the REAL
serve path on a period, comparing output digests against goldens
recorded in the checkpoint manifest (or a self-anchored first probe).
The canary gates swaps: `prepare_swap` probes the STAGED bundle and a
mismatch against the incoming manifest's goldens refuses the commit
typed (`CanaryFailed`); a post-commit canary failure arms the
`RollbackWatchdog` alongside the typed-error signal. Canary inputs use
the existing bucket shapes, so budget-0 holds with every quality signal
on (serve_bench's --quality leg gates it).
"""

from __future__ import annotations

import struct
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dsin_tpu.serve import buckets as buckets_lib
from dsin_tpu.serve import metrics as metrics_lib
from dsin_tpu.serve import placement as placement_lib
from dsin_tpu.serve import quality as quality_lib
from dsin_tpu.serve import router as router_lib
from dsin_tpu.serve import shmlane as shmlane_lib
from dsin_tpu.serve import swap as swap_lib
from dsin_tpu.serve import session as session_lib
from dsin_tpu.serve import trace as trace_lib
from dsin_tpu.serve.batcher import (Future, MicroBatcher, PriorityClass,
                                    Request, ServeError, ServiceDraining,
                                    ServiceUnavailable)
from dsin_tpu.utils import faults, recompile
from dsin_tpu.utils import locks as locks_lib
from dsin_tpu.utils.integrity import IntegrityError, frame_crc, verify_crc
from dsin_tpu.utils.retry import RetryPolicy

SERVE_MAGIC = b"DSRV"
SERVE_VERSION = 2   # v2: + CRC32 over header fields + payload
_FRAME_LEN_V1 = 17  # magic(4) + B(1) + 4*H(8) + I(4)
_FRAME_LEN = 21     # v2: + I(4) CRC

ENCODE = "encode"
DECODE = "decode"
DECODE_SI = "decode_si"   # session-affine SI decode (ISSUE 10)


@dataclass
class ServiceConfig:
    ae_config: str
    pc_config: str
    ckpt: Optional[str] = None
    seed: int = 0
    buckets: Sequence[Tuple[int, int]] = buckets_lib.DEFAULT_BUCKETS
    max_batch: int = 4
    max_wait_ms: float = 5.0
    max_queue: int = 64
    #: executor threads PER DEVICE (total pool = workers * devices)
    workers: int = 1
    #: devices to spread the bucket ladder over (serve/placement.py);
    #: None = 1, the single-device dataplane every earlier PR ran. On
    #: CPU hosts, force virtual devices with
    #: XLA_FLAGS=--xla_force_host_platform_device_count=N first.
    devices: Optional[int] = None
    #: bucket -> traffic weight for the initial placement plan (None =
    #: uniform); `rebalance_placement()` re-plans from observed traffic
    placement_weights: Optional[Mapping[Tuple[int, int], float]] = None
    #: rANS pool size per service; 0 = serialized legacy path (entropy
    #: runs inline on the worker thread after/before the device call);
    #: None = auto: min(4, cores - 1), at least 1 — the entropy stage is
    #: GIL-heavy numpy, so a pool wider than the spare cores actively
    #: hurts (measured 0.5x per-encode at 2 threads on a 2-core host)
    entropy_workers: Optional[int] = None
    #: where the entropy stage's coding work runs (ISSUE 7):
    #: "thread"  — the entropy pool threads code in-process (batch-native
    #:             rANS drops the GIL inside the C loop; numpy/BLAS PMF
    #:             work drops it too, so this is usually enough);
    #: "process" — a spawn-context ProcessPoolExecutor of worker-resident
    #:             codecs (coding/loader.py CodecSpec: rebuilt once per
    #:             worker, schedule cache warmed there) for hosts where
    #:             even batch-native work leaves the Python-side framing
    #:             GIL-bound. The entropy pool threads become thin
    #:             bridges: device->host transfer, per-request CRC/fault
    #:             semantics, framing, future resolution. Requires
    #:             entropy_workers > 0.
    entropy_backend: str = "thread"
    #: process backend only: ceiling on one micro-batch's coding task in
    #: a pool child. Child DEATH breaks the pool and is healed by a
    #: rebuild, but a child that HANGS (swap-thrash, stuck page-in)
    #: would otherwise block the bridge thread — and every future in
    #: its batch — forever. On expiry the batch fails typed and the
    #: pool is swapped for a fresh one. The bound covers the whole
    #: future — after a rebuild that includes the fresh pool's spawn +
    #: codec re-warm — so keep it generous.
    entropy_proc_timeout_s: float = 120.0
    #: heavy-payload transport for the process boundaries (ISSUE 17):
    #: "pipe" — payloads pickle through the multiprocessing pipe (the
    #:          pre-shm behavior, and the per-message fallback path);
    #: "shm"  — payloads ride fixed-size CRC-framed lanes in a
    #:          multiprocessing.shared_memory ring (serve/shmlane.py);
    #:          only a (lane, offset, length) descriptor crosses the
    #:          pipe. Governs the service->entropy-pool hop here and is
    #:          the default for FrontDoorRouter(transport=None)'s
    #:          router->replica hop. Bit-identical to "pipe" by
    #:          contract (gated in serve_bench).
    transport: str = "pipe"
    #: max batches a worker may hold in flight (device dispatched,
    #: entropy pending) before finishing the oldest; >= 2 overlaps
    #: batch N's entropy with batch N+1's device stage
    pipeline_depth: int = 2
    #: traffic classes (ISSUE 8), most-latency-sensitive first — e.g.
    #: batcher.default_priority_classes(max_queue): per-class bounded
    #: queues, default deadlines, and the bulk-sheds-first overload
    #: order, plus an AdmissionController front-door gate
    #: (serve/router.py) with per-class admit/shed counters. None =
    #: the single-class pre-priority behavior.
    priority_classes: Optional[Sequence[PriorityClass]] = None
    #: per-class outstanding (queued + in-flight) caps for the admission
    #: gate; None = derived: class queue bound + the worker pipelines'
    #: in-flight capacity. Only read when priority_classes is set.
    admission_limits: Optional[Mapping[str, int]] = None
    #: load-aware automatic rebalance (ISSUE 8 satellite): how often the
    #: supervisor inspects per-bucket traffic skew; None = off (the
    #: operator calls rebalance_placement() manually, the pre-ISSUE-8
    #: behavior). A rebalance warms NEW census pairs, so auto mode
    #: trades occasional compiles for placement that tracks traffic.
    rebalance_check_every_s: Optional[float] = None
    #: trigger when max bucket share >= threshold * the uniform share,
    #: for `rebalance_hysteresis_checks` CONSECUTIVE windows, and not
    #: within `rebalance_cooldown_s` of the last fire (no flapping)
    rebalance_skew_threshold: float = 2.0
    rebalance_hysteresis_checks: int = 2
    rebalance_cooldown_s: float = 60.0
    #: side-information serving (ISSUE 10): load the full DSIN (siNet
    #: included), open the session API (open_session/submit_decode_si),
    #: and compile the per-bucket SI executables at warmup. Requires
    #: every bucket edge divisible by the config's y_patch_size, and is
    #: single-device per replica for now (scale OUT through the
    #: session-pinning router, serve/router.py).
    enable_si: bool = False
    #: session store bounds (serve/session.py): max live sessions, max
    #: per-session device bytes in total, and an optional idle TTL.
    session_max: int = 8
    session_max_bytes: int = 64 * 1024 * 1024
    session_ttl_s: Optional[float] = None
    #: request tracing + flight recorder (ISSUE 11, serve/trace.py).
    #: `trace_enabled=False` removes the whole layer (no contexts
    #: minted, nothing recorded) — the bench's overhead baseline.
    #: `trace_sample_rate` is the HEAD sampling rate for per-request
    #: spans (deterministic counter rotation; 0.0 = only typed-error
    #: spans and flight events are recorded, the production-lean
    #: default); contexts arriving from the front door keep THEIR
    #: sampling decision regardless of this rate.
    trace_enabled: bool = True
    trace_sample_rate: float = 0.0
    trace_capacity: int = 4096
    #: flight recorder: always-on event ring; `flight_dir` enables the
    #: typed-error/worker-death triggered JSONL auto-dumps (None = ring
    #: only, queryable via /trace and snapshot()).
    flight_capacity: int = 2048
    flight_dir: Optional[str] = None
    flight_dump_min_interval_s: float = 1.0
    #: post-swap rollback watchdog (ISSUE 11 satellite / ROADMAP
    #: elastic-fleet item): compare typed-error-rate windows before and
    #: after every commit_swap and roll back automatically when the
    #: rate jumps by more than `rollback_watchdog_threshold` over at
    #: least `rollback_watchdog_min_requests` post-commit resolutions.
    #: None = off (the operator owns rollback, the PR 9 behavior).
    rollback_watchdog_window_s: Optional[float] = None
    rollback_watchdog_threshold: float = 0.5
    rollback_watchdog_min_requests: int = 8
    #: model-health telemetry (ISSUE 13, serve/quality.py).
    #: `quality_enabled=False` removes the whole layer: no bpp/gap
    #: observation, no SI score outputs compiled into the SI
    #: executable, no canary machinery.
    quality_enabled: bool = True
    #: head-sampling rate of the coding-gap pass (the PR 11
    #: deterministic counter rotation): each sampled encode pays a
    #: second incremental-engine scan on the entropy-pool thread, so
    #: the default keeps the telemetry inside the bench's <=2% paired
    #: overhead budget; benches force 1.0 to populate histograms fast.
    quality_gap_sample_rate: float = 1.0 / 16.0
    #: SI-match alarm: a session is alarmed once >= `si_alarm_frac` of
    #: its observed winning match scores (after `si_alarm_min_samples`
    #: of them) fall below `si_score_floor` — the "side image stopped
    #: correlating" signal.
    si_score_floor: float = 0.25
    si_alarm_frac: float = 0.5
    si_alarm_min_samples: int = 8
    #: golden canary prober period; None = no background prober (swaps
    #: still canary their staged bundle when the incoming manifest
    #: records goldens and quality_enabled). The prober drives the
    #: pinned per-bucket inputs through the REAL serve path.
    canary_every_s: Optional[float] = None
    #: seed of the deterministic canary inputs — must match the seed
    #: the checkpoint publisher recorded goldens with
    #: (quality.canary_inputs keys every derivation by it)
    canary_seed: int = 0
    #: per-op result timeout inside one canary probe
    canary_timeout_s: float = 120.0
    #: precision-ladder rung (ISSUE 19, coding/precision.py): "fp32"
    #: (baseline), "bf16" (distortion-side nets in bfloat16), or "int8"
    #: (experimental fake-quantized weights in bf16 containers). The
    #: entropy-critical probclass/centers path stays frozen-point-exact
    #: fp32 at every rung — streams are byte-identical across rungs for
    #: the same symbols. The rung folds into the model digest
    #: (loader.params_digest), so fleet handshake / hot-swap / canary
    #: can never mix rungs silently, and hot swaps re-cast incoming
    #: checkpoints onto THIS rung after manifest verification.
    precision: str = "fp32"
    #: persistent XLA compilation cache (utils/cache.py) at start(), so
    #: a restarted service re-warms from disk instead of recompiling
    persistent_cache: bool = True
    #: None = no HTTP endpoint; 0 = ephemeral port (tests)
    metrics_port: Optional[int] = None
    #: supervisor restart backoff: base and cap of the exponential curve
    #: (utils/retry.py RetryPolicy; delay doubles per consecutive restart)
    restart_backoff_s: float = 0.05
    restart_backoff_max_s: float = 2.0
    #: how often the supervisor checks the pool for dead workers
    supervise_every_s: float = 0.05


@dataclass
class EncodeResult:
    stream: bytes          # framed: ready for decode() / a wire
    payload_bytes: int     # entropy-coded payload only
    bpp: float             # payload bits over ORIGINAL h*w pixels
    shape: Tuple[int, int]
    bucket: Tuple[int, int]
    #: digest of the model bundle that produced this stream (ISSUE 9):
    #: during a hot swap, every response is attributable to exactly one
    #: model version — the no-torn-batch evidence tests/chaos read
    model_digest: Optional[str] = None


def frame_stream(payload: bytes, shape: Tuple[int, int],
                 bucket: Tuple[int, int]) -> bytes:
    h, w = shape
    bh, bw = bucket
    head = struct.pack("<BHHHHI", SERVE_VERSION, h, w, bh, bw, len(payload))
    crc = frame_crc(head, payload)
    return SERVE_MAGIC + head + struct.pack("<I", crc) + payload


class StreamCorrupt(ValueError):
    """Structurally damaged DSRV frame (bad magic, truncation, version
    or geometry skew) — typed (contract-typed-raise) so request-path
    corruption maps to one registered error family; still a ValueError
    for every caller that catches the documented base."""


def parse_stream(blob: bytes):
    """-> (payload, (h, w), (bh, bw)); every corruption mode is a typed
    error — StreamCorrupt (a ValueError subclass) for structural
    damage, IntegrityError (also under ValueError) for a v2 CRC
    mismatch. v1 frames predate the CRC and parse without one."""
    if len(blob) < _FRAME_LEN_V1 or blob[:4] != SERVE_MAGIC:
        raise StreamCorrupt("not a DSRV stream")
    version = blob[4]
    if version == 1:
        version, h, w, bh, bw, n = struct.unpack(
            "<BHHHHI", blob[4:_FRAME_LEN_V1])
        payload = blob[_FRAME_LEN_V1:_FRAME_LEN_V1 + n]
        crc = None
    elif version == SERVE_VERSION:
        if len(blob) < _FRAME_LEN:
            raise StreamCorrupt(f"truncated DSRV v2 header: {len(blob)} "
                                f"of {_FRAME_LEN} bytes")
        version, h, w, bh, bw, n, crc = struct.unpack(
            "<BHHHHII", blob[4:_FRAME_LEN])
        payload = blob[_FRAME_LEN:_FRAME_LEN + n]
    else:
        raise StreamCorrupt(f"unsupported DSRV version {version}")
    if len(payload) != n:
        raise StreamCorrupt(f"truncated stream: payload {len(payload)} "
                            f"of {n} bytes")
    if crc is not None:
        verify_crc(crc, "DSRV stream",
                   struct.pack("<BHHHHI", version, h, w, bh, bw, n),
                   payload)
    if h > bh or w > bw:
        raise StreamCorrupt(f"corrupt frame: image ({h}, {w}) exceeds "
                            f"its own bucket ({bh}, {bw})")
    return payload, (h, w), (bh, bw)


def _make_batched_fns(model):
    """The service's only two jitted functions. Params/batch_stats enter
    as traced ARGUMENTS (not closure captures — jaxlint:
    nonstatic-jit-capture); `model` is a static module bundle. One jit
    wrapper each: distinct bucket shapes become distinct executables in
    the same cache, so the executable census is #buckets per function."""

    def encode_fn(params, batch_stats, x):
        enc_out, _ = model.encode(params, batch_stats, x, train=False)
        return enc_out.symbols

    def decode_fn(params, batch_stats, symbols):
        from dsin_tpu.models.quantizer import centers_lookup
        q = centers_lookup(params["centers"], symbols)
        x_dec, _ = model.decode(params, batch_stats, q, train=False)
        return jnp.clip(x_dec, 0.0, 255.0)

    return jax.jit(encode_fn), jax.jit(decode_fn)


def _make_si_fns(model, for_pallas: bool, with_scores: bool = False):
    """The SI dataplane's two jitted functions (enable_si, ISSUE 10).
    Same contract as `_make_batched_fns`: params/batch_stats AND the
    SidePrep enter as traced arguments (`model` is the static module
    bundle), so executables are keyed by bucket shapes only — sessions
    come and go without a compile.

    * `si_prep_fn(params, batch_stats, y, mask_factors)` — the
      y-invariant half, run ONCE per session: AE-reconstruct y in eval
      mode (the same ŷ the train step searches, train/step.py), then
      `ops.sifinder.build_side_prep` (transform, window statistics,
      prior factors, and with `for_pallas` the fused kernel's padded
      side operands).
    * `si_decode_fn(params, batch_stats, symbols, prep)` — the per-
      request path: decode → prepped siFinder → siNet, one fused
      executable per bucket. With `with_scores` (ISSUE 13) it returns
      `(images, best_scores (N, P))` — the SI-match quality signal; the
      search itself is bit-identical (ops/sifinder.py), the executable
      merely keeps the winning scores it already computed."""
    from dsin_tpu.ops import sifinder as sifinder_lib
    cfg = model.ae_config
    ph, pw = (int(v) for v in cfg.y_patch_size)
    use_l2 = bool(cfg.use_L2andLAB)
    pallas_dtype = sifinder_lib.sifinder_conv_dtype(
        cfg, jnp.dtype("float32"))

    def si_prep_fn(params, batch_stats, y, mask_factors):
        enc_out, _ = model.encode(params, batch_stats, y[None],
                                  train=False)
        y_dec, _ = model.decode(params, batch_stats, enc_out.qbar,
                                train=False)
        return sifinder_lib.build_side_prep(
            y, y_dec[0], ph, pw, use_l2=use_l2,
            mask_factors=mask_factors, for_pallas=for_pallas,
            pallas_dtype=pallas_dtype)

    def si_decode_fn(params, batch_stats, symbols, prep):
        from dsin_tpu.models.quantizer import centers_lookup
        q = centers_lookup(params["centers"], symbols)
        x_dec, _ = model.decode(params, batch_stats, q, train=False)
        if with_scores:
            y_syn, scores = sifinder_lib.synthesize_side_image_prepped(
                x_dec, prep, ph, pw, cfg, with_scores=True)
            x_si = model.apply_sinet(params, x_dec, y_syn)
            return jnp.clip(x_si, 0.0, 255.0), scores
        y_syn = sifinder_lib.synthesize_side_image_prepped(
            x_dec, prep, ph, pw, cfg)
        x_si = model.apply_sinet(params, x_dec, y_syn)
        return jnp.clip(x_si, 0.0, 255.0)

    return jax.jit(si_prep_fn), jax.jit(si_decode_fn)


class _DeviceBatch:
    """One dispatched jitted batch. The device computes while the worker
    thread moves on to the next batch; the FIRST entropy task to need
    host values performs the single device->host transfer (np.asarray
    blocks until the computation finishes), siblings block briefly on
    the lock and share the copy. `device_ms` therefore measures
    dispatch -> results-on-host: queueing + compute + transfer."""

    __slots__ = ("_lock", "_dev", "_host", "dispatched", "transfer_done")

    def __init__(self, dev):
        self._lock = locks_lib.RankedLock("serve.device_batch")
        self._dev = dev                      # guarded-by: self._lock
        self._host = None                    # guarded-by: self._lock
        self.dispatched = time.monotonic()
        self.transfer_done: Optional[float] = None  # guarded-by: self._lock

    def host(self) -> np.ndarray:
        with self._lock:
            if self._host is None:
                # jaxlint: disable=blocking-call-under-lock -- the point
                # of this class: ONE shared device->host transfer;
                # sibling tasks block briefly and reuse the copy
                self._host = np.asarray(self._dev)
                self._dev = None
                self.transfer_done = time.monotonic()
            return self._host

    @property
    def device_ms(self) -> float:
        with self._lock:
            done = self.transfer_done
        if done is None:
            done = time.monotonic()
        return (done - self.dispatched) * 1e3


class _Inflight:
    """One batch moving through the pipeline: the worker's handle for
    finishing it (wait for entropy tasks; decode's device stage) and the
    per-batch ledger the stage metrics come from."""

    __slots__ = ("kind", "batch", "bucket", "t0", "device", "bundle",
                 "tasks", "handle", "sym", "per_item_exc", "crash",
                 "si_entry")

    def __init__(self, kind, batch, bucket, t0, device, bundle):
        self.kind = kind
        self.batch = batch
        self.bucket = bucket
        self.t0 = t0
        self.device = device   # executor's device index (placement)
        #: the ONE ModelBundle every stage of this batch reads — version
        #: coherence across a hot swap is this capture (serve/swap.py)
        self.bundle = bundle
        self.tasks = []
        self.handle: Optional[_DeviceBatch] = None   # encode
        self.sym: Optional[np.ndarray] = None        # decode gather
        self.per_item_exc = {}
        self.crash: Optional[BaseException] = None
        #: DECODE_SI: the SessionEntry captured at batch start — the
        #: device stage reads ITS prep, so an eviction mid-batch cannot
        #: tear the search (the entry is immutable)
        self.si_entry = None


class _EntropyPool:
    """One entropy-pool GENERATION: the ProcessPoolExecutor plus (shm
    transport) the lane ring its workers attached at init. Duck-types
    the two pool calls the service makes (`submit`, `shutdown`) so
    ModelBundle.retire() and _swap_entropy_proc keep working untouched;
    shutdown unlinks the ring WITH the pool, which is the whole
    lifetime story — a wedged child's late reply write lands in a
    detached mapping and hurts nobody. All lanes (task AND reply) are
    parent-allocated and parent-freed: the bridge thread blocks on the
    reply, so no cross-process free handshake exists to get wrong."""

    def __init__(self, pool, rings, reply_bytes: int):
        self.pool = pool
        self.rings = rings          # None = pipe transport
        self.reply_bytes = int(reply_bytes)

    def submit(self, fn, *args, **kwargs):
        return self.pool.submit(fn, *args, **kwargs)

    def shutdown(self, wait: bool = False) -> None:
        self.pool.shutdown(wait=wait)
        if self.rings is not None:
            self.rings.unlink()


class CompressionService:
    """Thread-per-worker micro-batching codec service.

    Lifecycle:  start() -> [warmup()] -> submit_*/encode/decode ...
                -> drain()   (or initiate_drain() from a signal handler)
    """

    def __init__(self, config: ServiceConfig):
        self.config = config
        self.policy = buckets_lib.BucketPolicy(config.buckets)
        self.metrics = metrics_lib.MetricsRegistry()
        # observability layer (ISSUE 11): tracer + flight recorder are
        # built before anything that may record into them; the rate and
        # capacities are validated by the constructors (typed, cheap)
        self.tracer = trace_lib.Tracer(
            sample_rate=config.trace_sample_rate,
            capacity=config.trace_capacity,
            enabled=config.trace_enabled, metrics=self.metrics)
        self.flight = trace_lib.FlightRecorder(
            capacity=config.flight_capacity, dump_dir=config.flight_dir,
            min_dump_interval_s=config.flight_dump_min_interval_s,
            metrics=self.metrics, enabled=config.trace_enabled)
        self._watchdog: Optional[swap_lib.RollbackWatchdog] = None
        if config.rollback_watchdog_window_s is not None:
            self._watchdog = swap_lib.RollbackWatchdog(
                config.rollback_watchdog_window_s,
                config.rollback_watchdog_threshold,
                config.rollback_watchdog_min_requests)
        # model-health telemetry (ISSUE 13): monitor + canary state are
        # built up front like the tracer — their constructors validate
        # the knobs (typed, cheap), and dataplane stages can always
        # reach self.quality without None checks
        self.quality = quality_lib.QualityMonitor(
            metrics=self.metrics, flight=self.flight,
            enabled=config.quality_enabled,
            gap_sample_rate=config.quality_gap_sample_rate,
            si_score_floor=config.si_score_floor,
            si_alarm_frac=config.si_alarm_frac,
            si_alarm_min_samples=config.si_alarm_min_samples)
        self._canary = quality_lib.CanaryState(
            config.canary_seed, self.metrics, flight=self.flight)
        self._canary_imgs = {}        # bucket -> (img, side), pinned
        self._canary_sids = {}        # bucket -> live canary session id
        self._canary_thread: Optional[threading.Thread] = None
        self._warmup_done = False
        self._si_scores_enabled = False
        self._batcher = MicroBatcher(
            config.max_batch, config.max_wait_ms, config.max_queue,
            classes=config.priority_classes,
            on_expired=self._note_expired, on_shed=self._note_shed)
        self._priority_enabled = config.priority_classes is not None
        self._admission: Optional[router_lib.AdmissionController] = None
        if self._priority_enabled:
            limits = config.admission_limits
            if limits is None:
                limits = router_lib.default_admission_limits(config)
            self._admission = router_lib.AdmissionController(
                limits, metrics=self.metrics)
        self._workers = []                 # guarded-by: self._workers_lock
        self._workers_lock = locks_lib.RankedLock("serve.workers")
        self._rebalance_lock = locks_lib.RankedLock("serve.rebalance")
        self._rebalancing = False          # guarded-by: self._rebalance_lock
        # slot -> last fatal exit / consecutive restarts / restart time
        self._worker_exits = {}            # guarded-by: self._workers_lock
        self._restarts = []                # guarded-by: self._workers_lock
        self._restart_at = []              # guarded-by: self._workers_lock
        self._restart_policy = RetryPolicy(
            max_attempts=1 << 30,          # supervise forever; cap is on
            base_delay_s=config.restart_backoff_s,   # the DELAY, not the
            max_delay_s=config.restart_backoff_max_s,  # attempt count
            backoff=2.0)
        self._supervisor: Optional[threading.Thread] = None
        self._closer: Optional[threading.Thread] = None
        self._started = False
        self._draining = threading.Event()
        self._drained = threading.Event()
        self._metrics_server: Optional[metrics_lib.MetricsServer] = None
        self._batch_hook = None   # test/diagnostic: called with each batch
        self._entropy_hook = None  # test/diagnostic: called per pool task
        self._entropy_pool: Optional[ThreadPoolExecutor] = None
        # "process"-backend pools live INSIDE each ModelBundle
        # (serve/swap.py): a hot swap gives the incoming model its own
        # worker-resident codecs, so a batch's entropy stage always
        # matches its device stage's params
        self._proc_backend = False
        self._proc_warm = []        # warmup's worker-residence pings
        self._codec_local = threading.local()
        self.placement: Optional[placement_lib.DevicePlacement] = None
        self._num_devices = 1
        self._total_workers = 0
        # (bucket, device) pairs whose two executables exist. COPY-ON-
        # WRITE (rebound, never mutated in place): warmup()/
        # rebalance_placement()/prepare_swap() run on different threads
        # (operator, supervisor auto-tick, a replica's swap thread) and
        # a reader iterating a live set while another thread .add()s
        # would raise mid-iteration — snapshot with one attribute read
        self._warmed_pairs = frozenset()
        self._warm_shapes = []      # per-bucket (D, H, W) volume shapes
        # side-information dataplane (ISSUE 10); populated at start()
        # when enable_si
        self._si_enabled = False
        self._sessions: Optional[session_lib.SessionStore] = None
        self._si_prep_jit = None
        self._si_decode_jit = None
        self._si_factors = {}       # bucket -> (gh, gw) device arrays|None
        self._si_warmed = frozenset()   # copy-on-write, like _warmed_pairs
        self.model = None
        #: the hot-swap state machine; current/prev/staged ModelBundles
        self._swap: Optional[swap_lib.SwapCoordinator] = None

    # -- model state (always the CURRENT bundle's view) ----------------------

    @property
    def state(self):
        """Host-side TrainState of the model currently serving."""
        return self._swap.current.state if self._swap is not None else None

    @property
    def codec(self):
        return self._swap.current.codec if self._swap is not None else None

    @property
    def model_digest(self) -> Optional[str]:
        """coding/loader.py params_digest of the serving model — the
        value the fleet handshake and the two-phase swap compare."""
        return self._swap.current.digest if self._swap is not None else None

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "CompressionService":
        if self._started:
            return self
        # validate the entropy-backend knobs BEFORE the multi-second
        # model build: a config typo should cost milliseconds
        backend = self.config.entropy_backend
        if backend not in ("thread", "process"):
            raise ValueError(f"entropy_backend must be 'thread' or "
                             f"'process', got {backend!r}")
        ew_cfg = self.config.entropy_workers
        if backend == "process" and ew_cfg is not None and ew_cfg <= 0:
            # None is fine: the auto policy below always resolves >= 1
            raise ValueError("entropy_backend='process' needs "
                             "entropy_workers > 0 (the process pool IS "
                             "the entropy stage)")
        if self.config.entropy_proc_timeout_s <= 0:
            raise ValueError(f"entropy_proc_timeout_s must be > 0, got "
                             f"{self.config.entropy_proc_timeout_s}")
        if self.config.transport not in ("pipe", "shm"):
            raise ValueError(f"transport must be 'pipe' or 'shm', got "
                             f"{self.config.transport!r}")
        # precision rung (ISSUE 19): constructing the policy validates
        # the rung name with the same typo-costs-milliseconds timing
        from dsin_tpu.coding import precision as precision_lib
        precision_lib.PrecisionPolicy(self.config.precision)
        # canary knobs (ISSUE 13), validated with the rest up front
        if self.config.canary_every_s is not None \
                and self.config.canary_every_s <= 0:
            raise ValueError(f"canary_every_s must be > 0 (or None), got "
                             f"{self.config.canary_every_s}")
        if self.config.canary_timeout_s <= 0:
            raise ValueError(f"canary_timeout_s must be > 0, got "
                             f"{self.config.canary_timeout_s}")
        if (self.config.canary_every_s is not None
                and self.config.enable_si
                and self.config.session_max < len(self.policy.buckets) + 1):
            # the prober's pinned sessions live in the SHARED user
            # store (one per bucket) and participate in its LRU like
            # any client — a store sized without them would let every
            # probe period evict live users' device-resident preps.
            # Size session_max = expected user working set + #buckets.
            raise ValueError(
                f"canary_every_s with enable_si needs session_max >= "
                f"{len(self.policy.buckets) + 1} (one pinned canary "
                f"session per bucket + at least one user slot), got "
                f"{self.config.session_max} — budget the prober's "
                f"sessions into the store or disable the background "
                f"canary")
        # SI-serving knobs (ISSUE 10), validated BEFORE the model build
        # like everything above: a config typo costs milliseconds
        self._si_enabled = bool(self.config.enable_si)
        if self._si_enabled:
            if self.config.devices not in (None, 1):
                raise ValueError(
                    f"enable_si serves on a single device per replica "
                    f"(got devices={self.config.devices}); scale out "
                    f"through FrontDoorRouter's session pinning "
                    f"(serve/router.py) — a session's device-resident "
                    f"prep cannot chase batches across a mesh")
            from dsin_tpu.config import parse_config_file
            _si_probe_cfg = parse_config_file(self.config.ae_config)
            ph, pw = (int(v) for v in _si_probe_cfg.y_patch_size)
            bad = [b for b in self.policy.buckets
                   if b[0] % ph or b[1] % pw]
            if bad:
                raise ValueError(
                    f"enable_si needs every bucket edge divisible by "
                    f"y_patch_size ({ph}, {pw}) — the siFinder patch "
                    f"grid must tile the bucket exactly; offending "
                    f"buckets: {bad}")
            # the store's own __init__ validates the bounds; the evict
            # hook keeps the SI-match tracker (ISSUE 13) from pinning
            # stats or alarms for sessions that no longer exist
            self._sessions = session_lib.SessionStore(
                self.config.session_max, self.config.session_max_bytes,
                self.config.session_ttl_s, metrics=self.metrics,
                flight=self.flight,
                on_evict=self.quality.session_gone)
        # load-aware auto-rebalance (ISSUE 8 satellite) knobs, validated
        # up front with the rest: a bad value must not leave spawned
        # worker threads behind when start() raises
        self._rebalance_trigger = None
        self._next_rebalance_check = None
        if self.config.rebalance_check_every_s is not None:
            if self.config.rebalance_check_every_s <= 0:
                raise ValueError(
                    f"rebalance_check_every_s must be > 0, got "
                    f"{self.config.rebalance_check_every_s}")
            self._rebalance_trigger = placement_lib.RebalanceTrigger(
                skew_threshold=self.config.rebalance_skew_threshold,
                hysteresis_checks=self.config.rebalance_hysteresis_checks,
                cooldown_s=self.config.rebalance_cooldown_s)
        from dsin_tpu.coding import loader as loader_lib
        from dsin_tpu.coding.loader import load_model_state, make_codec
        # init at the largest bucket; params are shape-independent (the
        # modules are fully convolutional) so every bucket shares them
        init_shape = self.policy.buckets[-1]
        self.model, state = load_model_state(
            self.config.ae_config, self.config.pc_config, self.config.ckpt,
            init_shape, need_sinet=self._si_enabled, seed=self.config.seed,
            persistent_cache=self.config.persistent_cache,
            precision=self.config.precision)
        codec = make_codec(self.model, state)
        self._encode_fn, self._decode_fn = _make_batched_fns(self.model)
        if self._si_enabled:
            from dsin_tpu.ops import sifinder as sifinder_lib
            ph, pw = (int(v) for v in self.model.ae_config.y_patch_size)
            # build the kernel half of every prep whenever the SI
            # executable will dispatch to the fused kernel: explicit
            # 'pallas'/'pallas_interpret' (the interpreter runs on any
            # backend — tests exercise it on CPU), or 'auto' on TPU
            si_impl = getattr(self.model.ae_config, "sifinder_impl",
                              "auto")
            si_for_pallas = (
                not bool(self.model.ae_config.use_L2andLAB)
                and (si_impl in ("pallas", "pallas_interpret")
                     or (si_impl == "auto"
                         and jax.default_backend() == "tpu")))
            # SI-match score output (ISSUE 13): compiled into the SI
            # executable only where the search can emit scores — the
            # XLA Pearson paths. The fused Pallas kernel folds scores
            # on-chip and an L2 search's distances are not a
            # correlation signal, so both keep the score-less
            # executable (quality telemetry notes the absence).
            self._si_scores_enabled = (
                self.config.quality_enabled and not si_for_pallas
                and not bool(self.model.ae_config.use_L2andLAB))
            self._si_prep_jit, self._si_decode_jit = _make_si_fns(
                self.model, si_for_pallas,
                with_scores=self._si_scores_enabled)
            # the prior factors are y-independent, bucket-static: one
            # device upload per bucket, shared by every session
            use_prior = bool(self.model.ae_config.use_gauss_mask)
            for bh, bw in self.policy.buckets:
                self._si_factors[(bh, bw)] = (
                    tuple(jnp.asarray(m) for m in
                          sifinder_lib.gaussian_position_mask_factors(
                              bh, bw, ph, pw))
                    if use_prior else None)
        self._bn_channels = int(self.model.ae_config.num_chan_bn)
        sub = buckets_lib.SUBSAMPLING
        self._warm_shapes = [(self._bn_channels, bh // sub, bw // sub)
                             for bh, bw in self.policy.buckets]
        # ladder -> mesh: the routing table executors read, plus one
        # committed replica of (params, batch_stats) per serve device so
        # a dispatch never drags parameters across devices at call time
        # None means 1; an explicit 0 (or negative) is a config bug and
        # must raise DevicePlacement's typed PlacementError, not be
        # silently reinterpreted as single-device
        self._num_devices = (1 if self.config.devices is None
                             else int(self.config.devices))
        self.placement = placement_lib.DevicePlacement(
            self.policy.buckets, num_devices=self._num_devices,
            weights=self.config.placement_weights)
        device_state = [
            self.placement.replicate(d, (state.params, state.batch_stats))
            for d in range(self._num_devices)]
        recompile.install()
        ew = self.config.entropy_workers
        if ew is None:
            import os
            ew = max(1, min(4, (os.cpu_count() or 2) - 1))
        backend = self.config.entropy_backend   # validated at start() top
        self._entropy_workers = ew
        if ew > 0:
            self._entropy_pool = ThreadPoolExecutor(
                max_workers=ew, thread_name_prefix="serve-entropy")
        self._proc_backend = backend == "process"
        initargs = None
        if self._proc_backend:
            # the spec is built per BUNDLE (numpy pulls happen here, on
            # the caller's thread, never under the pool-slot lock) and
            # reused by that bundle's child-death rebuilds
            initargs = (loader_lib.make_codec_spec(
                codec, rung=self.config.precision),
                        list(self._warm_shapes))
        # the start-time bundle keeps its checkpoint's manifest too
        # (swapped-in bundles always did): the canary prober compares
        # against publisher goldens from the very first model, not only
        # after the first hot swap
        start_manifest = None
        if self.config.ckpt:
            from dsin_tpu.train import checkpoint as ckpt_lib
            try:
                start_manifest = ckpt_lib.load_manifest(self.config.ckpt)
            except (OSError, ValueError):
                start_manifest = None   # legacy/corrupt: load_model_state
                #                         already owns that verdict
        bundle = swap_lib.ModelBundle(
            0, loader_lib.params_digest((state.params, state.batch_stats),
                                        rung=self.config.precision),
            state, codec, device_state, ckpt=self.config.ckpt,
            proc_initargs=initargs, manifest=start_manifest)
        if initargs is not None:
            bundle.set_proc(self._make_entropy_proc(initargs))
        self._swap = swap_lib.SwapCoordinator(bundle, self.metrics)
        self.metrics.set_info("serve_entropy_backend", {
            "backend": backend, "entropy_workers": ew,
            "pipeline_depth": self.config.pipeline_depth})
        self._total_workers = self.config.workers * self._num_devices
        with self._workers_lock:
            for i in range(self._total_workers):
                self._workers.append(self._spawn_worker(i))
                self._restarts.append(0)
                self._restart_at.append(None)
        self.metrics.gauge("serve_workers_live").set(self._total_workers)
        self.metrics.gauge("serve_devices").set(self._num_devices)
        self._publish_placement()
        # arm the auto-rebalance clock (trigger built + validated at
        # start() top): the supervisor ticks the skew trigger; manual
        # rebalance_placement() stays
        if self._rebalance_trigger is not None:
            self._next_rebalance_check = (
                time.monotonic() + self.config.rebalance_check_every_s)
        self._supervisor = threading.Thread(target=self._supervise_loop,
                                            name="serve-supervisor",
                                            daemon=True)
        self._supervisor.start()
        # golden canary (ISSUE 13): pinned deterministic inputs at the
        # EXISTING bucket shapes (no new executables — budget-0 holds
        # with the prober on), probed by a dedicated thread so a slow
        # probe can never stall worker crash-restart healing
        self._canary_imgs = quality_lib.canary_inputs(
            self.policy.buckets, self.config.canary_seed)
        if self.config.canary_every_s is not None \
                and self.config.quality_enabled:
            self._canary_thread = threading.Thread(
                target=self._canary_loop, name="serve-canary",
                daemon=True)
            self._canary_thread.start()
        if self.config.metrics_port is not None:
            self._metrics_server = metrics_lib.MetricsServer(
                self.metrics, self.health,
                port=self.config.metrics_port,
                trace=self._trace_http).start()
        self._started = True
        return self

    def _trace_http(self, params) -> object:
        """The /trace endpoint body (ISSUE 11): this process's span
        ring (`?id=` filters one trace, `?format=chrome` exports the
        Chrome/Perfetto event dict) plus the flight recorder's event
        ring and dump bookkeeping."""
        if params.get("format") == "chrome":
            return self.tracer.http_snapshot(params)
        snap = self.tracer.http_snapshot(params)
        snap["flight"] = self.flight.meta()
        return snap

    def warmup(self) -> dict:
        """Compile every (bucket, device, direction) executable in the
        placement plan's census, prime the numpy entropy engine's
        schedules, and spin up the entropy pool threads (each builds its
        codec clone), so the first real request pays nothing. Returns
        {"compiles": n, "cache_hits": h, "seconds": s} — with the
        persistent compilation cache on, a restarted service reports
        compiles == cache_hits: every executable was loaded from disk,
        none rebuilt (utils/recompile.py counts a cache load in BOTH
        numbers)."""
        assert self._started, "start() before warmup()"
        t0 = time.monotonic()
        before = recompile.compilation_count()
        before_hits = recompile.cache_hit_count()
        plan = self.placement.plan
        for bh, bw in self.policy.buckets:
            symbols = None
            for d in plan.devices_for((bh, bw)):
                symbols = self._warm_pair((bh, bw), d)
            # one per-image entropy roundtrip primes the incremental
            # engine's schedule path for this bucket's volume geometry
            # (device-independent: once per bucket, not per pair)
            stream = self.codec.encode(np.transpose(symbols[0], (2, 0, 1)))
            self.codec.decode(stream)
        if self._entropy_pool is not None:
            # force every pool thread into existence and build its codec
            # clone now (the barrier keeps the tasks on distinct
            # threads), so the first pipelined batch pays no lazy setup
            n = self._entropy_workers
            barrier = threading.Barrier(n)
            bundle = self._swap.current

            def _prime():
                barrier.wait(timeout=60)
                self._thread_codec(bundle)

            for f in [self._entropy_pool.submit(_prime) for _ in range(n)]:
                f.result(timeout=120)
        if self._si_enabled:
            # compile the SI dataplane's per-bucket executables (prep +
            # fused decode->search->siNet) now, so sessions churn with
            # zero steady-state compiles — the ISSUE 10 acceptance pin
            self._warm_si()
        proc = self._swap.current.proc()
        if proc is not None:
            # spin every pool process up now (spawn + codec rebuild +
            # schedule warm happen in the initializer) so the first real
            # batch pays coding work only; the pings also double as the
            # worker-residence evidence (pid + schedule census)
            from dsin_tpu.coding import loader as loader_lib
            pings = [proc.submit(loader_lib.worker_ping)
                     for _ in range(self._entropy_workers)]
            self._proc_warm = [f.result(timeout=300) for f in pings]
        compiles = recompile.compilation_count() - before
        cache_hits = recompile.cache_hit_count() - before_hits
        # the canary prober may run from here on: every executable a
        # probe touches exists now, so a probe can never compile
        self._warmup_done = True
        self.metrics.gauge("serve_warmup_compiles").set(compiles)
        self.metrics.gauge("serve_buckets").set(len(self.policy.buckets))
        self.metrics.gauge("serve_executable_census").set(
            self._census_size())
        return {"compiles": compiles,
                "cache_hits": cache_hits,
                "seconds": time.monotonic() - t0}

    def _census_size(self) -> int:
        """Executable count the warm covers: encode+decode per (bucket,
        device) pair, plus prep+SI-decode per bucket when SI is on."""
        return 2 * len(self._warmed_pairs) + 2 * len(self._si_warmed)

    def _warm_si(self, bundle: Optional[swap_lib.ModelBundle] = None
                 ) -> None:
        """Compile/prime the SI executables for every bucket (or, with
        `bundle`, drive the already-compiled ones with an incoming
        model's replicas — the hot-swap warm, zero new compiles)."""
        if bundle is None:
            bundle = self._swap.current
        params, bs = bundle.device_state[0]
        sub = buckets_lib.SUBSAMPLING
        for bh, bw in self.policy.buckets:
            y0 = jnp.zeros((bh, bw, 3), jnp.float32)
            prep = self._si_prep_jit(params, bs, y0,
                                     self._si_factors[(bh, bw)])
            # the sym batch must carry the SAME placement sharding the
            # dataplane's put_batch commits, or the warm compiles a
            # different executable than the one requests hit
            sym = self.placement.put_batch(
                0, np.zeros((self.config.max_batch, bh // sub, bw // sub,
                             self._bn_channels), np.int32))
            # with SI-match scores on the executable returns a tuple —
            # block on the whole output either way
            jax.block_until_ready(self._si_decode_jit(params, bs, sym,
                                                      prep))
            self._si_warmed = self._si_warmed | {(bh, bw)}

    def _warm_pair(self, bucket: Tuple[int, int], device: int,
                   bundle: Optional[swap_lib.ModelBundle] = None
                   ) -> np.ndarray:
        """Compile/prime BOTH executables of one (bucket, device) census
        pair — the input shardings commit the jit cache entries to that
        device. Returns the encode symbols so warmup can prime the
        bucket's entropy schedules. With `bundle`, runs the SAME (shape-
        keyed, already compiled) executables against an incoming model's
        replicas — the hot-swap warm, zero new compiles."""
        bh, bw = bucket
        if bundle is None:
            bundle = self._swap.current
        params, bs = bundle.device_state[device]
        x = self.placement.put_batch(
            device, np.zeros((self.config.max_batch, bh, bw, 3),
                             np.float32))
        symbols = np.asarray(self._encode_fn(params, bs, x))
        sym = self.placement.put_batch(
            device, np.zeros(
                (self.config.max_batch, bh // buckets_lib.SUBSAMPLING,
                 bw // buckets_lib.SUBSAMPLING, self._bn_channels),
                np.int32))
        np.asarray(self._decode_fn(params, bs, sym))
        # copy-on-write rebind (see __init__): concurrent readers keep
        # iterating their own snapshot
        self._warmed_pairs = self._warmed_pairs | {(bucket, device)}
        return symbols

    def _publish_placement(self) -> None:
        """Export the live bucket->device census (the
        `serve_device_assignments` info entry every scrape carries)."""
        self.metrics.set_info("serve_device_assignments",
                              self.placement.plan.as_dict())

    def rebalance_placement(self, weights=None) -> dict:
        """Re-plan bucket->device routing. `weights` defaults to the
        OBSERVED per-bucket request counts (+1 smoothing, so an idle
        bucket keeps a replica) — the operator hook for 'the hot bucket
        moved'. Any (bucket, device) pair new to the incoming plan is
        warmed BEFORE the atomic table swap, so the executable census
        only ever grows by warmed pairs and the zero-steady-compile pin
        keeps holding once this returns. Executors read the new table at
        their next batch pop; in-flight batches finish on their old
        (still-warmed) device."""
        assert self._started, "start() + warmup() before rebalance"
        # one rebalancer at a time: the supervisor auto-tick and the
        # operator hook would otherwise race the warm-then-swap
        # sequence (duplicate warms, stale plan landing last). The
        # ranked lock guards only the claim flag — the warms are long
        # compiles and must not run under any lock.
        with self._rebalance_lock:
            if self._rebalancing:
                return {"changed": False, "warmed_pairs": 0,
                        "skipped": "rebalance already in progress"}
            self._rebalancing = True
        try:
            return self._rebalance_locked_out(weights)
        finally:
            with self._rebalance_lock:
                self._rebalancing = False

    def _rebalance_locked_out(self, weights) -> dict:
        """Body of rebalance_placement; callers hold the claim flag
        (NOT the lock — compiles happen here)."""
        if weights is None:
            weights = {
                (bh, bw): 1.0 + self.metrics.counter(
                    f"serve_bucket_requests_{bh}x{bw}").value
                for bh, bw in self.policy.buckets}
        plan = placement_lib.plan_placement(
            self.policy.buckets, self._num_devices, weights)
        new_pairs = [pair for pair in plan.census()
                     if pair not in self._warmed_pairs]
        for bucket, device in new_pairs:
            self._warm_pair(bucket, device)
        changed = self.placement.set_plan(plan)
        self.metrics.counter("serve_placement_rebalances").inc()
        self.metrics.gauge("serve_executable_census").set(
            self._census_size())
        self._publish_placement()
        return {"changed": changed, "warmed_pairs": len(new_pairs),
                "assignments": plan.as_dict()}

    # -- live model operations (ISSUE 9) -------------------------------------

    def prepare_swap(self, ckpt_dir: str, canary: bool = True) -> dict:
        """Load + warm an incoming checkpoint into a staged ModelBundle,
        in the background of serving traffic (this runs on the CALLER's
        thread; the dataplane keeps serving the current bundle
        throughout). Manifest-verified: a wrong-params / wrong-pc-config
        / wrong-ladder checkpoint raises typed ManifestMismatch and
        nothing stages. The warm drives every already-compiled (bucket,
        device) executable with the incoming replicas and primes a
        fresh codec (+ process pool, when that backend is on) — zero
        new XLA compiles, because executables are keyed by shapes and
        params enter as arguments.

        Golden canary gate (ISSUE 13): when the incoming manifest
        records `canary` goldens (and quality telemetry is on), the
        STAGED bundle is probed through the real executables and a
        digest mismatch raises typed `CanaryFailed` — the commit is
        refused before the degraded model answers a single request.
        `canary=False` is the operator override (the chaos battery's
        forced-commit scenario; the post-commit prober + rollback
        watchdog remain the safety net). Returns {"digest", "epoch",
        "ckpt", "warm", "canary", "seconds"}; commit_swap() makes it
        live."""
        assert self._started, "start() + warmup() before a hot swap"
        from dsin_tpu.coding import loader as loader_lib
        epoch = self._swap.begin_prepare()
        t0 = time.monotonic()
        bundle = None
        try:
            new_state, info = loader_lib.load_swap_state(
                ckpt_dir, self.state,
                pc_config=self.model.pc_config,
                buckets=self.policy.buckets,
                need_sinet=self._si_enabled)
            if self.config.precision != "fp32":
                # re-cast the incoming checkpoint onto THIS service's
                # rung AFTER its manifest verified (identity against the
                # checkpoint's own bytes, then the serving copy drops
                # precision) — a swap must never change rungs silently
                from dsin_tpu.coding import precision as precision_lib
                policy = precision_lib.PrecisionPolicy(
                    self.config.precision)
                new_state = new_state.replace(
                    params=policy.cast_params(new_state.params))
                precision_lib.check_entropy_critical(new_state.params)
            # the prepare window: a kill here must leave the service
            # serving the old params with the claim released
            faults.inject("serve.swap")
            digest = loader_lib.params_digest(
                (new_state.params, new_state.batch_stats),
                rung=self.config.precision)
            codec = loader_lib.make_codec(self.model, new_state)
            device_state = [
                self.placement.replicate(
                    d, (new_state.params, new_state.batch_stats))
                for d in range(self._num_devices)]
            initargs = None
            if self._proc_backend:
                initargs = (loader_lib.make_codec_spec(
                    codec, rung=self.config.precision),
                            list(self._warm_shapes))
            bundle = swap_lib.ModelBundle(
                epoch, digest, new_state, codec, device_state,
                ckpt=ckpt_dir, proc_initargs=initargs,
                manifest=info.get("manifest"))
            if initargs is not None:
                bundle.set_proc(self._make_entropy_proc(initargs))
            warm = self._warm_bundle(bundle)
            canary_info = {"status": "disabled"}
            if canary and self.config.quality_enabled:
                # probe the staged bundle AFTER its warm (the warm
                # already paged its replicas in, so the probe reuses
                # every executable — zero compiles) and BEFORE it can
                # stage: a failing canary leaves nothing to commit
                canary_info = self._canary_check_bundle(bundle)
            self._swap.stage(bundle)
        except BaseException:
            # InjectedCrash included: the kill-during-swap chaos
            # contract is "still serving on the old params" — release
            # the claim, retire the partial bundle, surface the cause
            if bundle is not None:
                bundle.retire()
            self._swap.abandon_prepare()
            raise
        self.flight.record("swap_prepared", digest=digest,
                           ckpt=ckpt_dir)
        return {"digest": digest, "epoch": epoch, "ckpt": ckpt_dir,
                "warm": warm, "canary": canary_info,
                "seconds": round(time.monotonic() - t0, 3)}

    def _warm_bundle(self, bundle: swap_lib.ModelBundle) -> dict:
        """Run the incoming bundle through the live executable census
        (pages its replicas onto their devices; the jit cache serves
        every call — zero compiles), prime its codec's schedule cache
        with one entropy roundtrip per bucket, and spin up + ping its
        process pool when that backend is on."""
        from dsin_tpu.coding import loader as loader_lib
        t0 = time.monotonic()
        symbols_by_bucket = {}
        for bucket, device in sorted(self._warmed_pairs):
            symbols_by_bucket[bucket] = self._warm_pair(bucket, device,
                                                        bundle=bundle)
        if self._si_enabled and self._si_warmed:
            # drive the SI executables with the incoming replicas too
            # (same shape-keyed programs — zero new compiles)
            self._warm_si(bundle=bundle)
        for symbols in symbols_by_bucket.values():
            stream = bundle.codec.encode(np.transpose(symbols[0], (2, 0, 1)))
            bundle.codec.decode(stream)
        pings = []
        proc = bundle.proc()
        if proc is not None:
            futs = [proc.submit(loader_lib.worker_ping)
                    for _ in range(self._entropy_workers)]
            pings = [f.result(timeout=300) for f in futs]
        return {"pairs": len(self._warmed_pairs),
                "buckets": len(symbols_by_bucket),
                "proc_workers": len(pings),
                "seconds": round(time.monotonic() - t0, 3)}

    def commit_swap(self, expect_digest: Optional[str] = None) -> dict:
        """Make the staged bundle live: an O(1) pointer swap under the
        ranked `serve.model` lock. In-flight batches finish on the
        bundle they captured; the displaced model is retained warm for
        rollback(). `expect_digest` pins which model the caller
        believes it is committing (the fleet two-phase contract)."""
        assert self._started, "start() before commit_swap()"
        # the commit window: a kill HERE leaves current serving and the
        # staged bundle parked (the caller aborts it)
        faults.inject("serve.swap")
        for b in self._swap.commit(expect_digest):
            b.retire()
        # sessions are model-versioned: their preps embed the OLD
        # params' ŷ reconstruction — invalidate, clients re-open
        self._invalidate_sessions("swap")
        snap = self._swap.snapshot()
        self.flight.record("swap_commit", digest=snap["digest"],
                           prev=snap["prev_digest"])
        if self._watchdog is not None:
            # arm the post-swap health comparison (ISSUE 11 satellite):
            # the supervisor's counter samples provide the pre-window,
            # its ticks will evaluate the post-window
            errors, resolved = self._error_counters()
            self._watchdog.arm(time.monotonic(), snap["digest"],
                               errors, resolved)
        return snap

    def _error_counters(self) -> Tuple[int, int]:
        """(typed errors, total resolutions) — the watchdog's inputs,
        both counted at ONE place (the per-future _note_resolution
        callback) so a request can never land in the numerator and
        denominator a different number of times."""
        return (self.metrics.counter("serve_typed_errors").value,
                self.metrics.counter("serve_resolved").value)

    def abort_swap(self) -> dict:
        """Discard the staged bundle (or release a dangling prepare
        claim); safe to call when nothing is staged. The service keeps
        serving the current bundle — aborting is never an outage."""
        assert self._started, "start() before abort_swap()"
        for b in self._swap.abort():
            b.retire()
        self.flight.record("swap_abort")
        return self._swap.snapshot()

    def swap_model(self, ckpt_dir: str, canary: bool = True) -> dict:
        """The one-call operator hot swap: prepare (load + manifest
        verify + background warm + golden canary when the incoming
        manifest records goldens) then commit. Any failure — manifest
        mismatch, canary refusal, injected kill in either window —
        aborts back to the old params; the service never stops serving.
        The fleet router (serve/router.py) drives the two phases
        separately instead. `canary=False` is the operator override."""
        info = self.prepare_swap(ckpt_dir, canary=canary)
        try:
            self.commit_swap(expect_digest=info["digest"])
        except BaseException:
            self.abort_swap()
            raise
        return info

    def rollback(self, expect_current: Optional[str] = None) -> dict:
        """Re-instate the previous model bundle: instant (already warm,
        zero compiles — its executables never left the jit cache, its
        replicas never left their devices, its pool never died).
        `expect_current` makes it conditional: only roll back if the
        serving digest IS that one (the fleet commit-failure recovery —
        a replica that never committed refuses typed instead of
        re-instating some older model)."""
        assert self._started, "start() before rollback()"
        for b in self._swap.rollback(expect_current=expect_current):
            b.retire()
        if self._watchdog is not None:
            # a rollback (operator OR watchdog) supersedes any pending
            # post-swap comparison — never judge a model that already
            # left
            self._watchdog.disarm()
        self._invalidate_sessions("rollback")
        snap = self._swap.snapshot()
        self.flight.record("swap_rollback", digest=snap["digest"])
        return snap

    def _invalidate_sessions(self, reason: str) -> None:
        """Drop every cached SidePrep (the serving params changed — a
        stale prep would search against the wrong ŷ). Clients see typed
        SessionExpired and re-open."""
        if self._sessions is not None and self._sessions.live:
            self._sessions.clear(reason)

    # -- golden canary (ISSUE 13, serve/quality.py) ---------------------------

    def canary_goldens(self, staged: bool = False) -> dict:
        """The `manifest_extra["canary"]` entry a checkpoint publisher
        records (train/checkpoint.py): golden output digests of the
        CURRENT model — or, with `staged`, of a prepared-but-
        uncommitted bundle (the publish flow: prepare the candidate,
        record what it SHOULD produce, abort, re-save the checkpoint
        with the goldens)."""
        assert self._started, "start() + warmup() before canary_goldens()"
        bundle = self._swap.staged if staged else self._swap.current
        if bundle is None:
            raise swap_lib.SwapError(
                "canary_goldens(staged=True) with nothing staged — "
                "prepare_swap first")
        return quality_lib.goldens_struct(
            self.config.canary_seed, self.policy.buckets,
            self._canary_probe_bundle(bundle))

    def _canary_probe_bundle(self, bundle) -> dict:
        """Drive the pinned canary inputs through one bundle's REAL
        executables (the same shape-keyed programs the dataplane
        dispatches — params enter as arguments, so probing a staged
        bundle compiles nothing) and digest every output. Lane 0 of a
        max_batch-padded batch, exactly how the dataplane assembles one,
        so these digests equal what the serve path produces for the
        same model (per-lane results are batch-composition independent;
        tests/test_serve_quality.py pins the equality)."""
        sub = buckets_lib.SUBSAMPLING
        digests = {}
        for bucket in self.policy.buckets:
            bh, bw = bucket
            img, side = self._canary_imgs[bucket]
            params, bs = bundle.device_state[0]
            x = np.zeros((self.config.max_batch, bh, bw, 3), np.float32)
            x[0] = buckets_lib.pad_to_bucket(
                img.astype(np.float32, copy=False), bucket)
            symbols = np.asarray(self._encode_fn(
                params, bs, self.placement.put_batch(0, x)))
            vol = np.transpose(symbols[0], (2, 0, 1))
            payload = bundle.codec.encode(vol)
            stream = frame_stream(payload, (bh, bw), bucket)
            entry = {"encode": quality_lib.digest_bytes(stream)}
            vol2 = bundle.codec.decode(payload)
            sym = np.zeros((self.config.max_batch, bh // sub, bw // sub,
                            self._bn_channels), np.int32)
            sym[0] = np.transpose(vol2, (1, 2, 0))
            sym_dev = self.placement.put_batch(0, sym)
            imgs = np.asarray(self._decode_fn(params, bs, sym_dev))
            out = buckets_lib.crop_from_bucket(
                imgs[0], (bh, bw)).astype(np.uint8)
            entry["decode"] = quality_lib.digest_bytes(out.tobytes())
            entry["decode_si"] = None
            if self._si_enabled:
                prep = self._si_prep_jit(
                    params, bs,
                    jnp.asarray(buckets_lib.pad_to_bucket(
                        side.astype(np.float32, copy=False), bucket)),
                    self._si_factors[bucket])
                si_out = self._si_decode_jit(params, bs, sym_dev, prep)
                if self._si_scores_enabled:
                    si_out = si_out[0]
                si_img = buckets_lib.crop_from_bucket(
                    np.asarray(si_out)[0], (bh, bw)).astype(np.uint8)
                entry["decode_si"] = quality_lib.digest_bytes(
                    si_img.tobytes())
            digests[quality_lib.bucket_key(bucket)] = entry
        return digests

    def _canary_check_bundle(self, bundle) -> dict:
        """Prepare-time canary: probe a staged bundle against ITS
        manifest's goldens. A manifest without goldens skips (recorded —
        pre-canary checkpoints keep swapping); goldens that mismatch —
        or that cannot be compared (different canary seed, a served
        bucket they never covered) — refuse typed `CanaryFailed`."""
        goldens = (bundle.manifest or {}).get("canary")
        if goldens is None:
            self.metrics.counter("serve_canary_swap_skipped").inc()
            return {"status": "skipped",
                    "reason": "checkpoint manifest records no canary "
                              "goldens"}
        observed = self._canary_probe_bundle(bundle)
        mismatches = quality_lib.compare_goldens(
            goldens, observed, seed=self.config.canary_seed,
            buckets=self.policy.buckets)
        if mismatches:
            self.metrics.counter("serve_canary_swap_refusals").inc()
            self.flight.record("canary_refused_swap",
                               digest=bundle.digest,
                               mismatches=mismatches[:8])
            raise quality_lib.CanaryFailed(
                f"staged model {bundle.digest} failed its golden canary "
                f"— its outputs are not the outputs its manifest "
                f"promises; refusing to commit it: "
                f"{'; '.join(mismatches[:4])}")
        self.metrics.counter("serve_canary_swap_passes").inc()
        return {"status": "passed", "buckets": len(observed)}

    def run_canary(self) -> dict:
        """One canary probe through the REAL serve path (submit_encode /
        submit_decode / submit_decode_si on a pinned canary session),
        compared against the serving model's baseline — its manifest's
        goldens when comparable, else the self-anchored first probe of
        this digest. A digest MISMATCH is definitive (pinned inputs,
        deterministic executables): it fails the canary, dumps the
        flight recorder, and arms the rollback watchdog when one is
        judging this model. Typed serve errors during the probe (a
        drain, a mid-probe swap expiring the canary session) are
        infrastructure, not model quality — counted separately, never a
        canary failure."""
        assert self._started and self._warmup_done, \
            "start() + warmup() before run_canary()"
        if not self.config.quality_enabled:
            return {"status": "disabled"}
        if not self._canary.claim():
            return {"status": "busy"}
        try:
            return self._run_canary_claimed()
        finally:
            self._canary.release()

    def _run_canary_claimed(self) -> dict:
        t0 = time.monotonic()
        timeout = self.config.canary_timeout_s
        start_digest = self.model_digest
        bundle = self._swap.current
        observed = {}
        try:
            for bucket in self.policy.buckets:
                img, side = self._canary_imgs[bucket]
                res = self.encode(img, timeout=timeout)
                entry = {"encode": quality_lib.digest_bytes(res.stream)}
                dec = self.decode(res.stream, timeout=timeout)
                entry["decode"] = quality_lib.digest_bytes(dec.tobytes())
                entry["decode_si"] = None
                if self._si_enabled:
                    si = self._canary_decode_si(bucket, side, res.stream,
                                                timeout)
                    entry["decode_si"] = quality_lib.digest_bytes(
                        si.tobytes())
                observed[quality_lib.bucket_key(bucket)] = entry
        except (ServeError, ValueError, TimeoutError) as e:
            # typed infrastructure trouble (drain, shed, session churn
            # racing a swap, a probe op blowing canary_timeout_s on a
            # stalled queue): the probe learned nothing about quality
            self.metrics.counter("serve_canary_errors").inc()
            result = {"status": "error", "digest": start_digest,
                      "error": type(e).__name__}
            self._canary.note_result(result)
            return result
        if self.model_digest != start_digest:
            # a swap/rollback landed mid-probe: the digests mix two
            # models — discard rather than judge either
            self.metrics.counter("serve_canary_races").inc()
            result = {"status": "raced", "digest": start_digest}
            self._canary.note_result(result)
            return result
        source, mismatches = self._canary.baseline_for(
            start_digest, bundle.manifest, self.policy.buckets, observed)
        ms = (time.monotonic() - t0) * 1e3
        self.metrics.counter("serve_canary_runs").inc()
        self.metrics.histogram("serve_canary_ms").observe(ms)
        if mismatches:
            self.metrics.counter("serve_canary_failures").inc()
            self.metrics.gauge("serve_canary_ok").set(0)
            result = {"status": "failed", "digest": start_digest,
                      "baseline": source, "mismatches": mismatches}
            self._canary.note_result(result)
            # the forensic + rollback wiring: dump the flight ring, and
            # when the watchdog is judging exactly this model, canary
            # evidence arms it (its next supervisor tick rolls back)
            self.flight.note_death("canary_failure", digest=start_digest,
                                   baseline=source,
                                   mismatches=mismatches[:8])
            if self._watchdog is not None:
                self._watchdog.note_canary_failure(start_digest)
            return result
        self.metrics.gauge("serve_canary_ok").set(1)
        result = {"status": "ok", "digest": start_digest,
                  "baseline": source, "ms": round(ms, 1)}
        self._canary.note_result(result)
        return result

    def _canary_decode_si(self, bucket, side, stream, timeout):
        """SI leg of one probe on the pinned canary session — re-opened
        once when the store expired it (LRU pressure, a swap's
        invalidation); a second expiry inside one probe propagates as
        the probe's typed error."""
        sid = self._canary_sids.get(bucket)
        if sid is None:
            sid = self.open_session(side)
            self._canary_sids[bucket] = sid
        try:
            return self.decode_si(stream, sid, timeout=timeout)
        except session_lib.SessionExpired:
            sid = self.open_session(side)
            self._canary_sids[bucket] = sid
            return self.decode_si(stream, sid, timeout=timeout)

    def _canary_loop(self) -> None:
        """The background prober thread: one run_canary per period,
        starting only once warmup compiled the census (a pre-warm probe
        would compile executables the warmup owns). Probe errors are
        counted, never fatal — the prober outlives everything but
        drain."""
        while not self._draining.wait(self.config.canary_every_s):
            if not self._warmup_done:
                continue
            try:
                self.run_canary()
            except Exception:   # noqa: BLE001 — the prober must survive
                self.metrics.counter("serve_canary_errors").inc()

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def initiate_drain(self) -> None:
        """Non-blocking drain trigger — safe from a signal handler: flip
        the flag, then close the queue from a FRESH thread. The handler
        runs on the main thread mid-bytecode, which may already hold the
        batcher's (non-reentrant) lock inside submit(); closing inline
        there would self-deadlock. `drain()`/`wait_drained()` does the
        blocking part."""
        if self._draining.is_set():
            return
        self._draining.set()

        def _close():
            rejected = self._batcher.close()
            self.metrics.counter("serve_rejected_drain").inc(rejected)

        self._closer = threading.Thread(target=_close, name="serve-drain",
                                        daemon=True)
        self._closer.start()

    def wait_drained(self, timeout: Optional[float] = 30.0) -> bool:
        if self._closer is not None:
            self._closer.join(timeout)
        if self._supervisor is not None:
            # the supervisor exits once draining is set; join it first so
            # no restart races the worker joins below
            self._supervisor.join(timeout)
        if self._canary_thread is not None:
            # the prober exits on the drain flag like the supervisor; a
            # probe in flight resolves typed (the queue is closing) and
            # is counted as a canary error, never a hang
            self._canary_thread.join(timeout)
        with self._workers_lock:
            workers = list(self._workers)
        for t in workers:
            t.join(timeout)
        alive = any(t.is_alive() for t in workers)
        if not alive:
            self._drained.set()
            if self._entropy_pool is not None:
                # workers flushed their pipelines before exiting, so the
                # pool is idle; shutdown is immediate (and idempotent)
                self._entropy_pool.shutdown(wait=True)
            if self._swap is not None:
                # every bundle (current/prev/staged) retires its
                # process pool; workers joined, so the pools are idle
                for b in self._swap.all_bundles():
                    b.retire()
            if self._sessions is not None:
                # no hung session slots: drained services hold no
                # device-resident preps
                self._sessions.clear("drain")
            if self._metrics_server is not None:
                self._metrics_server.stop()
                self._metrics_server = None
            # stop the flight-dump thread AFTER the pipeline flushed:
            # typed errors raised by the drain itself still dump
            self.flight.flush(timeout=5.0)
            self.flight.close()
        return not alive

    def drain(self, timeout: Optional[float] = 30.0) -> bool:
        """Graceful shutdown: returns True when every worker exited."""
        self.initiate_drain()
        return self.wait_drained(timeout)

    def install_signal_handlers(self) -> bool:
        """SIGINT/SIGTERM -> initiate_drain (main thread only)."""
        from dsin_tpu.utils.signals import install_drain_handlers
        return install_drain_handlers(self.initiate_drain)

    def __enter__(self) -> "CompressionService":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.drain()

    # -- request intake -----------------------------------------------------

    @property
    def live_workers(self) -> int:
        with self._workers_lock:
            return sum(t.is_alive() for t in self._workers)

    def health(self) -> dict:
        live = self.live_workers
        configured = self._total_workers if self._started else 0
        if self.draining:
            status = "draining"
        elif live == 0:
            status = "unhealthy"       # /healthz answers 503
        elif live < configured:
            status = "degraded"        # still serving; pool being healed
        else:
            status = "ok"
        return {"status": status,
                "queue_depth": self._batcher.depth,
                "buckets": [list(b) for b in self.policy.buckets],
                "devices": self._num_devices,
                "assignments": (self.placement.plan.as_dict()
                                if self.placement is not None else {}),
                "workers_live": live,
                "workers_configured": configured,
                "worker_restarts":
                    self.metrics.counter("serve_worker_restarts").value,
                # which model is serving + where a swap stands (ISSUE 9)
                "model": (self._swap.snapshot()
                          if self._swap is not None else {}),
                # the SI session dataplane (ISSUE 10; absent = SI off)
                **({"sessions": {"live": self._sessions.live,
                                 "bytes": self._sessions.bytes_used}}
                   if self._sessions is not None else {}),
                # model health (ISSUE 13; absent = quality off): the
                # last canary verdict + how many sessions are alarmed
                **({"quality": {
                        "canary": self._canary.last,
                        "si_match_alarms": int(self.metrics.gauge(
                            "serve_si_match_alarms").value)}}
                   if self.config.quality_enabled else {})}

    def _deadline(self, deadline_ms: Optional[float]) -> Optional[float]:
        return (None if deadline_ms is None
                else time.monotonic() + deadline_ms / 1000.0)

    def _note_expired(self, n: int, by_class) -> None:
        """Batcher on_expired hook (runs under the batcher lock —
        metrics leaves only): total + per-class deadline counters."""
        self.metrics.counter("serve_rejected_deadline").inc(n)
        for cls, k in by_class.items():
            self.metrics.counter(f"serve_expired_{cls}").inc(k)

    def _note_shed(self, cls: str, n: int) -> None:
        """Batcher on_shed hook: per-class overload-victim counter (the
        bulk-sheds-first evidence serve_bench's frontdoor gate reads)."""
        self.metrics.counter(f"serve_shed_{cls}").inc(n)

    def _submit(self, request: Request) -> Future:
        # admission is where a request's TraceContext is minted (ISSUE
        # 11) — one per request unless the front door already minted
        # one (the router's context carries ITS sampling decision
        # across the pipe, which is what stitches a fleet trace)
        if request.trace is None:
            request.trace = self.tracer.mint()
        # the future carries the context so (a) callers can look their
        # trace up by id and (b) the typed-error resolution callback
        # can tag the error span — set BEFORE anything can resolve it
        request.future.trace = request.trace
        # the drain flag flips before the queue actually closes (the
        # close runs on the serve-drain thread) — refuse here too so no
        # request slips into that window
        if self._draining.is_set():
            self.metrics.counter("serve_rejected_drain").inc()
            self.flight.record("shed", reason="draining")
            raise ServiceDraining("service is draining; not accepting "
                                  "new requests")
        if self._started and self.live_workers == 0:
            # zero live workers: nothing would drain the queue, so the
            # request could only hang until its deadline — fail fast and
            # let the client retry elsewhere while the supervisor heals
            self.metrics.counter("serve_rejected_unavailable").inc()
            self.flight.record("shed", reason="no_workers")
            raise ServiceUnavailable(
                "no live workers (pool is restarting); retry shortly")
        cls = None
        if self._admission is not None:
            # front-door gate BEFORE enqueue (serve/router.py): a shed
            # here costs one counter read — nothing was queued, padded,
            # or pickled (no zombie work)
            cls = request.priority or self._batcher.default_class
            request.priority = cls
            try:
                self._admission.admit(cls)
            except Exception:
                self.metrics.counter("serve_rejected_overload").inc()
                self.flight.record("shed", reason="admission", cls=cls)
                raise
        try:
            self._batcher.submit(request)
        except ServiceDraining:
            if cls is not None:
                self._admission.release(cls)
            self.metrics.counter("serve_rejected_drain").inc()
            self.flight.record("shed", reason="draining")
            raise
        except Exception:
            if cls is not None:
                self._admission.release(cls)
            self.metrics.counter("serve_rejected_overload").inc()
            self.flight.record("shed", reason="queue_full",
                               cls=request.priority)
            raise
        if cls is not None:
            # attach AFTER a successful enqueue: resolution (result,
            # shed-as-victim, expiry, drain, crash) frees the slot
            self._admission.attach(cls, request.future)
        # typed-error visibility (ISSUE 11): ANY typed resolution —
        # shed-as-victim, expiry, integrity, session loss — counts,
        # tags the trace, and triggers a flight dump. Attached after
        # enqueue like the admission slot; an already-resolved future
        # fires the callback immediately.
        request.future.add_done_callback(self._note_resolution)
        self.flight.record("admit", cls=request.priority,
                           key=str(request.key))
        # counted only once ACCEPTED: submitted - completed must bound
        # the queued+in-flight backlog, so rejections stay out of it
        self.metrics.counter("serve_submitted").inc()
        self.metrics.gauge("serve_queue_depth").set(self._batcher.depth)
        return request.future

    def _note_resolution(self, fut: Future) -> None:
        """Done-callback on every accepted request: a future resolving
        with a TYPED error (the ServeError/ValueError/InjectedFault
        families — IntegrityError and SessionExpired are subclasses)
        increments `serve_typed_errors` (the rollback watchdog's input
        signal), records the always-on error span for its trace id, and
        triggers a flight-recorder dump. May run under the batcher
        condition (shed/drain resolutions), so everything here is
        leaf-locked and free of blocking I/O."""
        exc = fut.exception(timeout=0)
        # every accepted request resolves exactly once through here —
        # the denominator the rollback watchdog needs. (serve_completed
        # cannot serve that role: _note_batch_done counts the whole
        # batch, so a failed lane would land in BOTH the error
        # numerator and that denominator and cap a 100%-failure storm's
        # computed rate at 0.5.)
        self.metrics.counter("serve_resolved").inc()
        if exc is None or not isinstance(
                exc, (ServeError, ValueError, faults.InjectedFault)):
            return
        self.metrics.counter("serve_typed_errors").inc()
        ctx = getattr(fut, "trace", None)
        self.tracer.error(ctx, exc)
        self.flight.note_error(
            exc, trace_id=ctx.trace_id if ctx is not None else None)

    # contract: request-path — every reachable raise must be a typed error
    def submit_encode(self, img: np.ndarray,
                      deadline_ms: Optional[float] = None,
                      priority: Optional[str] = None,
                      trace=None) -> Future:
        """(h, w, 3) uint8/float image -> Future[EncodeResult]. Raises
        ServiceOverloaded/ServiceDraining/NoBucketFits at the door.
        `priority` names a configured traffic class (None = the most
        latency-sensitive one; the class's default deadline applies
        when `deadline_ms` is None). `trace` (ISSUE 11) is a front-door
        TraceContext whose head sampling decision this service honors;
        None = mint one here."""
        img = np.asarray(img)
        if img.ndim != 3 or img.shape[-1] != 3:
            # jaxlint: disable=contract-typed-raise -- synchronous arg
            # validation at the submission boundary: the caller still
            # holds the thread, no future exists to hang, and ValueError
            # on malformed input is the documented misuse contract
            raise ValueError(f"expected (h, w, 3) image, got {img.shape}")
        h, w = img.shape[:2]
        bucket = self.policy.bucket_for(h, w)
        padded = buckets_lib.pad_to_bucket(
            img.astype(np.float32, copy=False), bucket)
        return self._submit(Request(
            key=(ENCODE, bucket), payload=(padded, (h, w)),
            deadline=self._deadline(deadline_ms), priority=priority,
            trace=trace))

    # contract: request-path — every reachable raise must be a typed error
    def submit_decode(self, blob: bytes,
                      deadline_ms: Optional[float] = None,
                      priority: Optional[str] = None,
                      trace=None) -> Future:
        """Framed DSRV stream -> Future[(h, w, 3) uint8 image]. A v2
        frame failing its CRC raises IntegrityError here, at the door."""
        payload, shape, bucket = parse_stream(blob)
        if bucket not in self.policy.buckets:
            raise buckets_lib.NoBucketFits(
                f"stream was encoded for bucket {bucket}, which this "
                f"service does not serve (buckets: "
                f"{list(self.policy.buckets)})")
        # the payload's own CRC rides along so the worker re-verifies
        # right before the entropy decode — catches corruption that
        # happens AFTER admission (the serve.rans fault site's scenario)
        return self._submit(Request(
            key=(DECODE, bucket), payload=(payload, shape,
                                           frame_crc(payload)),
            deadline=self._deadline(deadline_ms), priority=priority,
            trace=trace))

    # -- side-information sessions (ISSUE 10) ---------------------------------

    def _require_si(self) -> session_lib.SessionStore:
        if not self._si_enabled:
            raise session_lib.SessionError(
                "this service was started without enable_si — it has no "
                "session dataplane (set ServiceConfig.enable_si=True)")
        return self._sessions

    def open_session(self, side_img: np.ndarray,
                     session_id: Optional[str] = None) -> str:
        """Register a side image y; returns the session id. This is the
        WHOLE request-invariant half of the SI search, paid once: pad y
        onto its bucket, run the jitted per-bucket prep executable
        (AE-reconstruct, transform, window statistics, prior factors,
        Pallas padding on TPU), and park the resulting device-resident
        SidePrep in the LRU/TTL store. Every later `decode_si` against
        this id skips all of it."""
        sessions = self._require_si()
        assert self._started, "start() + warmup() before open_session()"
        if self._draining.is_set():
            self.metrics.counter("serve_rejected_drain").inc()
            raise ServiceDraining("service is draining; not accepting "
                                  "new sessions")
        img = np.asarray(side_img)
        if img.ndim != 3 or img.shape[-1] != 3:
            raise ValueError(f"expected (h, w, 3) side image, "
                             f"got {img.shape}")
        h, w = img.shape[:2]
        bucket = self.policy.bucket_for(h, w)
        padded = buckets_lib.pad_to_bucket(
            img.astype(np.float32, copy=False), bucket)
        bundle = self._swap.current
        params, bs = bundle.device_state[0]
        t0 = time.monotonic()
        prep = self._si_prep_jit(params, bs, jnp.asarray(padded),
                                 self._si_factors[bucket])
        jax.block_until_ready(prep)
        self.metrics.histogram("serve_si_prep_ms").observe(
            (time.monotonic() - t0) * 1e3)
        sid = session_id if session_id is not None \
            else sessions.next_sid()
        nbytes = sum(int(leaf.nbytes)
                     for leaf in jax.tree_util.tree_leaves(prep))
        # tracker registration BEFORE the store put: the store's evict
        # hook is what un-registers, and it can only fire for sids the
        # store holds — registering after the put would let a racing
        # eviction/clear land between the two and leak a phantom
        # tracker entry no hook will ever clean (serve/quality.py)
        self.quality.session_open(sid)
        try:
            sessions.put(session_lib.SessionEntry(
                sid=sid, prep=prep, bucket=bucket, nbytes=nbytes,
                digest=bundle.digest))
        except BaseException:
            # refused (SessionOverCapacity): the sid never entered the
            # store, so no evict hook will fire — unregister here
            self.quality.session_gone(sid, "rejected")
            raise
        self.metrics.counter("serve_sessions_opened").inc()
        return sid

    def close_session(self, session_id: str) -> bool:
        """Free a session's device-resident prep; False if it was
        already gone (evicted/expired — not an error: the slot is free
        either way)."""
        sessions = self._require_si()
        return sessions.evict(session_id, "closed")

    # contract: request-path — every reachable raise must be a typed error
    def submit_decode_si(self, blob: bytes, session_id: str,
                         deadline_ms: Optional[float] = None,
                         priority: Optional[str] = None,
                         trace=None) -> Future:
        """Framed DSRV stream + open session -> Future[(h, w, 3) uint8
        SI-fused reconstruction]. The session is validated (and its LRU
        recency refreshed) at the door — a gone session raises typed
        `SessionExpired` here; one that expires between admission and
        batch start fails the batch's futures with the same type. The
        stream must route to the session's bucket: the siFinder patch
        grid and correlation map are one geometry."""
        sessions = self._require_si()
        payload, shape, bucket = parse_stream(blob)
        if bucket not in self.policy.buckets:
            raise buckets_lib.NoBucketFits(
                f"stream was encoded for bucket {bucket}, which this "
                f"service does not serve (buckets: "
                f"{list(self.policy.buckets)})")
        entry = sessions.get(session_id)
        if entry.bucket != bucket:
            raise session_lib.SessionError(
                f"stream bucket {bucket} does not match session "
                f"{session_id!r} (opened at {entry.bucket}) — the SI "
                f"search needs x and y at one geometry; open a session "
                f"with a side image of the request's bucket")
        return self._submit(Request(
            key=(DECODE_SI, bucket), payload=(payload, shape,
                                              frame_crc(payload)),
            deadline=self._deadline(deadline_ms), priority=priority,
            session=session_id, trace=trace))

    def decode_si(self, blob: bytes, session_id: str,
                  deadline_ms: Optional[float] = None,
                  timeout: Optional[float] = 60.0,
                  priority: Optional[str] = None) -> np.ndarray:
        return self.submit_decode_si(blob, session_id, deadline_ms,
                                     priority=priority).result(timeout)

    def _resolve_session(self, batch, bundle) -> session_lib.SessionEntry:
        """Batch-start session lookup (worker side): the entry captured
        HERE is what the device stage reads — immutable, so a
        concurrent eviction cannot tear the search. A session that
        outlived its slot (LRU/TTL) or its model (hot swap landed since
        the prep was built) fails the whole batch typed."""
        t0 = time.monotonic()
        entry = self._sessions.get(batch[0].session)
        if entry.digest != bundle.digest:
            self._sessions.evict(batch[0].session, "swap")
            raise session_lib.SessionExpired(
                f"session {batch[0].session!r} was prepared against "
                f"model {entry.digest} but {bundle.digest} is serving "
                f"(hot swap/rollback since) — re-open it")
        self.tracer.span_batch(batch, trace_lib.SPAN_SESSION, t0,
                               time.monotonic(),
                               session=batch[0].session)
        return entry

    def encode(self, img: np.ndarray, deadline_ms: Optional[float] = None,
               timeout: Optional[float] = 60.0,
               priority: Optional[str] = None) -> EncodeResult:
        return self.submit_encode(img, deadline_ms,
                                  priority=priority).result(timeout)

    def decode(self, blob: bytes, deadline_ms: Optional[float] = None,
               timeout: Optional[float] = 60.0,
               priority: Optional[str] = None) -> np.ndarray:
        return self.submit_decode(blob, deadline_ms,
                                  priority=priority).result(timeout)

    # -- worker side --------------------------------------------------------

    def _spawn_worker(self, slot: int) -> threading.Thread:
        t = threading.Thread(target=self._worker_main, args=(slot,),
                             name=f"serve-worker-{slot}", daemon=True)
        t.start()
        return t

    def _worker_main(self, slot: int) -> None:
        """Thread target: run the loop; record a fatal exit for the
        supervisor instead of spewing the default thread traceback.
        Device affinity is a function of the SLOT (`slot % devices`), so
        a supervisor restart lands the replacement executor on the same
        device — the census and the per-device queues never move."""
        try:
            self._worker_loop(slot % self._num_devices)
        except BaseException as e:  # noqa: BLE001 — supervisor's evidence
            with self._workers_lock:
                self._worker_exits[slot] = e
            self.metrics.counter("serve_worker_crashes").inc()

    def _worker_loop(self, device: int) -> None:
        inflight: deque = deque()
        depth = max(1, int(self.config.pipeline_depth)) \
            if self._entropy_pool is not None else 1
        gauge = self.metrics.gauge("serve_pipeline_inflight")
        # the accept set — both directions of every bucket the live plan
        # places on this device — is rebuilt only when the plan object
        # changes (a rebalance): next_batch is the executor's hottest
        # call (a 0-timeout busy-poll while batches are in flight), so
        # per-pop it pays one plan-snapshot read, not a frozenset build.
        # None (no filter) on a single device — the pre-placement path.
        accept = None
        accept_plan = None
        try:
            while True:
                if self._num_devices > 1:
                    plan = self.placement.plan
                    if plan is not accept_plan:
                        accept_plan = plan
                        accept = frozenset(
                            (kind, bucket)
                            for kind in (ENCODE, DECODE, DECODE_SI)
                            for bucket in plan.buckets_for(device))
                # with work in flight, poll instead of blocking: an empty
                # queue means it is time to finish the oldest batch, not
                # to sit on it for the poll interval
                batch = self._batcher.next_batch(
                    timeout=0.0 if inflight else 0.25, accept=accept)
                if batch is None:
                    return        # closed and empty: finally flushes
                if not batch:
                    if inflight:
                        self._finish_oldest(inflight, gauge)
                    continue
                t_start = time.monotonic()
                try:
                    rec = self._start_batch(batch, device)
                except BaseException as e:  # noqa: BLE001 — answer callers
                    for r in batch:
                        if not r.future.done():
                            r.future.set_exception(e)
                    if not isinstance(e, Exception):
                        # KeyboardInterrupt / InjectedCrash-class
                        # conditions must kill this thread so the
                        # supervisor sees the death — swallowing them
                        # left the pool silently shrunk (ISSUE 3)
                        raise
                    continue
                if rec is not None:
                    dt = (time.monotonic() - t_start) * 1e3
                    self._busy_ms.add(dt)
                    self._device_busy(device).add(dt)
                    inflight.append(rec)
                    gauge.set(len(inflight))
                while len(inflight) >= depth:
                    self._finish_oldest(inflight, gauge)
        finally:
            # the pipeline's no-hung-futures guarantee: whether this
            # thread exits a drain (None batch) or dies on a crash
            # between a batch's device dispatch and its entropy
            # completion, every in-flight record is completed or failed
            # before the thread ends — the supervisor restarts a clean
            # slot, never one with orphaned futures
            while inflight:
                self._finish_oldest(inflight, gauge, swallow=True)
            gauge.set(0)

    def _finish_oldest(self, inflight: deque, gauge,
                       swallow: bool = False) -> None:
        rec = inflight.popleft()
        gauge.set(len(inflight))
        try:
            self._finish_batch(rec)
        except BaseException as e:  # noqa: BLE001 — must answer callers
            for r in rec.batch:
                if not r.future.done():
                    r.future.set_exception(e)
            if not isinstance(e, Exception) and not swallow:
                raise

    # -- supervision --------------------------------------------------------

    def _supervise_loop(self) -> None:
        """Restart dead workers with capped exponential backoff. Exits
        when the drain flag flips (dead workers stay dead during drain —
        the queue close is what completes outstanding work then)."""
        while not self._draining.is_set():
            now = time.monotonic()
            live = 0
            with self._workers_lock:
                for i, t in enumerate(self._workers):
                    if t.is_alive():
                        live += 1
                        continue
                    if self._restart_at[i] is None:
                        # first observation of this death: schedule the
                        # restart after the slot's current backoff —
                        # and dump the flight ring (the "what happened
                        # just before the worker died" artifact)
                        self._restart_at[i] = now + self._restart_policy \
                            .delay(self._restarts[i])
                        self.flight.note_death(
                            "worker_death", slot=i,
                            error=type(self._worker_exits.get(i)).__name__
                            if self._worker_exits.get(i) else None)
                    elif now >= self._restart_at[i]:
                        self._restarts[i] += 1
                        self._restart_at[i] = None
                        self._workers[i] = self._spawn_worker(i)
                        self.metrics.counter("serve_worker_restarts").inc()
                        self.flight.record("worker_restart", slot=i,
                                           restarts=self._restarts[i])
                        live += 1
            self.metrics.gauge("serve_workers_live").set(live)
            if (self._rebalance_trigger is not None
                    and now >= self._next_rebalance_check):
                self._next_rebalance_check = (
                    now + self.config.rebalance_check_every_s)
                try:
                    self._auto_rebalance_tick(now)
                except Exception:  # noqa: BLE001 — a failed rebalance
                    # (e.g. a compile error warming a new census pair)
                    # must not unwind the supervisor: worker
                    # self-healing outranks the opt-in rebalance
                    self.metrics.counter(
                        "serve_auto_rebalance_errors").inc()
            if self._watchdog is not None:
                self._watchdog_tick(now)
            self._draining.wait(self.config.supervise_every_s)
        self.metrics.gauge("serve_workers_live").set(self.live_workers)

    def _watchdog_tick(self, now: float) -> None:
        """One rollback-watchdog step on the supervisor thread (ISSUE
        11 satellite): feed the counter sample, and when an armed
        post-swap comparison resolves against the new model, roll back
        CONDITIONALLY (expect_current pins the judged digest, so a
        watchdog racing an operator rollback refuses typed instead of
        double-flipping). The verdict is computed outside every lock;
        rollback itself is the O(1) pointer swap."""
        errors, resolved = self._error_counters()
        self._watchdog.sample(now, errors, resolved)
        verdict = self._watchdog.evaluate(now, errors, resolved)
        if verdict is None:
            return
        self.flight.record("watchdog_verdict", **verdict)
        if not verdict["fire"]:
            return
        try:
            self.rollback(expect_current=verdict["digest"])
        except swap_lib.SwapError:
            # the judged model already left (operator rollback / second
            # swap won the race) — nothing to protect against anymore
            self.metrics.counter("serve_watchdog_refused").inc()
            return
        self.metrics.counter("serve_watchdog_rollbacks").inc()
        self.flight.note_death("watchdog_rollback", **verdict)

    def _auto_rebalance_tick(self, now: float) -> None:
        """One skew check on the supervisor thread (single-threaded use
        of the trigger, its contract). Fires rebalance_placement() with
        the window's observed weights; the warm-before-swap contract
        there means an auto rebalance can compile (new census pairs)
        INLINE here — worker crash-restart healing pauses for the
        duration of the warm. Both costs are why auto mode is opt-in
        (rebalance_check_every_s)."""
        counts = {
            (bh, bw): self.metrics.counter(
                f"serve_bucket_requests_{bh}x{bw}").value
            for bh, bw in self.policy.buckets}
        weights = self._rebalance_trigger.observe(now, counts)
        self.metrics.gauge("serve_traffic_skew").set(
            self._rebalance_trigger.last_skew)
        if weights is None or self._num_devices <= 1:
            return
        self.rebalance_placement(weights=weights)
        self.metrics.counter("serve_auto_rebalances").inc()

    @property
    def _busy_ms(self) -> metrics_lib.Accumulator:
        """Wall time workers actually spent on batches (assemble +
        dispatch + finish); the denominator-side input of
        serve_overlap_ratio."""
        return self.metrics.accumulator("serve_busy_ms_total")

    def _device_busy(self, device: int) -> metrics_lib.Accumulator:
        """Per-device slice of the busy time — with per-device batch
        counts, the occupancy evidence serve_bench's --devices axis
        records (an idle device shows up as a flat line here)."""
        return self.metrics.accumulator(f"serve_device_busy_ms_d{device}")

    def _thread_codec(self, bundle: swap_lib.ModelBundle):
        """Entropy-stage codec for the CURRENT thread and the batch's
        model bundle: pool threads each own a BottleneckCodec clone PER
        EPOCH (per-pass rANS/buffer state stays thread-private; the
        clone shares its bundle codec's schedule-cached, lock-guarded
        incremental engine). Keying by epoch is the hot-swap coherence:
        a thread coding an old-bundle batch keeps using the old model's
        clone even after the swap commits. Clones of retired epochs are
        pruned lazily against the coordinator's live set."""
        if self._entropy_pool is None:
            return bundle.codec
        clones = getattr(self._codec_local, "clones", None)
        if clones is None:
            clones = self._codec_local.clones = {}
        codec = clones.get(bundle.epoch)
        if codec is None:
            codec = clones[bundle.epoch] = bundle.codec.thread_clone()
            if len(clones) > 3:
                live = set(self._swap.live_epochs())
                live.add(bundle.epoch)
                for e in [e for e in clones if e not in live]:
                    del clones[e]
        return codec

    def _start_batch(self, batch, device: int) -> Optional[_Inflight]:
        """Stage 1, on the worker thread. Serialized mode
        (entropy_workers=0) runs the whole batch here and returns None;
        pipelined mode dispatches the device stage / fans the entropy
        work out to the pool and returns the in-flight record for
        _finish_batch. `device` is the executor's placement index: the
        batch is placed there (mesh.py batch sharding) and computed
        against that device's replicated params."""
        faults.inject("serve.worker.batch")
        if self._batch_hook is not None:
            self._batch_hook(batch)
        kind, bucket = batch[0].key
        # ONE bundle read per batch: every stage below — device params,
        # entropy codec, process pool — comes from this capture, so a
        # hot swap landing mid-batch cannot tear it (serve/swap.py)
        bundle = self._swap.current
        t0 = time.monotonic()
        # batch formation is where queue wait ENDS: one queue.wait span
        # per sampled request (each has its own arrival), and an
        # always-on batch-seal flight event
        if self.tracer.enabled:
            for r in batch:
                ctx = r.trace
                if ctx is not None and ctx.sampled:
                    self.tracer.record(trace_lib.SPAN_QUEUE, r.arrival,
                                       t0, [ctx.trace_id],
                                       cls=r.priority)
        self.flight.record("batch_seal", op=kind, bucket=list(bucket),
                           size=len(batch), device=device)
        self.metrics.gauge("serve_queue_depth").set(self._batcher.depth)
        self.metrics.histogram("serve_batch_occupancy").observe(
            len(batch) / self.config.max_batch)
        if self._entropy_pool is None:
            if kind == ENCODE:
                device_ms, entropy_ms = self._run_encode(
                    batch, bucket, device, bundle)
            else:
                device_ms, entropy_ms = self._run_decode(
                    batch, bucket, device, bundle,
                    si=(kind == DECODE_SI))
            dt = (time.monotonic() - t0) * 1e3
            self._busy_ms.add(dt)
            self._device_busy(device).add(dt)
            self._note_batch_done(batch, t0, device_ms, entropy_ms, device,
                                  observe_latency=True)
            return None
        rec = _Inflight(kind, batch, bucket, t0, device, bundle)
        if kind == ENCODE:
            bh, bw = bucket
            x = np.zeros((self.config.max_batch, bh, bw, 3), np.float32)
            for i, r in enumerate(batch):
                x[i] = r.payload[0]
            params, bs = bundle.device_state[device]
            # async dispatch: the jit call returns before the device
            # finishes; the transfer happens in whichever pool task
            # first calls rec.handle.host() — the worker never blocks
            # here, so batch N+1's device call can follow immediately
            rec.handle = _DeviceBatch(self._encode_fn(
                params, bs, self.placement.put_batch(device, x)))
        else:
            if kind == DECODE_SI:
                # resolve the session BEFORE any entropy work is queued:
                # a gone/swapped session fails the batch typed here (the
                # worker loop answers every future) with nothing in
                # flight to flush
                rec.si_entry = self._resolve_session(batch, bundle)
            bh, bw = bucket
            sub = buckets_lib.SUBSAMPLING
            rec.sym = np.zeros((self.config.max_batch, bh // sub,
                                bw // sub, self._bn_channels), np.int32)
        # ONE pool task per micro-batch (ISSUE 7): the coding work runs
        # batch-native (one ctypes call per batch for encode, one per
        # wavefront for decode) so the C loop holds no GIL; per-request
        # isolation lives INSIDE the task, not in the fan-out
        rec.tasks = [self._entropy_pool.submit(self._entropy_batch_task,
                                               rec)]
        return rec

    def _item_failed(self, rec: _Inflight, i: int, req,
                     e: BaseException) -> None:
        """Record + answer one request's entropy-stage failure (the
        per-request isolation contract: an IntegrityError lands on that
        request's future only; a non-`Exception` crash is recorded for
        _finish_batch to re-raise on the worker thread)."""
        rec.per_item_exc[i] = e
        if not req.future.done():
            req.future.set_exception(e)
            self._observe_latency(req)
        if isinstance(e, IntegrityError):
            self.metrics.counter("serve_integrity_errors").inc()
        if not isinstance(e, Exception):
            rec.crash = e

    def _entropy_lane_bytes(self) -> int:
        """Payload bound for ONE entropy task/reply lane: a whole
        micro-batch of the largest bucket's symbol volumes at int64
        width, plus pickle slack. Oversize falls back inline by the
        lane contract, so this is a sizing hint, not a guarantee."""
        vol = max((d * h * w for (d, h, w) in self._warm_shapes),
                  default=128 * 1024)
        return self.config.max_batch * vol * 8 + 65536

    def _make_entropy_proc(self, initargs):
        """A fresh "process"-backend pool for ONE bundle's CodecSpec.
        spawn (not fork): forking a process whose jax backend has live
        threads is a deadlock lottery. Workers rebuild the codec from
        the picklable spec ONCE (initializer) and warm every bucket's
        schedule there — worker-resident state, nothing re-pickled per
        task (coding/loader.py). Called from start(), prepare_swap(),
        and _proc_call's child-death rebuild.

        transport="shm" (ISSUE 17): each pool GENERATION gets its own
        lane ring (task + reply lanes, ALL allocated parent-side — the
        bridge blocks on the reply, so no cross-process free protocol
        is needed) whose manifest rides the worker initializer; the
        ring unlinks with the pool, so a wedged child's late writes
        land in a detached mapping, harmlessly."""
        import multiprocessing
        from concurrent.futures import ProcessPoolExecutor
        from dsin_tpu.coding import loader as loader_lib
        rings = None
        lane_manifest = None
        if self.config.transport == "shm":
            classes = shmlane_lib.derive_lane_classes(
                [("ent", self._entropy_lane_bytes())],
                2 * max(2, self._entropy_workers
                        * max(1, self.config.pipeline_depth)) + 2)
            rings = shmlane_lib.LaneRing.create("ent", classes,
                                                metrics=self.metrics)
            lane_manifest = rings.manifest()
        pool = ProcessPoolExecutor(
            max_workers=self._entropy_workers,
            mp_context=multiprocessing.get_context("spawn"),
            initializer=loader_lib.init_worker_codec,
            initargs=tuple(initargs) + (lane_manifest,))
        return _EntropyPool(pool, rings, self._entropy_lane_bytes())

    def _proc_call(self, bundle, fn, *args):
        """One coding task on the process backend, surviving child
        death: a pool worker that segfaults or is OOM-killed marks the
        whole ProcessPoolExecutor broken — every later submit raises
        BrokenProcessPool forever — so on that signal the first bridge
        thread here swaps in a fresh pool (the spawn initializer
        re-warms the worker-resident codecs) and every caller retries
        on it. A second break propagates and fails this batch's
        requests typed, but the NEXT batch again finds a fresh pool.
        A child that HANGS without dying (swap-thrash, stuck page-in
        while unpickling) never breaks the pool, so the .result() is
        bounded by entropy_proc_timeout_s: on expiry the wedged pool is
        swapped out the same way and the batch fails typed instead of
        hanging its futures — no retry, the task already burned the
        whole budget. A submit can also lose the swap race itself —
        another bridge thread replaced and shut down the pool between
        our read and the submit, which raises a bare RuntimeError, not
        BrokenProcessPool — equally retryable: nothing ran in a child.
        The bridge thread blocks GIL-free on the child doing the
        coding work — this .result() is the whole point of the process
        backend, and no lock is held across it."""
        from concurrent.futures import TimeoutError as FutTimeout
        from concurrent.futures.process import BrokenProcessPool
        timeout = self.config.entropy_proc_timeout_s
        last_exc = None
        for attempt in (0, 1):
            proc = bundle.proc()
            if proc is None:
                # the bundle was retired mid-batch (two swaps landed
                # inside one batch's lifetime) — fail this batch typed;
                # the NEXT batch captures a live bundle
                raise RuntimeError(
                    f"entropy pool of model bundle epoch {bundle.epoch} "
                    f"was retired while this batch was in flight")
            try:
                # lane the task per-ATTEMPT on the CURRENT generation's
                # ring (a retry after a pool swap must not reference
                # the dead generation's unlinked segment)
                fut, refs = self._submit_entropy(proc, fn, args)
            except RuntimeError as e:
                # either the pool is broken (BrokenProcessPool IS a
                # RuntimeError) or our `proc` read raced a concurrent
                # bridge thread's swap and submit found it shut down —
                # both are retryable on a fresh pool (nothing ran in
                # the child). Any other RuntimeError is not ours.
                if (not isinstance(e, BrokenProcessPool) and
                        "cannot schedule new futures" not in str(e)):
                    raise
                self._swap_entropy_proc(bundle, proc)
                last_exc = e
                continue
            try:
                out = fut.result(timeout)
                # resolve BEFORE the finally frees the reply lane
                return self._resolve_entropy(proc, out)
            except BrokenProcessPool as e:
                self._swap_entropy_proc(bundle, proc)
                last_exc = e
                continue
            except FutTimeout:
                self._swap_entropy_proc(bundle, proc)
                raise TimeoutError(
                    f"entropy process backend task exceeded {timeout}s "
                    f"(child alive but stuck); pool replaced") from None
            finally:
                # sole-allocator bookkeeping: the parent reclaims task
                # + reply lanes once the future settled, whatever
                # happened (no-op after a swap unlinked the ring)
                self._release_entropy(proc, refs)
        raise last_exc

    def _submit_entropy(self, proc, fn, args):
        """Submit one coding task -> (future, (task_ref, reply_ref)).
        Pipe transport submits as-is. shm transport lanes the payload
        (args[0]) when it is big enough and a lane is free — inline
        fallback otherwise, counted by the ring — and pre-claims a
        reply lane for the worker to write the result into."""
        rings = getattr(proc, "rings", None)
        if rings is None:
            return proc.submit(fn, *args), (None, None)
        payload, rest = args[0], args[1:]
        task_ref = rings.put_obj(payload)
        reply_ref = rings.claim(proc.reply_bytes)
        try:
            fut = proc.submit(
                fn, payload if task_ref is None else task_ref,
                *rest, reply=reply_ref)
        except BaseException:
            self._release_entropy(proc, (task_ref, reply_ref))
            raise
        return fut, (task_ref, reply_ref)

    def _resolve_entropy(self, proc, out):
        """A LaneRef result copies out of the reply lane (CRC-verified;
        corruption raises typed IntegrityError and fails the batch —
        never plausible wrong symbols). free=False: _proc_call's
        finally owns the reclaim."""
        if not isinstance(out, shmlane_lib.LaneRef):
            return out
        return proc.rings.take_obj(out, free=False)

    @staticmethod
    def _release_entropy(proc, refs) -> None:
        rings = getattr(proc, "rings", None)
        if rings is None:
            return
        for ref in refs:
            if ref is not None:
                rings.free(ref)

    def _swap_entropy_proc(self, bundle, seen) -> None:
        """Replace a bundle's broken/wedged pool with a fresh one built
        from ITS OWN CodecSpec (first bridge thread to report `seen`
        swaps; the rest find it already done) and abandon the old one
        without waiting on its children."""
        if bundle.swap_proc_if(
                seen,
                lambda: self._make_entropy_proc(bundle.proc_initargs)):
            self.metrics.counter("serve_entropy_proc_rebuilds").inc()
        seen.shutdown(wait=False)                # idempotent

    def _encode_vols(self, bundle, vols, trace=None) -> list:
        """N (D, H, W) symbol volumes -> [(payload, None) |
        (None, exc)] per lane (loader.encode_batch_isolated's
        contract on both backends), one batch call on the configured
        backend — always against the BATCH's bundle, never the live
        pointer (hot-swap coherence). `trace` (sampled TraceContexts)
        rides the process-backend task and comes back as a bit-checked
        echo with the child-side coding span (ISSUE 11)."""
        from dsin_tpu.coding import loader as loader_lib
        if bundle.proc_initargs is not None:
            out = self._proc_call(bundle, loader_lib.worker_encode_batch,
                                  vols, trace)
            if trace is not None:
                out, echo = out
                self._note_proc_echo(trace, echo)
            return out
        return loader_lib.encode_batch_isolated(self._thread_codec(bundle),
                                                vols)

    @staticmethod
    def _decode_with(codec, payloads) -> list:
        """[(volume, None) | (None, exc)] per payload — the shared
        lockstep-batch-with-per-lane-fallback contract lives in
        loader.decode_batch_isolated (one copy for both backends)."""
        from dsin_tpu.coding import loader as loader_lib
        return loader_lib.decode_batch_isolated(codec, payloads)

    def _decode_payloads(self, bundle, payloads, trace=None) -> list:
        if bundle.proc_initargs is not None:
            from dsin_tpu.coding import loader as loader_lib
            out = self._proc_call(bundle, loader_lib.worker_decode_batch,
                                  payloads, trace)
            if trace is not None:
                out, echo = out
                self._note_proc_echo(trace, echo)
            return out
        return self._decode_with(self._thread_codec(bundle), payloads)

    def _note_proc_echo(self, sent, echo: dict) -> None:
        """Process-backend trace echo (ISSUE 11): bit-check the
        contexts that rode the pool task against what came back —
        serialization must be lossless for ids to stitch — and record
        the child-side coding span (pid + coding_ms measured in the
        worker process, positioned at the bridge-side receive)."""
        back = echo.get("trace")
        if tuple(back or ()) != tuple(sent):
            # a mangled context cannot corrupt results (the lanes ride
            # separately) but it breaks stitching — surface it loudly
            self.metrics.counter("serve_trace_proc_mismatch").inc()
            return
        t1 = time.monotonic()
        t0 = t1 - echo.get("coding_ms", 0.0) / 1e3
        self.tracer.record(trace_lib.SPAN_ENTROPY_PROC, t0, t1,
                           [c.trace_id for c in sent],
                           pid=echo.get("pid"))

    def _decode_batch_lanes(self, batch, sym, decode, fail) -> None:
        """One micro-batch's decode-side entropy work under the
        per-request fault contract, shared by the pipelined task and the
        serialized path: the `serve.rans` fault site + payload-CRC
        re-verify run per lane, the batch decode isolates structural
        errors per lane (loader.decode_batch_isolated), and the sym
        write itself is guarded per lane — a CRC-valid stream whose
        DTPC header lies about the bucket geometry passes the door, so
        it must fail only ITS request, never its batchmates. `decode`
        maps payloads -> [(vol, exc)]; `fail(i, req, exc)` records one
        lane's failure."""
        good, payloads = [], []
        for i, req in enumerate(batch):
            try:
                data = faults.corrupt("serve.rans", req.payload[0])
                # re-verify right before the entropy decode: corruption
                # past the door (buffer damage, injected faults) must
                # raise typed, never decode to a plausible wrong image
                verify_crc(req.payload[2], "DSRV payload (worker)", data)
            except BaseException as e:  # noqa: BLE001 — isolate lanes
                fail(i, req, e)
            else:
                good.append(i)
                payloads.append(data)
        if not good:
            return
        for i, (vol, exc) in zip(good, decode(payloads)):
            if exc is None:
                # EXPLICIT shape check, not assignment-raises: numpy
                # BROADCASTS a compatible wrong geometry (a liar header
                # claiming (1, 1, 1) would constant-fill the slot and
                # resolve as a plausible wrong image instead of raising)
                h, w, c = sym[i].shape          # want vol = (C, h, w)
                if tuple(vol.shape) == (c, h, w):
                    sym[i] = np.transpose(vol, (1, 2, 0))
                    continue
                exc = ValueError(
                    f"decoded volume {tuple(vol.shape)} does not fit "
                    f"the bucket slot {sym[i].shape}")
            fail(i, batch[i], exc)

    def _entropy_batch_task(self, rec: _Inflight) -> tuple:
        """Stage 2, ONE entropy-pool task per micro-batch: batch-native
        rANS work (thread backend: in-process via the thread's codec
        clone; process backend: shipped to a worker-resident codec in
        the pool, this thread just bridges). Per-request semantics are
        preserved inside the task — the serve.rans fault site and the
        payload-CRC re-verify run per request, an IntegrityError lands
        on that request's future only, and encode futures resolve here
        the moment their frame is built. Never raises: a non-`Exception`
        (InjectedCrash class) is recorded on the record and re-raised by
        _finish_batch on the worker thread, where it kills the worker
        the supervisor owns. Returns the (start, end) entropy span."""
        te0 = te1 = None
        try:
            if self._entropy_hook is not None:
                for i, req in enumerate(rec.batch):
                    self._entropy_hook(rec, i, req)
            trace = self.tracer.sampled_tuple(rec.batch)
            if rec.kind == ENCODE:
                symbols = rec.handle.host()   # shared one-time transfer
                # the encode device span ends at the shared transfer:
                # the same dispatched->transfer_done instants the
                # device_ms metric integrates (cross-check contract)
                self.tracer.span_batch(
                    rec.batch, trace_lib.SPAN_DEVICE,
                    rec.handle.dispatched, rec.handle.transfer_done,
                    kind=rec.kind, bucket=list(rec.bucket),
                    device=rec.device)
                te0 = time.monotonic()
                vols = [np.transpose(symbols[i], (2, 0, 1))
                        for i in range(len(rec.batch))]
                payloads = self._encode_vols(rec.bundle, vols,
                                             trace=trace)
                te1 = time.monotonic()
                for i, req in enumerate(rec.batch):
                    payload, exc = payloads[i]
                    if exc is not None:
                        # per-request isolation, encode half: one
                        # lane's coding error (capacity exhaustion on
                        # a pathological stream) fails only ITS request
                        self._item_failed(rec, i, req, exc)
                        continue
                    h, w = req.payload[1]
                    req.future.set_result(EncodeResult(
                        stream=frame_stream(payload, (h, w), rec.bucket),
                        payload_bytes=len(payload),
                        bpp=len(payload) * 8.0 / (h * w),
                        shape=(h, w), bucket=rec.bucket,
                        model_digest=rec.bundle.digest))
                    self._observe_latency(req)
                # model-health telemetry (ISSUE 13): AFTER every future
                # resolved, still on this pool thread — the always-on
                # bpp export plus the head-sampled coding-gap pass
                # (pure numpy; the caller's latency never pays for it)
                if self.quality.enabled:
                    gap_codec = None
                    for i, req in enumerate(rec.batch):
                        payload, exc = payloads[i]
                        if exc is not None:
                            continue
                        h, w = req.payload[1]
                        self.quality.note_encode(
                            rec.bucket, (h, w), len(payload),
                            len(payload) + _FRAME_LEN)
                        if self.quality.sample_gap():
                            if gap_codec is None:
                                gap_codec = self._thread_codec(rec.bundle)
                            self.quality.observe_gap(
                                gap_codec, vols[i], payload, rec.bucket)
            else:
                te0 = time.monotonic()
                self._decode_batch_lanes(
                    rec.batch, rec.sym,
                    lambda p: self._decode_payloads(rec.bundle, p,
                                                    trace=trace),
                    lambda i, req, e: self._item_failed(rec, i, req, e))
                te1 = time.monotonic()
        except BaseException as e:  # noqa: BLE001 — answer every caller
            for i, req in enumerate(rec.batch):
                if i not in rec.per_item_exc and not req.future.done():
                    self._item_failed(rec, i, req, e)
            if not isinstance(e, Exception):
                rec.crash = e
        if te0 is not None and te1 is not None:
            self.metrics.histogram("serve_entropy_batch_ms").observe(
                (te1 - te0) * 1e3)
            self.tracer.span_batch(rec.batch, trace_lib.SPAN_ENTROPY,
                                   te0, te1, kind=rec.kind,
                                   backend=self.config.entropy_backend)
        return (te0, te1)

    def _finish_batch(self, rec: _Inflight) -> None:
        """Stage 3, back on the worker thread: wait for the record's
        entropy tasks, run the decode device stage, publish the batch
        metrics, then surface a recorded crash."""
        tf0 = time.monotonic()
        spans = [t.result() for t in rec.tasks]   # tasks never raise
        device_ms = 0.0
        if rec.kind == ENCODE:
            device_ms = rec.handle.device_ms
        elif len(rec.per_item_exc) == len(rec.batch):
            # every item already failed (CRC/decode): the jitted decode
            # would only reconstruct a zero tensor nobody reads — skip
            # the device call entirely (ISSUE 4 satellite)
            self.metrics.counter("serve_device_skipped_batches").inc()
        else:
            t_dev = time.monotonic()
            params, bs = rec.bundle.device_state[rec.device]
            sym_dev = self.placement.put_batch(rec.device, rec.sym)
            si_scores = None
            if rec.kind == DECODE_SI:
                out = self._si_decode_jit(params, bs, sym_dev,
                                          rec.si_entry.prep)
                if self._si_scores_enabled:
                    # (images, winning per-patch scores) — the score
                    # half is the SI-match quality signal (ISSUE 13)
                    imgs = np.asarray(out[0])
                    si_scores = np.asarray(out[1])
                else:
                    imgs = np.asarray(out)
            else:
                imgs = np.asarray(self._decode_fn(params, bs, sym_dev))
            t_dev_end = time.monotonic()
            device_ms = (t_dev_end - t_dev) * 1e3
            self.tracer.span_batch(rec.batch, trace_lib.SPAN_DEVICE,
                                   t_dev, t_dev_end, kind=rec.kind,
                                   bucket=list(rec.bucket),
                                   device=rec.device)
            if rec.kind == DECODE_SI:
                self.metrics.histogram("serve_si_search_ms").observe(
                    device_ms)
                # the SI device stage IS the fused search executable:
                # record it under its own name too, so an SI trace
                # reads decode->search->siNet at a glance and the
                # bench can cross-check serve_si_search_ms
                self.tracer.span_batch(
                    rec.batch, trace_lib.SPAN_SI_SEARCH, t_dev,
                    t_dev_end, session=rec.batch[0].session)
            for i, r in enumerate(rec.batch):
                if i in rec.per_item_exc:
                    continue       # its future already holds the error
                h, w = r.payload[1]
                r.future.set_result(
                    buckets_lib.crop_from_bucket(imgs[i], (h, w))
                    .astype(np.uint8))
                self._observe_latency(r)
            if si_scores is not None and self.quality.enabled:
                # per-session SI-match summary, after the futures
                # resolved; failed lanes decoded zeros — their scores
                # are meaningless and stay out
                for i, r in enumerate(rec.batch):
                    if i not in rec.per_item_exc:
                        self.quality.note_si_scores(r.session,
                                                    si_scores[i])
        starts = [s[0] for s in spans if s[0] is not None]
        ends = [s[1] for s in spans if s[1] is not None]
        entropy_ms = (max(ends) - min(starts)) * 1e3 \
            if starts and ends else 0.0
        dt = (time.monotonic() - tf0) * 1e3
        self._busy_ms.add(dt)
        self._device_busy(rec.device).add(dt)
        self._note_batch_done(rec.batch, rec.t0, device_ms, entropy_ms,
                              rec.device)
        if rec.crash is not None:
            raise rec.crash

    def _observe_latency(self, req) -> None:
        """Record arrival -> future-RESOLUTION latency — called at the
        moment the request's future is set, so pipelined mode does not
        bill the caller for pipeline dwell after their answer landed.
        With priority classes on, the per-class histogram carries the
        per-class p99 the frontdoor bench gates."""
        ms = (time.monotonic() - req.arrival) * 1e3
        self.metrics.histogram("serve_latency_ms").observe(ms)
        if self._priority_enabled and req.priority is not None:
            self.metrics.histogram(
                f"serve_latency_ms_{req.priority}").observe(ms)

    def _note_batch_done(self, batch, t0, device_ms, entropy_ms,
                         device: int, observe_latency: bool = False) -> None:
        now = time.monotonic()
        if observe_latency:
            # serialized path: futures resolved moments ago in _run_*,
            # so note-time latency is resolution-time latency
            for r in batch:
                self._observe_latency(r)
        _, bucket = batch[0].key
        # per-bucket traffic census: rebalance_placement()'s default
        # weights, and the evidence a placement decision is read against
        self.metrics.counter(
            f"serve_bucket_requests_{bucket[0]}x{bucket[1]}").inc(len(batch))
        self.metrics.counter(f"serve_device_batches_d{device}").inc()
        self.metrics.counter("serve_batches").inc()
        self.metrics.counter("serve_completed").inc(len(batch))
        self.metrics.histogram("serve_batch_ms").observe((now - t0) * 1e3)
        self.metrics.histogram("serve_device_ms").observe(device_ms)
        self.metrics.histogram("serve_entropy_ms").observe(entropy_ms)
        self.metrics.accumulator("serve_device_ms_total").add(device_ms)
        self.metrics.accumulator("serve_entropy_ms_total").add(entropy_ms)
        self.metrics.gauge("serve_xla_compiles").set(
            recompile.compilation_count())
        self._update_overlap_gauge()

    def _update_overlap_gauge(self) -> None:
        """serve_overlap_ratio = 1 - busy/(device+entropy): 0 when the
        stages run strictly serialized on the worker (busy == their
        sum), approaching 1 - max/sum as the pipeline hides one stage
        behind the other. Clamped at 0 — bookkeeping overhead can push
        a serialized worker's busy time slightly past the stage sum."""
        dev = self.metrics.accumulator("serve_device_ms_total").value
        ent = self.metrics.accumulator("serve_entropy_ms_total").value
        busy = self._busy_ms.value
        if dev + ent > 0:
            self.metrics.gauge("serve_overlap_ratio").set(
                max(0.0, 1.0 - busy / (dev + ent)))

    def _run_encode(self, batch, bucket, device: int,
                    bundle) -> Tuple[float, float]:
        """Serialized encode (entropy_workers=0): device then entropy,
        inline on the worker thread. Returns (device_ms, entropy_ms)."""
        bh, bw = bucket
        x = np.zeros((self.config.max_batch, bh, bw, 3), np.float32)
        for i, r in enumerate(batch):
            x[i] = r.payload[0]
        params, bs = bundle.device_state[device]
        t_dev = time.monotonic()
        symbols = np.asarray(self._encode_fn(
            params, bs, self.placement.put_batch(device, x)))
        t_ent = time.monotonic()
        from dsin_tpu.coding import loader as loader_lib
        vols = [np.transpose(symbols[i], (2, 0, 1))
                for i in range(len(batch))]
        payloads = loader_lib.encode_batch_isolated(bundle.codec, vols)
        for i, r in enumerate(batch):
            payload, exc = payloads[i]
            if exc is not None:
                # same per-request isolation contract as the pipelined
                # encode task: the lane's error stays on its future
                r.future.set_exception(exc)
                continue
            h, w = r.payload[1]
            r.future.set_result(EncodeResult(
                stream=frame_stream(payload, (h, w), bucket),
                payload_bytes=len(payload),
                bpp=len(payload) * 8.0 / (h * w),
                shape=(h, w), bucket=bucket,
                model_digest=bundle.digest))
        t_done = time.monotonic()
        # quality telemetry after t_done: the serialized path has no
        # pool to hide the sampled gap pass on, but it still must not
        # bill the entropy span/metric (the serve_bench cross-check)
        if self.quality.enabled:
            for i, r in enumerate(batch):
                payload, exc = payloads[i]
                if exc is not None:
                    continue
                h, w = r.payload[1]
                self.quality.note_encode(bucket, (h, w), len(payload),
                                         len(payload) + _FRAME_LEN)
                if self.quality.sample_gap():
                    self.quality.observe_gap(bundle.codec, vols[i],
                                             payload, bucket)
        # spans share the exact instants the stage metrics integrate
        # (the serve_bench cross-check holds them to each other)
        self.tracer.span_batch(batch, trace_lib.SPAN_DEVICE, t_dev,
                               t_ent, kind=ENCODE, bucket=list(bucket),
                               device=device)
        self.tracer.span_batch(batch, trace_lib.SPAN_ENTROPY, t_ent,
                               t_done, kind=ENCODE, backend="inline")
        return ((t_ent - t_dev) * 1e3, (t_done - t_ent) * 1e3)

    def _run_decode(self, batch, bucket, device: int, bundle,
                    si: bool = False) -> Tuple[float, float]:
        """Serialized decode (entropy_workers=0): entropy then device,
        inline on the worker thread. Returns (device_ms, entropy_ms).
        `si` routes the device stage through the fused SI executable
        against the batch's session prep (resolved FIRST — a gone
        session fails the batch typed before any entropy work)."""
        si_entry = self._resolve_session(batch, bundle) if si else None
        bh, bw = bucket
        sub = buckets_lib.SUBSAMPLING
        sym = np.zeros((self.config.max_batch, bh // sub, bw // sub,
                        self._bn_channels), np.int32)
        per_item_exc = {}
        t_ent = time.monotonic()

        def _fail(i, r, e):
            if not isinstance(e, Exception):
                raise e   # worker-killing injected crash, as before
            per_item_exc[i] = e
            if isinstance(e, IntegrityError):
                self.metrics.counter("serve_integrity_errors").inc()

        self._decode_batch_lanes(
            batch, sym, lambda p: self._decode_with(bundle.codec, p),
            _fail)
        t_ent_end = time.monotonic()
        entropy_ms = (t_ent_end - t_ent) * 1e3
        self.tracer.span_batch(batch, trace_lib.SPAN_ENTROPY, t_ent,
                               t_ent_end, kind=batch[0].key[0],
                               backend="inline")
        if len(per_item_exc) == len(batch):
            # whole batch failed before the device stage: decoding a
            # zero tensor would be pure wasted device work — answer the
            # callers and skip the jitted call (ISSUE 4 satellite)
            for i, r in enumerate(batch):
                r.future.set_exception(per_item_exc[i])
            self.metrics.counter("serve_device_skipped_batches").inc()
            return (0.0, entropy_ms)
        params, bs = bundle.device_state[device]
        t_dev = time.monotonic()
        sym_dev = self.placement.put_batch(device, sym)
        si_scores = None
        if si:
            out = self._si_decode_jit(params, bs, sym_dev, si_entry.prep)
            if self._si_scores_enabled:
                imgs = np.asarray(out[0])
                si_scores = np.asarray(out[1])
            else:
                imgs = np.asarray(out)
        else:
            imgs = np.asarray(self._decode_fn(params, bs, sym_dev))
        t_dev_end = time.monotonic()
        device_ms = (t_dev_end - t_dev) * 1e3
        self.tracer.span_batch(batch, trace_lib.SPAN_DEVICE, t_dev,
                               t_dev_end, kind=batch[0].key[0],
                               bucket=list(bucket), device=device)
        if si:
            self.metrics.histogram("serve_si_search_ms").observe(
                device_ms)
            self.tracer.span_batch(batch, trace_lib.SPAN_SI_SEARCH,
                                   t_dev, t_dev_end,
                                   session=batch[0].session)
        for i, r in enumerate(batch):
            if i in per_item_exc:
                r.future.set_exception(per_item_exc[i])
                continue
            h, w = r.payload[1]
            r.future.set_result(
                buckets_lib.crop_from_bucket(imgs[i], (h, w))
                .astype(np.uint8))
        if si_scores is not None and self.quality.enabled:
            for i, r in enumerate(batch):
                if i not in per_item_exc:
                    self.quality.note_si_scores(r.session, si_scores[i])
        return (device_ms, entropy_ms)
