"""Lock-guarded service metrics + stdlib health/metrics HTTP endpoint.

Everything an operator needs to answer "is the service keeping up":
queue depth, batch occupancy (how full the micro-batches actually run —
low occupancy at high load means max_wait_ms is mis-tuned), request
latency quantiles, rejection counters split by cause, and the XLA
compile count (any steady-state motion there is a bucket-policy bug;
dsin_tpu/utils/recompile.py is the source of truth).

No prometheus client dependency: counters/gauges/histograms are tiny
lock-guarded classes and the endpoint is `http.server` — the text format
is prometheus-compatible enough (`name value` lines) to scrape, and
`/healthz` + `/metrics?format=json` serve humans and tests.

Latency quantiles come from a bounded reservoir (last `maxlen` samples)
— exact percentiles over an unbounded run would grow memory, and a
sliding window is the operationally useful view anyway.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Mapping, Optional
from urllib.parse import parse_qs, urlparse

from dsin_tpu.utils import locks as locks_lib


#: every metric name the serve stack emits — the one central registry
#: `contract-registry-drift` resolves `.counter/.gauge/.histogram`
#: literals against (entries ending `*` are prefixes and cover the
#: f-string families, e.g. per-bucket/per-replica names). A new metric
#: is added HERE first; a literal that resolves to no row is a lint
#: finding, and a row no call site visits is one too.
METRIC_REGISTRY = (
    "federation_digest_skew",
    "federation_health_driver_errors",
    "federation_health_rollbacks",
    "federation_member_call_failures_*",
    "federation_member_evictions",
    "federation_member_readmissions",
    "federation_members",
    "federation_members_live",
    "federation_reconcile_failures",
    "federation_reconciles",
    "federation_rollbacks",
    "federation_rollout_aborts",
    "federation_rollout_promotions",
    "federation_rollout_wave_rollbacks",
    "federation_rollout_waves",
    "federation_rollouts",
    "federation_routed_*",
    "federation_routed_m_*",
    "federation_sessions_dropped_*",
    "federation_sessions_opened",
    "federation_sessions_pinned",
    "serve_admitted_*",
    "serve_auto_rebalance_errors",
    "serve_auto_rebalances",
    "serve_autoscale_downs",
    "serve_autoscale_errors",
    "serve_autoscale_fleet_rollbacks",
    "serve_autoscale_outstanding",
    "serve_autoscale_ups",
    "serve_batch_ms",
    "serve_batch_occupancy",
    "serve_batches",
    "serve_bpp_payload_*",
    "serve_bpp_wire_*",
    "serve_bucket_requests_*",
    "serve_buckets",
    "serve_canary_errors",
    "serve_canary_failures",
    "serve_canary_ms",
    "serve_canary_ok",
    "serve_canary_races",
    "serve_canary_runs",
    "serve_canary_swap_passes",
    "serve_canary_swap_refusals",
    "serve_canary_swap_skipped",
    "serve_coding_gap_bits",
    "serve_coding_gap_errors",
    "serve_coding_gap_pct_*",
    "serve_coding_gap_samples",
    "serve_completed",
    "serve_device_batches_d*",
    "serve_device_ms",
    "serve_device_skipped_batches",
    "serve_devices",
    "serve_entropy_batch_ms",
    "serve_entropy_ms",
    "serve_entropy_proc_rebuilds",
    "serve_executable_census",
    "serve_expired_*",
    "serve_flight_dumps",
    "serve_integrity_errors",
    "serve_latency_ms",
    "serve_latency_ms_*",
    "serve_overlap_ratio",
    "serve_pipeline_inflight",
    "serve_placement_rebalances",
    "serve_queue_depth",
    "serve_rejected_deadline",
    "serve_rejected_drain",
    "serve_rejected_overload",
    "serve_rejected_unavailable",
    "serve_resolved",
    "serve_rollbacks",
    "serve_router_digest_skew",
    "serve_router_evictions",
    "serve_router_expired_*",
    "serve_router_readmissions",
    "serve_router_replica_deaths",
    "serve_router_replicas",
    "serve_router_replicas_total",
    "serve_router_reroutes",
    "serve_router_rollbacks",
    "serve_router_routed_*",
    "serve_router_routed_r*",
    "serve_router_scale_downs",
    "serve_router_scale_ups",
    "serve_router_session_orphans",
    "serve_router_sessions_dropped_*",
    "serve_router_sessions_opened",
    "serve_router_sessions_pinned",
    "serve_router_swap_aborts",
    "serve_router_swaps",
    "serve_session_bytes",
    "serve_session_evictions",
    "serve_session_evictions_*",
    "serve_sessions_live",
    "serve_sessions_opened",
    "serve_shed_*",
    "serve_shed_admission_*",
    "serve_shm_bytes",
    "serve_shm_fallback_*",
    "serve_shm_fallbacks",
    "serve_shm_frees",
    "serve_shm_integrity_errors",
    "serve_shm_sends",
    "serve_si_match_alarm_transitions",
    "serve_si_match_alarms",
    "serve_si_match_min_score",
    "serve_si_match_score",
    "serve_si_prep_ms",
    "serve_si_search_ms",
    "serve_submitted",
    "serve_swap_errors",
    "serve_swap_state",
    "serve_swaps",
    "serve_template_admits",
    "serve_template_failures",
    "serve_template_misses",
    "serve_template_ready",
    "serve_template_restocks",
    "serve_template_stale",
    "serve_trace_proc_mismatch",
    "serve_trace_spans",
    "serve_traffic_skew",
    "serve_typed_errors",
    "serve_warmup_compiles",
    "serve_watchdog_refused",
    "serve_watchdog_rollbacks",
    "serve_worker_crashes",
    "serve_worker_restarts",
    "serve_workers_live",
    "serve_xla_compiles",
)


class Counter:
    def __init__(self):
        self._lock = locks_lib.RankedLock("metrics.metric")
        self._value = 0                    # guarded-by: self._lock

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge:
    def __init__(self):
        self._lock = locks_lib.RankedLock("metrics.metric")
        self._value = 0.0                  # guarded-by: self._lock

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Accumulator:
    """Lock-guarded float total — a Counter for non-integer quantities
    (stage milliseconds, bytes). The pipelined dataplane keeps its
    device/entropy/busy wall-time sums here so `serve_overlap_ratio`
    can be recomputed from the snapshot alone."""

    def __init__(self):
        self._lock = locks_lib.RankedLock("metrics.metric")
        self._value = 0.0                  # guarded-by: self._lock

    def add(self, v: float) -> None:
        with self._lock:
            self._value += float(v)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Bounded-reservoir summary: count/mean (and all-time min/max)
    over everything ever observed, quantiles over the most recent
    `maxlen` samples. Min/max exist for the model-health signals
    (ISSUE 13): the worst coding gap and the weakest SI-match score ARE
    the alarm tails — a p99 over a sliding reservoir forgets the one
    catastrophic sample an operator needs to see."""

    def __init__(self, maxlen: int = 4096):
        self._lock = locks_lib.RankedLock("metrics.metric")
        self._window: deque = deque(maxlen=maxlen)  # guarded-by: self._lock
        self._count = 0                    # guarded-by: self._lock
        self._sum = 0.0                    # guarded-by: self._lock
        self._min = float("inf")           # guarded-by: self._lock
        self._max = float("-inf")          # guarded-by: self._lock

    def observe(self, v: float) -> None:
        with self._lock:
            self._window.append(float(v))
            self._count += 1
            self._sum += float(v)
            if v < self._min:
                self._min = float(v)
            if v > self._max:
                self._max = float(v)

    def quantile(self, q: float) -> float:
        """Nearest-rank quantile over the window; 0.0 when empty."""
        with self._lock:
            if not self._window:
                return 0.0
            xs = sorted(self._window)
        rank = min(len(xs) - 1, max(0, int(round(q * (len(xs) - 1)))))
        return xs[rank]

    def summary(self) -> Dict[str, float]:
        with self._lock:
            count, total = self._count, self._sum
            vmin, vmax = self._min, self._max
        return {
            "count": count,
            "mean": (total / count) if count else 0.0,
            "p50": self.quantile(0.50),
            "p99": self.quantile(0.99),
            "min": vmin if count else 0.0,
            "max": vmax if count else 0.0,
        }


class MetricsRegistry:
    """Named metric namespace; creation is idempotent so call sites just
    `registry.counter('x').inc()` without wiring declarations around."""

    def __init__(self):
        self._lock = locks_lib.RankedLock("metrics.registry")
        self._counters: Dict[str, Counter] = {}          # guarded-by: self._lock
        self._gauges: Dict[str, Gauge] = {}              # guarded-by: self._lock
        self._histograms: Dict[str, Histogram] = {}      # guarded-by: self._lock
        self._accumulators: Dict[str, Accumulator] = {}  # guarded-by: self._lock
        self._info: Dict[str, object] = {}               # guarded-by: self._lock
        self._seq = 0                                    # guarded-by: self._lock

    # construct only on miss (not setdefault's eager default): building
    # a metric builds its RankedLock, which registers a stats ledger —
    # per-call throwaway construction would funnel every hot-path
    # accessor hit through that registration

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter()
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge()
            return g

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram()
            return h

    def accumulator(self, name: str) -> Accumulator:
        with self._lock:
            a = self._accumulators.get(name)
            if a is None:
                a = self._accumulators[name] = Accumulator()
            return a

    def set_info(self, name: str, value) -> None:
        """Publish a STRUCTURAL fact (JSON-able, e.g. the bucket->device
        census `serve_device_assignments`) that a flat numeric metric
        cannot carry. Rides the snapshot under "info" and renders as a
        `# name json` comment line in the text format — structure for
        humans/tests, no prometheus parser ever sees a non-numeric
        sample."""
        with self._lock:
            self._info[name] = value

    def snapshot(self) -> dict:
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
            accumulators = dict(self._accumulators)
            info = dict(self._info)
            # monotonic per-registry sequence + capture wall-clock
            # (ISSUE 11 satellite): every snapshot is provably FRESH —
            # a scrape whose seq did not advance (or whose timestamp is
            # old) came from a wedged/cached source, and the router's
            # AggregatedMetrics flags it instead of silently merging
            # stale numbers
            self._seq += 1
            seq = self._seq
        return {
            "seq": seq,
            "captured_at": time.time(),
            "info": info,
            "counters": {k: c.value for k, c in sorted(counters.items())},
            "gauges": {k: g.value for k, g in sorted(gauges.items())},
            "histograms": {k: h.summary()
                           for k, h in sorted(histograms.items())},
            "accumulators": {k: a.value
                             for k, a in sorted(accumulators.items())},
            # the ranked-lock ledgers (hold time, contention, inversions
            # — utils/locks.py) ride the same snapshot so one /metrics
            # scrape answers "is anything fighting over a lock"
            "locks": locks_lib.stats_snapshot(),
            "lock_order_inversions": locks_lib.inversion_count(),
        }

    def render_text(self) -> str:
        return render_snapshot_text(self.snapshot())


def render_snapshot_text(snap: dict) -> str:
    """Snapshot dict -> the prometheus-ish text format. Module-level so
    the router's AGGREGATED (fleet-merged) snapshot renders through the
    identical formatter as a single service's — one text dialect."""
    lines = []
    for k, v in snap["info"].items():
        lines.append(f"# {k} {json.dumps(v, sort_keys=True)}")
    for k, v in snap["counters"].items():
        lines.append(f"{k}_total {v}")
    for k, v in snap["gauges"].items():
        lines.append(f"{k} {v:g}")
    for k, v in snap["accumulators"].items():
        lines.append(f"{k} {v:g}")
    for k, s in snap["histograms"].items():
        lines.append(f"{k}_count {s['count']}")
        # min/max guarded with `in`: fleet-merged snapshots
        # (serve/router.py) may carry summaries from replicas that
        # predate them
        for stat in ("mean", "p50", "p99", "min", "max"):
            if stat in s:
                lines.append(f"{k}_{stat} {s[stat]:g}")
    for name, s in snap.get("locks", {}).items():
        stem = "lock_" + name.replace(".", "_")
        lines.append(f"{stem}_acquisitions_total "
                     f"{s['acquisitions']}")
        lines.append(f"{stem}_contentions_total {s['contentions']}")
        lines.append(f"{stem}_hold_ms_total {s['hold_ms_total']:g}")
    lines.append(f"lock_order_inversions_total "
                 f"{snap.get('lock_order_inversions', 0)}")
    return "\n".join(lines) + "\n"


# -- fleet/federation merge helpers (ISSUE 18) --------------------------------
#
# The SAME merge rules apply at both aggregation tiers — router over
# replica scrapes (serve/router.py AggregatedMetrics) and federation
# over member roll-ups (serve/federation.py FederatedMetrics): counters,
# gauges, and accumulators SUM; histograms fold as total count,
# count-weighted mean, worst-source p50/p99, and min/min-max/max tails.
# One implementation here keeps the two tiers from drifting.

def hist_partials(histograms: Dict[str, dict]) -> Dict[str, list]:
    """Seed the running merge state from one snapshot's histogram
    summaries: name -> [count, weighted_sum, p50s, p99s, mins, maxs]
    (min/max guarded with `in` for sources predating them)."""
    return {k: [s["count"], s["mean"] * s["count"], [s["p50"]],
                [s["p99"]],
                [s["min"]] if "min" in s else [],
                [s["max"]] if "max" in s else []]
            for k, s in histograms.items()}


def merge_numeric_sections(counters: Dict[str, float],
                           gauges: Dict[str, float],
                           accumulators: Dict[str, float],
                           hist: Dict[str, list], snap: dict) -> None:
    """Fold one source snapshot's numeric sections into the running
    merge state in place (histograms into `hist_partials` shape)."""
    for k, v in snap.get("counters", {}).items():
        counters[k] = counters.get(k, 0) + v
    for k, v in snap.get("gauges", {}).items():
        gauges[k] = gauges.get(k, 0.0) + v
    for k, v in snap.get("accumulators", {}).items():
        accumulators[k] = accumulators.get(k, 0.0) + v
    for k, s in snap.get("histograms", {}).items():
        part = hist.setdefault(k, [0, 0.0, [], [], [], []])
        part[0] += s["count"]
        part[1] += s["mean"] * s["count"]
        part[2].append(s["p50"])
        part[3].append(s["p99"])
        if "min" in s:
            part[4].append(s["min"])
        if "max" in s:
            part[5].append(s["max"])


def fold_hist_partials(hist: Dict[str, list]) -> Dict[str, dict]:
    """Running merge state -> final histogram summaries: quantiles do
    not compose exactly from summaries, so the aggregate reports the
    WORST source p50/p99 (the honest SLO view) while the alarm tails
    (min/max) survive the merge exactly."""
    return {
        k: {"count": c,
            "mean": (wsum / c) if c else 0.0,
            "p50": max(p50s) if p50s else 0.0,
            "p99": max(p99s) if p99s else 0.0,
            **({"min": min(mins)} if mins else {}),
            **({"max": max(maxs)} if maxs else {})}
        for k, (c, wsum, p50s, p99s, mins, maxs) in sorted(hist.items())}


class MetricsServer:
    """`/healthz` + `/metrics` (+ `/trace`, ISSUE 11) on a daemon
    thread; port 0 = ephemeral (tests read `.port` after start).

    `trace` is an optional provider called with the request's query
    params (flattened `{key: value}`) returning a JSON-able body — a
    service passes its tracer's view, the router passes the fleet-
    merged AggregatedTraces. Without a provider /trace answers 404, so
    pre-tracing deployments keep their exact surface."""

    def __init__(self, registry: MetricsRegistry,
                 health: Callable[[], dict],
                 port: int = 0, host: str = "127.0.0.1",
                 trace: Optional[Callable[[Mapping[str, str]],
                                          object]] = None):
        registry_ref, health_ref, trace_ref = registry, health, trace

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: ARG002
                pass  # request logging would interleave with service logs

            def _send(self, code: int, body: str, ctype: str) -> None:
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802 (BaseHTTPRequestHandler API)
                url = urlparse(self.path)
                if url.path == "/healthz":
                    state = health_ref()
                    # degraded (pool below configured but alive) still
                    # serves — a load balancer should keep routing here;
                    # unhealthy (zero workers) and draining must 503
                    code = (200 if state.get("status") in ("ok", "degraded")
                            else 503)
                    self._send(code, json.dumps(state), "application/json")
                elif url.path == "/metrics":
                    if "format=json" in (url.query or ""):
                        self._send(200, json.dumps(registry_ref.snapshot()),
                                   "application/json")
                    else:
                        self._send(200, registry_ref.render_text(),
                                   "text/plain; version=0.0.4")
                elif url.path == "/trace" and trace_ref is not None:
                    params = {k: v[-1] for k, v in
                              parse_qs(url.query or "").items()}
                    self._send(200, json.dumps(trace_ref(params),
                                               default=str),
                               "application/json")
                else:
                    self._send(404, "not found\n", "text/plain")

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> "MetricsServer":
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="serve-metrics", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
