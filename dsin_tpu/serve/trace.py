"""End-to-end request tracing + crash flight recorder (ISSUE 11).

The serve stack exports aggregate counters and p50/p99 histograms, which
answer "is the fleet keeping up" but not "why was THIS request slow" or
"what happened in the 200ms before that typed error". This module adds
the two missing evidence layers — stage-resolved per-request latency is
the methodology "Evaluating the Practicality of Learned Image
Compression" (PAPERS.md, arXiv 2207.14524) argues serving claims need:

* **Tracer** — span-based request tracing. A `TraceContext` (trace id +
  head sampling decision) is minted at admission (`service._submit` /
  the router's `_submit`) and rides `batcher.Request` through queue
  wait -> batch formation -> device dispatch -> entropy task (thread
  AND spawn-process backends; the context is serialized with the pool
  task and bit-checked on echo) -> SI session lookup/search -> frame,
  and crosses the replica pipe protocol so a front-door trace stitches
  the router hop and the replica-internal spans into ONE timeline.
  Spans land in a bounded per-process ring (the ranked `serve.trace`
  lock, utils/locks.py; O(1) append, overwrite-oldest) and export two
  ways: the `/trace` endpoint on the existing MetricsServer (JSON; the
  router's AggregatedTraces merges across replicas like PR 9's
  AggregatedMetrics) and a Chrome/Perfetto trace-event file
  (`dump_chrome`) for offline viewing.

  Sampling is HEAD-based and deterministic (a counter rotation at
  `sample_rate`, no RNG — the same stream samples the same requests
  every run), decided once at mint and carried by the context across
  every process boundary: a replica records spans for any context the
  front door sampled, regardless of its own rate. Requests that end in
  a TYPED ERROR are always visible: `error(ctx, exc)` records the
  error span with the trace id even for head-unsampled contexts, so an
  error trace id is never a dead end. The unsampled fast path records
  nothing and allocates nothing — one enabled-flag read plus a
  per-request attribute probe.

  Spans deliberately wrap DISPATCH boundaries (device-call issue to
  results-on-host, entropy task start to frame) and never enter jitted
  code, so tracing holds `CompilationSentinel(budget=0)`: enabling or
  disabling it cannot change any executable.

* **FlightRecorder** — a SECOND, always-on bounded ring of recent
  structured events: admission decisions, sheds, batch seals, swap
  transitions, session evictions, worker restarts, replica deaths.
  Whenever a typed error resolves a future or a worker/replica dies,
  the recorder auto-dumps the ring to a JSONL artifact (rate-limited,
  written by a dedicated daemon thread — never file I/O under a ranked
  lock) — turning every chaos_bench violation and production incident
  into a replayable timeline. With no `dump_dir` configured the ring
  still records and is queryable via `snapshot()`; only the file dump
  is off.

Both rings share the `serve.trace` rank (85): recording is legal from
under every serve-stack lock (the batcher condition at rank 10 resolves
shed victims whose done-callbacks record here; session evictions record
from under `serve.session` at 16; supervisor restarts from under
`serve.workers` at 20) while metric counters (rank 90) stay acquirable
from inside the recorders. Same-rank ring/meta locks are never nested.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import (Any, Callable, Dict, Iterable, List, Mapping,
                    NamedTuple, Optional, Sequence, Tuple)

from dsin_tpu.utils import locks as locks_lib

#: span taxonomy (README "Tracing & flight recorder"): one name per
#: pipeline stage, shared by the serialized and pipelined dataplanes so
#: a timeline reads the same in both modes
SPAN_QUEUE = "queue.wait"           # arrival -> batch formation
SPAN_DEVICE = "batch.device"        # device dispatch -> results on host
SPAN_ENTROPY = "batch.entropy"      # batch rANS work (bridge-side span)
SPAN_ENTROPY_PROC = "batch.entropy.proc"  # child-side coding (process backend)
SPAN_SI_SEARCH = "batch.si_search"  # fused decode->siFinder->siNet executable
SPAN_SESSION = "session.lookup"     # SI session store lookup at batch start
SPAN_ROUTER = "router.dispatch"     # front-door send -> future resolution
SPAN_FEDERATION = "federation.dispatch"  # federation hop -> member resolution
SPAN_ERROR = "error"                # typed-error resolution (always recorded)


class TraceContext(NamedTuple):
    """The unit that crosses every boundary: picklable, immutable, tiny.
    `sampled` is the HEAD decision — downstream layers record spans for
    a sampled context no matter their own rate, which is what stitches
    a front-door trace through a replica. Bit-identity across the
    replica pipe and the process entropy pool is pinned by tests
    (NamedTuple equality IS the bit-check)."""
    trace_id: str
    sampled: bool
    origin: str = "service"


class _Ring:
    """Bounded overwrite-oldest ring under the ranked `serve.trace`
    lock: O(1) append, snapshot returns oldest-first. Items are
    append-only dicts (never mutated after append), so snapshot's
    shallow copy is safe to hand out."""

    __slots__ = ("_lock", "_buf", "_n", "capacity")

    def __init__(self, capacity: int):
        if capacity < 1:
            raise ValueError(f"ring capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)   # immutable after construction
        self._lock = locks_lib.RankedLock("serve.trace")
        self._buf: List[Optional[dict]] = [None] * self.capacity  # guarded-by: self._lock
        self._n = 0                                               # guarded-by: self._lock

    def append(self, item: dict) -> None:
        with self._lock:
            self._buf[self._n % len(self._buf)] = item
            self._n += 1

    def snapshot(self) -> Tuple[List[dict], int]:
        """-> (items oldest-first, total ever appended)."""
        with self._lock:
            n = self._n
            cap = len(self._buf)
            if n <= cap:
                return [s for s in self._buf[:n]], n
            i = n % cap
            return self._buf[i:] + self._buf[:i], n


class Tracer:
    """Span recorder with deterministic head sampling.

    The recording surface is shaped for the dataplane's hot path:
    `span_batch(requests, ...)` reads each request's `.trace` attribute
    and records ONE span carrying every sampled trace id in the batch —
    when nothing is sampled it returns without allocating. All spans
    carry wall-clock anchors (`ts`) besides their monotonic-derived
    duration, so spans from different PROCESSES (router + replicas)
    land on one comparable timeline when merged."""

    def __init__(self, sample_rate: float = 0.0, capacity: int = 4096,
                 enabled: bool = True, metrics=None):
        if not 0.0 <= sample_rate <= 1.0:
            raise ValueError(f"trace sample_rate must be in [0, 1], "
                             f"got {sample_rate}")
        self._ring = _Ring(capacity)
        # mint state under its own same-rank lock (never nested with the
        # ring's: mint never records, record never mints)
        self._mint_lock = locks_lib.RankedLock("serve.trace")
        self._minted = 0       # guarded-by: self._mint_lock
        self._n_sampled = 0    # guarded-by: self._mint_lock
        self._rate = float(sample_rate)   # guarded-by: self._mint_lock
        self._enabled = bool(enabled)
        # per-process id prefix: ids stay unique across the fleet
        # (router + N replicas each mint) without coordination
        self._prefix = f"t{os.getpid():x}-{id(self) & 0xffff:04x}"
        self.metrics = metrics

    # -- knobs ---------------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self._enabled

    def set_enabled(self, on: bool) -> bool:
        """Flip the whole tracer (mint + record); returns the previous
        value. The bench's overhead comparison toggles this."""
        prev = self._enabled
        self._enabled = bool(on)
        return prev

    @property
    def sample_rate(self) -> float:
        with self._mint_lock:
            return self._rate

    def set_sample_rate(self, rate: float) -> float:
        if not 0.0 <= rate <= 1.0:
            raise ValueError(f"trace sample_rate must be in [0, 1], "
                             f"got {rate}")
        with self._mint_lock:
            prev, self._rate = self._rate, float(rate)
        return prev

    # -- minting -------------------------------------------------------------

    def mint(self, origin: str = "service") -> Optional[TraceContext]:
        """One context per admitted request. The sampling decision is a
        deterministic counter rotation at the configured rate (the
        serve_bench `_mixed_class` idiom): the Nth minted request is
        sampled iff floor((N+1)*rate) > floor(N*rate) — no RNG, so a
        replayed stream traces the same requests."""
        if not self._enabled:
            return None
        with self._mint_lock:
            n = self._minted
            self._minted = n + 1
            sampled = int((n + 1) * self._rate) > int(n * self._rate)
            if sampled:
                self._n_sampled += 1
        return TraceContext(f"{self._prefix}-{n:08x}", sampled, origin)

    # -- recording -----------------------------------------------------------

    def record(self, name: str, t0: float, t1: float,
               tids: Sequence[str], **args) -> None:
        """Low-level span append; `t0`/`t1` are time.monotonic() stage
        endpoints measured by the CALLER (the same instants the metric
        accumulators integrate, so the serve_bench cross-check can hold
        the two instrumentation layers to each other)."""
        if not self._enabled or not tids:
            return
        now_m = time.monotonic()
        span = {
            "name": name,
            "tid": tids[0],
            "tids": list(tids),
            # wall-clock anchor of the span START: comparable across
            # processes (monotonic bases are not)
            "ts": time.time() - (now_m - t0),
            "dur_ms": round((t1 - t0) * 1e3, 4),
            "pid": os.getpid(),
            "thread": threading.current_thread().name,
        }
        if args:
            span["args"] = args
        self._ring.append(span)
        if self.metrics is not None:
            # span volume on /metrics: ring occupancy vs overwrite rate
            # is how an operator sizes trace_capacity
            self.metrics.counter("serve_trace_spans").inc()

    def span_batch(self, requests: Iterable[Any], name: str,
                   t0: float, t1: float, **args) -> None:
        """Record one span for the SAMPLED subset of a batch's requests
        (each carrying `.trace`). The all-unsampled path allocates
        nothing: the id list is only built once a sampled context is
        seen."""
        if not self._enabled:
            return
        tids = None
        for r in requests:
            ctx = r.trace
            if ctx is not None and ctx.sampled:
                if tids is None:
                    tids = []
                tids.append(ctx.trace_id)
        if tids:
            self.record(name, t0, t1, tids, **args)

    def span_for(self, ctx: Optional[TraceContext], name: str,
                 t0: float, t1: float, **args) -> None:
        """Single-context convenience (the router's dispatch span)."""
        if ctx is not None and ctx.sampled:
            self.record(name, t0, t1, [ctx.trace_id], **args)

    def sampled_tuple(self, requests: Iterable[Any]
                      ) -> Optional[Tuple[TraceContext, ...]]:
        """The sampled contexts of a batch as a picklable tuple (what
        the process entropy backend serializes with its task), or None
        when nothing is sampled — the task then ships no trace bytes."""
        if not self._enabled:
            return None
        out = None
        for r in requests:
            ctx = r.trace
            if ctx is not None and ctx.sampled:
                if out is None:
                    out = []
                out.append(ctx)
        return tuple(out) if out else None

    def error(self, ctx: Optional[TraceContext],
              exc: BaseException) -> None:
        """Typed-error visibility: record the error span for ANY
        context, sampled or not — the always-on half of the sampling
        contract (an error trace id must resolve to at least its
        failure, never to nothing)."""
        if not self._enabled or ctx is None:
            return
        t = time.monotonic()
        self.record(SPAN_ERROR, t, t, [ctx.trace_id],
                    error=type(exc).__name__, message=str(exc)[:200])

    # -- export --------------------------------------------------------------

    def snapshot(self, trace_id: Optional[str] = None) -> dict:
        """{"spans": [...], "recorded": total appended, "dropped":
        overwritten count, "minted"/"sampled": mint census}. With
        `trace_id`, spans are filtered to that trace (primary id or
        batch membership)."""
        spans, total = self._ring.snapshot()
        if trace_id is not None:
            spans = [s for s in spans
                     if s["tid"] == trace_id or trace_id in s["tids"]]
        with self._mint_lock:
            minted, sampled, rate = (self._minted, self._n_sampled,
                                     self._rate)
        return {
            "spans": spans,
            "recorded": total,
            "dropped": max(0, total - self._ring.capacity),
            "capacity": self._ring.capacity,
            "enabled": self._enabled,
            "sample_rate": rate,
            "minted": minted,
            "sampled": sampled,
        }

    def stage_totals_ms(self) -> Dict[str, float]:
        """Summed span duration per stage name over the CURRENT ring —
        the tracer-side number the serve_bench cross-check holds
        against the `serve_*_ms` accumulators."""
        totals: Dict[str, float] = {}
        spans, _ = self._ring.snapshot()
        for s in spans:
            totals[s["name"]] = totals.get(s["name"], 0.0) + s["dur_ms"]
        return totals

    def reset(self) -> None:
        """Drop every recorded span (benches isolate passes); mint
        state (ids, sampling rotation) is preserved."""
        with self._ring._lock:
            self._ring._buf = [None] * self._ring.capacity
            self._ring._n = 0

    def http_snapshot(self, params: Mapping[str, str]) -> object:
        """The `/trace` endpoint body for this process (MetricsServer's
        trace provider contract): `?id=` filters one trace,
        `?format=chrome` returns the Chrome/Perfetto trace-event dict."""
        if params.get("format") == "chrome":
            return chrome_trace(self.snapshot()["spans"])
        return self.snapshot(trace_id=params.get("id"))

    def dump_chrome(self, path: str) -> int:
        """Write the ring as a Chrome/Perfetto trace-event file (load
        via chrome://tracing or ui.perfetto.dev); returns the number of
        events written. Temp+rename so a crash cannot truncate it."""
        events = chrome_trace(self.snapshot()["spans"])
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(events, f)
        os.replace(tmp, path)
        return len(events["traceEvents"])


def chrome_trace(spans: Sequence[dict]) -> dict:
    """Spans -> the Chrome trace-event JSON dict (complete 'X' events;
    `ts`/`dur` in microseconds per the format spec)."""
    events = []
    for s in spans:
        events.append({
            "name": s["name"],
            "ph": "X",
            "ts": s["ts"] * 1e6,
            "dur": s["dur_ms"] * 1e3,
            "pid": s["pid"],
            "tid": s["thread"],
            "args": {"trace_ids": s["tids"], **s.get("args", {})},
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def merge_trace_snapshots(parts: Sequence[dict]) -> List[dict]:
    """Fleet stitch: concatenate per-process span lists onto one
    timeline, ordered by their wall-clock anchors (the router's
    AggregatedTraces feeds this its own snapshot plus every replica
    scrape)."""
    spans: List[dict] = []
    for part in parts:
        spans.extend(part.get("spans", ()))
    spans.sort(key=lambda s: s["ts"])
    return spans


class FlightRecorder:
    """Always-on ring of recent structured events + typed-error/death
    triggered JSONL dumps.

    `record(kind, **fields)` is the O(1) hot-path surface (legal from
    under any serve-stack lock below `serve.trace`). `note_error` /
    `note_death` record AND schedule a dump; the dump itself — a ring
    snapshot written to `dump_dir/flight-<pid>-<seq>.jsonl` via
    temp+rename — runs on a dedicated daemon thread, rate-limited by
    `min_dump_interval_s` (a typed-error storm coalesces into one dump
    per interval, each covering the whole storm so far). `flush()`
    waits for every scheduled dump (tests and bench artifacts)."""

    def __init__(self, capacity: int = 2048,
                 dump_dir: Optional[str] = None,
                 min_dump_interval_s: float = 1.0,
                 metrics=None, enabled: bool = True):
        if min_dump_interval_s < 0:
            raise ValueError(f"min_dump_interval_s must be >= 0, got "
                             f"{min_dump_interval_s}")
        self._ring = _Ring(capacity)
        self._meta_lock = locks_lib.RankedLock("serve.trace")
        self._want = 0          # dump requests issued      guarded-by: self._meta_lock
        self._done = 0          # dump requests satisfied   guarded-by: self._meta_lock
        self._dumps = 0         # files written             guarded-by: self._meta_lock
        self._last_reason = None          # guarded-by: self._meta_lock
        self._last_dump_path: Optional[str] = None  # guarded-by: self._meta_lock
        self._thread: Optional[threading.Thread] = None  # guarded-by: self._meta_lock
        self._wake = threading.Event()
        self._closed = threading.Event()
        self._min_interval = float(min_dump_interval_s)
        self._dump_dir = dump_dir
        self._enabled = bool(enabled)
        self.metrics = metrics

    # -- knobs ---------------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self._enabled

    def set_enabled(self, on: bool) -> bool:
        prev = self._enabled
        self._enabled = bool(on)
        return prev

    @property
    def dump_dir(self) -> Optional[str]:
        return self._dump_dir

    # -- recording -----------------------------------------------------------

    def record(self, kind: str, **fields) -> None:
        if not self._enabled:
            return
        self._ring.append({"t": time.time(), "kind": kind, **fields})

    def note_error(self, exc: BaseException,
                   trace_id: Optional[str] = None) -> None:
        """A typed error just resolved a future: record it and schedule
        a dump — the '200ms before the error' forensic artifact."""
        if not self._enabled:
            return
        self.record("typed_error", error=type(exc).__name__,
                    message=str(exc)[:200], trace_id=trace_id)
        self.trigger_dump("typed_error")

    def note_death(self, what: str, **fields) -> None:
        """A worker/replica died: record + dump."""
        if not self._enabled:
            return
        self.record(what, **fields)
        self.trigger_dump(what)

    # -- dumping -------------------------------------------------------------

    def trigger_dump(self, reason: str) -> None:
        """Schedule a dump (no-op without a dump_dir). Never performs
        file I/O on the calling thread — callers may hold serve-stack
        locks."""
        if not self._enabled or self._dump_dir is None \
                or self._closed.is_set():
            return
        with self._meta_lock:
            self._want += 1
            self._last_reason = reason
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._dump_loop, name="serve-flight-dump",
                    daemon=True)
                self._thread.start()
        self._wake.set()

    def _dump_loop(self) -> None:
        last_dump_t = 0.0
        while True:
            self._wake.wait()
            if self._closed.is_set():
                return
            self._wake.clear()
            # rate limit OUTSIDE any lock; triggers landing during the
            # sleep coalesce into this dump (their events are already
            # in the ring when we snapshot)
            delay = self._min_interval - (time.monotonic() - last_dump_t)
            if delay > 0:
                time.sleep(delay)
            with self._meta_lock:
                want = self._want
                reason = self._last_reason
            events, _total = self._ring.snapshot()
            path = None
            try:
                os.makedirs(self._dump_dir, exist_ok=True)
                with self._meta_lock:
                    seq = self._dumps
                path = os.path.join(
                    self._dump_dir,
                    f"flight-{os.getpid()}-{seq:04d}.jsonl")
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    f.write(json.dumps({"kind": "_dump", "t": time.time(),
                                        "reason": reason,
                                        "events": len(events)},
                                       default=str) + "\n")
                    for ev in events:
                        f.write(json.dumps(ev, default=str) + "\n")
                os.replace(tmp, path)
            except OSError:
                path = None   # an unwritable dir must not kill the loop
            last_dump_t = time.monotonic()
            with self._meta_lock:
                self._done = want
                if path is not None:
                    self._dumps += 1
                    self._last_dump_path = path
            if path is not None and self.metrics is not None:
                self.metrics.counter("serve_flight_dumps").inc()

    def flush(self, timeout: float = 5.0) -> bool:
        """Block until every dump scheduled so far has been written
        (True) or the timeout passes (False)."""
        with self._meta_lock:
            target = self._want
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._meta_lock:
                if self._done >= target:
                    return True
            time.sleep(0.005)
        with self._meta_lock:
            return self._done >= target

    def close(self) -> None:
        """Stop the dump thread (drain path). Idempotent; events
        already recorded stay queryable."""
        self._closed.set()
        self._wake.set()
        with self._meta_lock:
            t = self._thread
        if t is not None:
            t.join(timeout=5)

    # -- export --------------------------------------------------------------

    def snapshot(self) -> List[dict]:
        events, _ = self._ring.snapshot()
        return events

    def meta(self) -> dict:
        """Dump bookkeeping for /trace, bench artifacts, and chaos
        violation reports."""
        events, total = self._ring.snapshot()
        with self._meta_lock:
            return {"events": len(events), "recorded": total,
                    "dumps": self._dumps,
                    "last_dump_path": self._last_dump_path,
                    "dump_dir": self._dump_dir,
                    "pending": max(0, self._want - self._done)}


def echo_context(ctx: TraceContext) -> TraceContext:
    """Process-pool propagation probe: returns the context exactly as
    received. Submitted to a REAL spawn executor by the bit-check test
    — equality after the round trip IS the serialization contract the
    entropy backend relies on."""
    return ctx
