"""Dynamic micro-batcher: bounded queues + same-bucket coalescing,
priority-class aware.

The throughput/latency trade every batched service makes, with explicit
failure semantics instead of the two silent ones:

* **Backpressure, not buffering**: `submit` on a full queue raises
  `ServiceOverloaded` IMMEDIATELY. An unbounded queue converts overload
  into unbounded memory growth plus latencies every client has already
  given up on — rejecting at the door is the only behavior a load
  balancer upstream can act on.
* **Deadlines, not zombie work**: a request whose deadline passes while
  queued is completed with `DeadlineExceeded` and never batched —
  serving an answer nobody is waiting for still costs a batch slot.

Priority classes (ISSUE 8): millions of users means tiered traffic, not
one FIFO — a bulk encode burst must not blow the p99 of a
latency-sensitive decode. The batcher therefore takes an ordered tuple
of `PriorityClass`es (first = most latency-sensitive; default: one
"default" class, the pre-priority behavior). Each class carries

* its own BOUNDED queue (`max_queue` per class, on top of the shared
  total bound) — a bulk flood can only ever occupy bulk's slots;
* a per-class DEFAULT DEADLINE (`default_deadline_ms`, applied at
  submit when the request carries none) — bulk work queued past its
  usefulness expires typed instead of rotting;
* a defined SHED ORDER under overload: when the shared total bound is
  hit, a higher-class submit evicts the NEWEST queued request of the
  lowest non-empty class below it (`interactive` admits while `bulk`
  sheds; the victim's future resolves with a typed per-class
  `ServiceOverloaded`). A submit with no lower-class victim sheds
  itself. Every shed/expiry error names its class and the depth at the
  moment of the decision, so shed decisions are debuggable from logs
  alone.

Coalescing: requests carry an opaque hashable `key` ((kind, bucket) in
the service); a batch only ever contains one (class, key), because one
key maps to one XLA executable. Popping is CLASS-THEN-BUCKET aware: a
worker serves the highest-priority class with work first, and within a
class picks keys ROUND-ROBIN across the live (non-empty) key queues —
the probe resumes after the last key served, so a hot small bucket
whose queue never drains cannot monopolize the workers: every live key
is at most #live-keys pops from service within its class
(weighted-fair across buckets; FIFO within a (class, key)). Strict
priority across classes is deliberate: bulk's starvation mode under
sustained interactive load is bounded by its own deadline/shed
contract, not by stealing interactive's latency budget. The worker
then waits up to `max_wait_ms` for the chosen queue to fill to
`max_batch` — the head request's age bounds added latency, late
same-bucket arrivals ride along free.

All batcher state lives under ONE condition — the named
`serve.batcher` rung (rank 10; the `on_expired`/`on_shed` callbacks
run under it and report into the metrics leaf locks, utils/locks.py)
— so tier-1 exercises all of it on CPU with no jax in sight.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import (AbstractSet, Any, Callable, Dict, Hashable, List,
                    NamedTuple, Optional, Sequence, Tuple)

from dsin_tpu.utils import locks as locks_lib

#: the two traffic classes the serve stack ships with (serve/router.py
#: routes by them; ServiceConfig.priority_classes enables them)
INTERACTIVE = "interactive"
BULK = "bulk"


class ServeError(RuntimeError):
    """Base for every request-rejection mode the service can answer with."""


class ServiceOverloaded(ServeError):
    """Queue full — shed load now; retry against another replica/later.

    Typed per class: `priority` names the class whose bound (or shed
    decision) produced this, `depth` the class/queue depth at that
    moment — both also spelled out in the message so a log line alone
    identifies the guilty queue (ISSUE 8 satellite)."""

    def __init__(self, msg: str, priority: Optional[str] = None,
                 depth: Optional[int] = None):
        super().__init__(msg)
        self.priority = priority
        self.depth = depth


class ServiceDraining(ServeError):
    """Service is shutting down — it finishes in-flight work only."""


class ServiceUnavailable(ServeError):
    """No live workers — nothing would drain the queue, so accepting the
    request could only park it until its deadline. Fail fast instead;
    the supervisor is restarting the pool (serve/service.py)."""


class DeadlineExceeded(ServeError):
    """Deadline passed while the request was still queued. `priority`
    names the request's class (per-class deadline accounting)."""

    def __init__(self, msg: str, priority: Optional[str] = None):
        super().__init__(msg)
        self.priority = priority


class UnknownPriorityClass(ServeError, ValueError):
    """The request names a traffic class this service was not
    configured with — client misuse, typed (contract-typed-raise) so
    the front door can reject it as a 4xx instead of a crash. Also a
    ValueError: callers that treated the old bare raise as argument
    validation keep working."""


@dataclass(frozen=True)
class PriorityClass:
    """One traffic class: its queue bound and its default deadline.
    Order in the `MicroBatcher(classes=...)` tuple IS the policy —
    earlier classes pop first and shed last."""
    name: str
    max_queue: int
    default_deadline_ms: Optional[float] = None

    def __post_init__(self):
        if self.max_queue < 1:
            raise ValueError(f"class {self.name!r}: max_queue must be "
                             f">= 1, got {self.max_queue}")
        if (self.default_deadline_ms is not None
                and self.default_deadline_ms <= 0):
            raise ValueError(f"class {self.name!r}: default_deadline_ms "
                             f"must be > 0, got {self.default_deadline_ms}")


def default_priority_classes(
        max_queue: int,
        interactive_deadline_ms: Optional[float] = None,
        bulk_deadline_ms: Optional[float] = None,
        bulk_max_queue: Optional[int] = None,
) -> Tuple[PriorityClass, PriorityClass]:
    """The shipped two-class policy: `interactive` pops first and sheds
    last; `bulk` takes the overload. Each class is bounded at
    `max_queue` by default (the shared total bound is what forces the
    shed interplay); cap bulk tighter with `bulk_max_queue`."""
    return (PriorityClass(INTERACTIVE, max_queue=max_queue,
                          default_deadline_ms=interactive_deadline_ms),
            PriorityClass(BULK,
                          max_queue=(max_queue if bulk_max_queue is None
                                     else bulk_max_queue),
                          default_deadline_ms=bulk_deadline_ms))


class Future:
    """Minimal one-shot result slot (stdlib Event; no asyncio loop to
    own). `add_done_callback` exists for the front door: the admission
    gate (serve/router.py) releases its per-class slot the moment the
    future resolves, on the resolving thread — callbacks must stay
    cheap and leaf-locked (they may run under the batcher condition,
    e.g. when a shed or drain resolves the future)."""

    def __init__(self):
        self._done = threading.Event()
        self._result: Any = None
        self._exc: Optional[BaseException] = None
        self._cb_lock = locks_lib.RankedLock("serve.future")
        # None once fired: late add_done_callback runs immediately
        self._callbacks: Optional[List[Callable]] = []  # guarded-by: self._cb_lock

    def _fire_callbacks(self) -> None:
        with self._cb_lock:
            cbs = self._callbacks or []
            self._callbacks = None
        for cb in cbs:
            cb(self)

    def set_result(self, value: Any) -> None:
        self._result = value
        self._done.set()
        self._fire_callbacks()

    def set_exception(self, exc: BaseException) -> None:
        self._exc = exc
        self._done.set()
        self._fire_callbacks()

    def add_done_callback(self, fn: Callable[["Future"], None]) -> None:
        """Run `fn(self)` once the future resolves — immediately (on the
        calling thread) if it already has, else exactly once on the
        resolving thread. Callbacks fire at most once per future even
        if a buggy caller double-resolves."""
        with self._cb_lock:
            if self._callbacks is not None:
                self._callbacks.append(fn)
                return
        fn(self)

    def done(self) -> bool:
        return self._done.is_set()

    def exception(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError("request still pending")
        return self._exc

    def result(self, timeout: Optional[float] = None) -> Any:
        exc = self.exception(timeout)
        if exc is not None:
            raise exc
        return self._result


class SessionKey(NamedTuple):
    """Internal queue key for session-affine requests (ISSUE 10): the
    routing half (`route` — the caller's `Request.key`, what `accept`
    filters and executables are keyed by) plus the session id. Two
    requests coalesce only when BOTH halves match, so a batch never
    mixes side images — one session, one device-resident SidePrep, one
    executable call."""
    route: Hashable
    session: str


@dataclass
class Request:
    """One unit of work. `payload` is opaque to the batcher; `key`
    decides what it may be batched with; `deadline` is absolute
    time.monotonic(); `priority` names a configured class (None = the
    batcher's first/most-latency-sensitive class, filled in at
    submit). `session` (ISSUE 10) narrows coalescing: requests sharing
    a key still only batch together when they also share the session —
    consumers' `accept` sets keep filtering on the key alone. `trace`
    (ISSUE 11) is the request's TraceContext (serve/trace.py), minted
    at admission and read by every pipeline stage that records a span —
    opaque to the batcher itself."""
    key: Hashable
    payload: Any
    deadline: Optional[float] = None
    future: Future = field(default_factory=Future)
    arrival: float = field(default_factory=time.monotonic)
    priority: Optional[str] = None
    session: Optional[str] = None
    trace: Optional[Any] = None


class MicroBatcher:
    """Bounded multi-queue with same-key coalescing, priority classes,
    deadlines, and drain.

    Contract:
      submit(req)        -> enqueue | raise ServiceOverloaded (typed:
                            class + depth in the message and on the
                            exception) / ServiceDraining; may SHED the
                            newest lower-class request to admit a
                            higher-class one when the total bound is hit
      next_batch(t)      -> [Request, ...] (one (class, key), 1..max_batch)
                            | [] on timeout | None once closed AND empty
      close()            -> reject everything queued with ServiceDraining;
                            workers mid-batch are unaffected (in-flight
                            work completes — that is the drain guarantee)

    Device-affine consumers (serve/placement.py): `next_batch(accept=…)`
    takes an optional key SET — keys outside it are invisible to THIS
    call (across every class), so a per-device executor only ever pops
    batches for buckets placed on its device while other executors
    drain the rest. The round-robin ring is shared across consumers
    (fairness is per-bucket, not per-consumer); a consumer whose
    accepted keys are all empty waits exactly like one facing an empty
    batcher.
    """

    def __init__(self, max_batch: int, max_wait_ms: float, max_queue: int,
                 on_expired=None, classes: Optional[Sequence[PriorityClass]]
                 = None, on_shed=None):
        if max_batch < 1 or max_queue < 1:
            raise ValueError("max_batch and max_queue must be >= 1")
        if max_wait_ms < 0:
            raise ValueError("max_wait_ms must be >= 0")
        self.max_batch = int(max_batch)
        self.max_wait = float(max_wait_ms) / 1000.0
        self.max_queue = int(max_queue)
        if classes is None:
            classes = (PriorityClass("default", max_queue=self.max_queue),)
        if not classes:
            raise ValueError("need at least one priority class")
        names = [pc.name for pc in classes]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate priority class names: {names}")
        #: pop-priority order: classes[0] pops first, sheds last
        self.classes: Tuple[PriorityClass, ...] = tuple(classes)
        self._by_name: Dict[str, PriorityClass] = {pc.name: pc
                                                   for pc in self.classes}
        self.default_class = self.classes[0].name
        #: called with (total expired, {class: count}) — deadline-expired
        #: requests (under the batcher lock — keep it leaf-locked and
        #: cheap, e.g. metric counters)
        self.on_expired = on_expired
        #: called with (class name, count) per overload shed — same
        #: under-the-lock contract as on_expired
        self.on_shed = on_shed
        self._cond = locks_lib.RankedCondition("serve.batcher")
        # per-class: key -> deque of requests
        self._queues: Dict[str, Dict[Hashable, deque]] = {
            pc.name: {} for pc in self.classes}  # guarded-by: self._cond
        # per-class: live keys in first-seen order / next-probe ring idx
        self._order: Dict[str, List[Hashable]] = {
            pc.name: [] for pc in self.classes}  # guarded-by: self._cond
        self._rr: Dict[str, int] = {pc.name: 0
                                    for pc in self.classes}  # guarded-by: self._cond
        self._class_depth: Dict[str, int] = {
            pc.name: 0 for pc in self.classes}   # guarded-by: self._cond
        self._depth = 0                    # guarded-by: self._cond
        self._closed = False               # guarded-by: self._cond

    # -- producer side ------------------------------------------------------

    def _shed_lower_locked(self, cls: str) -> bool:
        """The overload shed order: evict the NEWEST queued request of
        the lowest-priority non-empty class strictly below `cls`, so
        the incoming higher-class request can take its slot
        ("interactive admits while bulk sheds"). Newest-loses within
        the victim class: it has waited least, so shedding it wastes
        the least queue time. Returns False when no lower-class work is
        queued (the caller then sheds itself)."""
        idx = next(i for i, pc in enumerate(self.classes)
                   if pc.name == cls)
        for pc in reversed(self.classes[idx + 1:]):
            queues = self._queues[pc.name]
            if self._class_depth[pc.name] <= 0 or not queues:
                continue
            # newest request = the latest tail across the class's keys
            # (FIFO append keeps each deque's tail its newest)
            key = max(queues, key=lambda k: queues[k][-1].arrival)
            victim = queues[key].pop()
            if not queues[key]:
                self._drop_key_locked(pc.name, key)
            self._class_depth[pc.name] -= 1
            self._depth -= 1
            depth_now = self._class_depth[pc.name]
            victim.future.set_exception(ServiceOverloaded(
                f"shed under overload: class {pc.name!r} request at key "
                f"{key!r} (class depth now {depth_now}, total "
                f"{self._depth}/{self.max_queue}) gave its slot to an "
                f"incoming {cls!r} request",
                priority=pc.name, depth=depth_now))
            if self.on_shed is not None:
                self.on_shed(pc.name, 1)
            return True
        return False

    # contract: request-path — every reachable raise must be a typed error
    def submit(self, request: Request) -> None:
        with self._cond:
            if self._closed:
                raise ServiceDraining("service is draining; not accepting "
                                      "new requests")
            cls = request.priority
            if cls is None:
                cls = request.priority = self.default_class
            pc = self._by_name.get(cls)
            if pc is None:
                raise UnknownPriorityClass(
                    f"unknown priority class {cls!r} (configured: "
                    f"{[c.name for c in self.classes]})")
            if request.deadline is None and pc.default_deadline_ms is not None:
                request.deadline = (time.monotonic()
                                    + pc.default_deadline_ms / 1000.0)
            cd = self._class_depth[cls]
            if cd >= pc.max_queue:
                raise ServiceOverloaded(
                    f"class {cls!r} queue full ({cd}/{pc.max_queue}) at "
                    f"key {request.key!r} (total {self._depth}/"
                    f"{self.max_queue}) — shed at the door",
                    priority=cls, depth=cd)
            if self._depth >= self.max_queue and \
                    not self._shed_lower_locked(cls):
                raise ServiceOverloaded(
                    f"queue full (total {self._depth}/{self.max_queue}; "
                    f"class {cls!r} at {cd}/{pc.max_queue}) with no "
                    f"lower-priority victim to shed — {cls!r} request at "
                    f"key {request.key!r} shed at the door",
                    priority=cls, depth=self._depth)
            qkey = (request.key if request.session is None
                    else SessionKey(request.key, request.session))
            q = self._queues[cls].get(qkey)
            if q is None:
                q = self._queues[cls][qkey] = deque()
                self._order[cls].append(qkey)
            q.append(request)
            self._class_depth[cls] += 1
            self._depth += 1
            self._cond.notify_all()

    @property
    def depth(self) -> int:
        with self._cond:
            return self._depth

    def class_depths(self) -> Dict[str, int]:
        """{class: queued count} snapshot (front-door observability)."""
        with self._cond:
            return dict(self._class_depth)

    @property
    def closed(self) -> bool:
        with self._cond:
            return self._closed

    # -- consumer side ------------------------------------------------------

    def _drop_key_locked(self, cls: str, key: Hashable) -> None:
        """Remove an emptied key's queue AND its ring slot, keeping the
        class's round-robin probe pointed at the same successor key."""
        del self._queues[cls][key]
        order = self._order[cls]
        idx = order.index(key)
        del order[idx]
        if idx < self._rr[cls]:
            self._rr[cls] -= 1

    def _expire_locked(self) -> None:
        """Complete every already-dead queued request with
        DeadlineExceeded (holding the lock; O(depth), fine at service
        queue scales)."""
        now = time.monotonic()
        expired: Dict[str, int] = {}
        for cls, queues in self._queues.items():
            for key in list(queues):
                q = queues[key]
                if not any(r.deadline is not None and r.deadline <= now
                           for r in q):
                    continue
                alive = deque(r for r in q
                              if r.deadline is None or r.deadline > now)
                for r in q:
                    if r.deadline is not None and r.deadline <= now:
                        self._depth -= 1
                        self._class_depth[cls] -= 1
                        expired[cls] = expired.get(cls, 0) + 1
                        r.future.set_exception(DeadlineExceeded(
                            f"class {cls!r} deadline passed after "
                            f"{(now - r.arrival) * 1e3:.1f}ms in queue at "
                            f"key {key!r}", priority=cls))
                if alive:
                    queues[key] = alive
                else:
                    self._drop_key_locked(cls, key)
        if expired and self.on_expired is not None:
            self.on_expired(sum(expired.values()), expired)

    def _next_key_locked(self, accept: Optional[AbstractSet[Hashable]] = None
                         ) -> Optional[Tuple[str, Hashable]]:
        """Class-then-bucket pop order: serve the highest-priority class
        with eligible work, round-robin over ITS live keys in
        first-seen ring order, resuming after the last key served.
        Within a class every live key is at most len(ring) pops from
        service, so a hot bucket with a continuously-refilling queue
        cannot starve the others (oldest-head selection could: its head
        is always the oldest while a backlog of its own requests keeps
        arriving behind it). With `accept`, keys outside the set are
        skipped — they stay queued for a consumer that does accept
        them."""
        for pc in self.classes:
            cls = pc.name
            order = self._order[cls]
            n = len(order)
            if n == 0:
                continue
            start = self._rr[cls] % n
            for i in range(n):
                idx = (start + i) % n
                key = order[idx]
                # accept filters on the ROUTE half only: a device-affine
                # executor accepts (kind, bucket); which session rides
                # that bucket is batching policy, not placement
                route = key.route if isinstance(key, SessionKey) else key
                if accept is not None and route not in accept:
                    continue
                if self._queues[cls].get(key):
                    self._rr[cls] = idx + 1
                    return cls, key
        return None

    def next_batch(self, timeout: Optional[float] = None,
                   accept: Optional[AbstractSet[Hashable]] = None
                   ) -> Optional[List[Request]]:
        """Block until a batch is ready. Returns [] when `timeout` elapses
        with nothing to do (so worker loops can poll a stop flag), None
        once the batcher is closed and empty (worker should exit).
        `accept` restricts THIS call to a key set (device-affine
        executors); pending keys outside it neither match nor wake it
        beyond the shared condition's notify."""
        give_up = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                self._expire_locked()
                sel = self._next_key_locked(accept)
                if sel is None:
                    if self._closed:
                        return None
                    if give_up is not None:
                        remaining = give_up - time.monotonic()
                        if remaining <= 0:
                            return []
                        self._cond.wait(remaining)
                    else:
                        self._cond.wait()
                    continue
                cls, key = sel
                # coalesce: wait for the head's key to fill, bounded by the
                # HEAD's age so the first-in request caps the added latency
                full_at = self._queues[cls][key][0].arrival + self.max_wait
                while (not self._closed
                       and key in self._queues[cls]
                       and len(self._queues[cls][key]) < self.max_batch):
                    remaining = full_at - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
                    self._expire_locked()
                q = self._queues[cls].get(key)
                if not q:
                    continue   # everything expired or was rejected meanwhile
                batch = []
                while q and len(batch) < self.max_batch:
                    batch.append(q.popleft())
                    self._class_depth[cls] -= 1
                    self._depth -= 1
                if not q:
                    self._drop_key_locked(cls, key)
                return batch

    # -- drain --------------------------------------------------------------

    def close(self) -> int:
        """Stop accepting, reject everything still queued (they were never
        started, so 'rejected cleanly' is accurate), wake all waiters.
        Returns the number of rejected requests. Idempotent."""
        with self._cond:
            if self._closed:
                return 0
            self._closed = True
            rejected = 0
            for cls, queues in self._queues.items():
                for q in queues.values():
                    for r in q:
                        rejected += 1
                        r.future.set_exception(ServiceDraining(
                            "service drained before this request was "
                            "started"))
                queues.clear()
                self._order[cls].clear()
                self._rr[cls] = 0
                self._class_depth[cls] = 0
            self._depth = 0
            self._cond.notify_all()
            return rejected
