"""Dynamic micro-batcher: bounded queue + same-bucket coalescing.

The throughput/latency trade every batched service makes, with explicit
failure semantics instead of the two silent ones:

* **Backpressure, not buffering**: `submit` on a full queue raises
  `ServiceOverloaded` IMMEDIATELY. An unbounded queue converts overload
  into unbounded memory growth plus latencies every client has already
  given up on — rejecting at the door is the only behavior a load
  balancer upstream can act on.
* **Deadlines, not zombie work**: a request whose deadline passes while
  queued is completed with `DeadlineExceeded` and never batched —
  serving an answer nobody is waiting for still costs a batch slot.

Coalescing: requests carry an opaque hashable `key` ((kind, bucket) in
the service); a batch only ever contains one key, because one key maps
to one XLA executable. A worker picks keys ROUND-ROBIN across the live
(non-empty) key queues — the probe resumes after the last key served,
so a hot small bucket whose queue never drains cannot monopolize the
workers: every live key is at most #live-keys pops from service
(weighted-fair across buckets; FIFO within a key). The worker then
waits up to `max_wait_ms` for the chosen key's queue to fill to
`max_batch` — the head request's age bounds added latency, late
same-bucket arrivals ride along free.

All batcher state lives under ONE condition — the named
`serve.batcher` rung (rank 10, the hierarchy's outermost: the
`on_expired` callback runs under it and reports into the metrics leaf
locks, utils/locks.py) — so tier-1 exercises all of it on CPU with no
jax in sight.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import (AbstractSet, Any, Dict, Hashable, List, Optional)

from dsin_tpu.utils import locks as locks_lib


class ServeError(RuntimeError):
    """Base for every request-rejection mode the service can answer with."""


class ServiceOverloaded(ServeError):
    """Queue full — shed load now; retry against another replica/later."""


class ServiceDraining(ServeError):
    """Service is shutting down — it finishes in-flight work only."""


class ServiceUnavailable(ServeError):
    """No live workers — nothing would drain the queue, so accepting the
    request could only park it until its deadline. Fail fast instead;
    the supervisor is restarting the pool (serve/service.py)."""


class DeadlineExceeded(ServeError):
    """Deadline passed while the request was still queued."""


class Future:
    """Minimal one-shot result slot (stdlib Event; no asyncio loop to own)."""

    def __init__(self):
        self._done = threading.Event()
        self._result: Any = None
        self._exc: Optional[BaseException] = None

    def set_result(self, value: Any) -> None:
        self._result = value
        self._done.set()

    def set_exception(self, exc: BaseException) -> None:
        self._exc = exc
        self._done.set()

    def done(self) -> bool:
        return self._done.is_set()

    def exception(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError("request still pending")
        return self._exc

    def result(self, timeout: Optional[float] = None) -> Any:
        exc = self.exception(timeout)
        if exc is not None:
            raise exc
        return self._result


@dataclass
class Request:
    """One unit of work. `payload` is opaque to the batcher; `key` decides
    what it may be batched with; `deadline` is absolute time.monotonic()."""
    key: Hashable
    payload: Any
    deadline: Optional[float] = None
    future: Future = field(default_factory=Future)
    arrival: float = field(default_factory=time.monotonic)


class MicroBatcher:
    """Bounded multi-queue with same-key coalescing, deadlines, and drain.

    Contract:
      submit(req)        -> enqueue | raise ServiceOverloaded/ServiceDraining
      next_batch(t)      -> [Request, ...] (one key, 1..max_batch of them)
                            | [] on timeout | None once closed AND empty
      close()            -> reject everything queued with ServiceDraining;
                            workers mid-batch are unaffected (in-flight
                            work completes — that is the drain guarantee)

    Device-affine consumers (serve/placement.py): `next_batch(accept=…)`
    takes an optional key SET — keys outside it are invisible to THIS
    call, so a per-device executor only ever pops batches for buckets
    placed on its device while other executors drain the rest. The
    round-robin ring is shared across consumers (fairness is per-bucket,
    not per-consumer); a consumer whose accepted keys are all empty
    waits exactly like one facing an empty batcher.
    """

    def __init__(self, max_batch: int, max_wait_ms: float, max_queue: int,
                 on_expired=None):
        if max_batch < 1 or max_queue < 1:
            raise ValueError("max_batch and max_queue must be >= 1")
        if max_wait_ms < 0:
            raise ValueError("max_wait_ms must be >= 0")
        self.max_batch = int(max_batch)
        self.max_wait = float(max_wait_ms) / 1000.0
        self.max_queue = int(max_queue)
        #: called with the count of deadline-expired requests (under the
        #: batcher lock — keep it leaf-locked and cheap, e.g. a counter)
        self.on_expired = on_expired
        self._cond = locks_lib.RankedCondition("serve.batcher")
        self._queues: Dict[Hashable, deque] = {}  # guarded-by: self._cond
        # live keys in first-seen order / ring index of the next probe
        self._order: List[Hashable] = []   # guarded-by: self._cond
        self._rr = 0                       # guarded-by: self._cond
        self._depth = 0                    # guarded-by: self._cond
        self._closed = False               # guarded-by: self._cond

    # -- producer side ------------------------------------------------------

    def submit(self, request: Request) -> None:
        with self._cond:
            if self._closed:
                raise ServiceDraining("service is draining; not accepting "
                                      "new requests")
            if self._depth >= self.max_queue:
                raise ServiceOverloaded(
                    f"request queue full ({self._depth}/{self.max_queue})")
            q = self._queues.get(request.key)
            if q is None:
                q = self._queues[request.key] = deque()
                self._order.append(request.key)
            q.append(request)
            self._depth += 1
            self._cond.notify_all()

    @property
    def depth(self) -> int:
        with self._cond:
            return self._depth

    @property
    def closed(self) -> bool:
        with self._cond:
            return self._closed

    # -- consumer side ------------------------------------------------------

    def _drop_key_locked(self, key: Hashable) -> None:
        """Remove an emptied key's queue AND its ring slot, keeping the
        round-robin probe pointed at the same successor key."""
        del self._queues[key]
        idx = self._order.index(key)
        del self._order[idx]
        if idx < self._rr:
            self._rr -= 1

    def _expire_locked(self) -> None:
        """Complete every already-dead queued request with DeadlineExceeded
        (holding the lock; O(depth), fine at service queue scales)."""
        now = time.monotonic()
        expired = 0
        for key in list(self._queues):
            q = self._queues[key]
            if not any(r.deadline is not None and r.deadline <= now
                       for r in q):
                continue
            alive = deque(r for r in q
                          if r.deadline is None or r.deadline > now)
            for r in q:
                if r.deadline is not None and r.deadline <= now:
                    self._depth -= 1
                    expired += 1
                    r.future.set_exception(DeadlineExceeded(
                        f"deadline passed after "
                        f"{(now - r.arrival) * 1e3:.1f}ms in queue"))
            if alive:
                self._queues[key] = alive
            else:
                self._drop_key_locked(key)
        if expired and self.on_expired is not None:
            self.on_expired(expired)

    def _next_key_locked(self, accept: Optional[AbstractSet[Hashable]] = None
                         ) -> Optional[Hashable]:
        """Weighted-fair pop order: round-robin over the live keys in
        first-seen ring order, resuming after the last key served. Every
        live key is at most len(ring) pops from service, so a hot bucket
        with a continuously-refilling queue cannot starve the others
        (oldest-head selection could: its head is always the oldest
        while a backlog of its own requests keeps arriving behind it).
        With `accept`, keys outside the set are skipped — they stay
        queued for a consumer that does accept them."""
        n = len(self._order)
        if n == 0:
            return None
        start = self._rr % n
        for i in range(n):
            idx = (start + i) % n
            key = self._order[idx]
            if accept is not None and key not in accept:
                continue
            if self._queues.get(key):
                self._rr = idx + 1
                return key
        return None

    def next_batch(self, timeout: Optional[float] = None,
                   accept: Optional[AbstractSet[Hashable]] = None
                   ) -> Optional[List[Request]]:
        """Block until a batch is ready. Returns [] when `timeout` elapses
        with nothing to do (so worker loops can poll a stop flag), None
        once the batcher is closed and empty (worker should exit).
        `accept` restricts THIS call to a key set (device-affine
        executors); pending keys outside it neither match nor wake it
        beyond the shared condition's notify."""
        give_up = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                self._expire_locked()
                key = self._next_key_locked(accept)
                if key is None:
                    if self._closed:
                        return None
                    if give_up is not None:
                        remaining = give_up - time.monotonic()
                        if remaining <= 0:
                            return []
                        self._cond.wait(remaining)
                    else:
                        self._cond.wait()
                    continue
                # coalesce: wait for the head's key to fill, bounded by the
                # HEAD's age so the first-in request caps the added latency
                full_at = self._queues[key][0].arrival + self.max_wait
                while (not self._closed
                       and key in self._queues
                       and len(self._queues[key]) < self.max_batch):
                    remaining = full_at - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
                    self._expire_locked()
                q = self._queues.get(key)
                if not q:
                    continue   # everything expired or was rejected meanwhile
                batch = []
                while q and len(batch) < self.max_batch:
                    batch.append(q.popleft())
                    self._depth -= 1
                if not q:
                    self._drop_key_locked(key)
                return batch

    # -- drain --------------------------------------------------------------

    def close(self) -> int:
        """Stop accepting, reject everything still queued (they were never
        started, so 'rejected cleanly' is accurate), wake all waiters.
        Returns the number of rejected requests. Idempotent."""
        with self._cond:
            if self._closed:
                return 0
            self._closed = True
            rejected = 0
            for q in self._queues.values():
                for r in q:
                    rejected += 1
                    r.future.set_exception(ServiceDraining(
                        "service drained before this request was started"))
            self._queues.clear()
            self._order.clear()
            self._rr = 0
            self._depth = 0
            self._cond.notify_all()
            return rejected
