"""Model-health observability: coding gap, SI-match quality, golden canary.

PR 11 answered "why was THIS request slow"; nothing yet answered "is the
fleet still producing GOOD compression". Every ops metric stays green
while the serve stack ships a numerically degraded model, a
mispredicting context model, or uncorrelated side images — DSIN's value
IS its rate/distortion behavior, so this module (ISSUE 13) turns the
paper-level quantities into first-class production signals, following
the quality-measurement methodology of "Evaluating the Practicality of
Learned Image Compression" (PAPERS.md, arXiv 2207.14524):

* **Coding gap** — per-request realized payload bits vs the model's own
  `BottleneckCodec.ideal_bits` cross-entropy bound (ONE definition:
  `codec.coding_gap`, coding/codec.py). The bound costs a second
  incremental-engine pass per sampled request, so it is HEAD-SAMPLED
  with the PR 11 deterministic counter rotation (`gap_sample_rate`; no
  RNG — a replayed stream samples the same requests) and runs in the
  entropy pool after the request's future already resolved — never
  under a lock, never in jit, never on the caller's latency. Exported
  as per-bucket `serve_coding_gap_pct_<bh>x<bw>` histograms: the gap is
  rANS redundancy over the quantized tables, stable for a healthy
  model — a RISING gap means probclass no longer matches the data
  distribution.

* **SI-match quality** — the prepped siFinder search optionally returns
  its winning masked Pearson score per patch (ops/sifinder.py
  `with_scores`; the argmax path is bit-identical either way), and
  `QualityMonitor` summarizes them PER SESSION (mean/min top-score,
  fraction below the floor). A stereo/burst session whose side image
  stops correlating crosses `si_alarm_frac` below `si_score_floor` and
  arms a quality alarm — `serve_si_match_alarms` gauge, a transition
  counter, and a `quality_alarm` flight-recorder event — visible before
  users see mush.

* **Golden canary** — pinned deterministic inputs (`canary_inputs`, one
  per existing bucket shape: no new executables, budget-0 holds) driven
  through the REAL serve path on a period, output digests compared
  against goldens recorded in the checkpoint manifest
  (`manifest_extra["canary"]`, train/checkpoint.py) — or self-anchored
  at the first probe of a model whose manifest carries none. A mismatch
  is definitive (pinned inputs, deterministic executables): it exports
  `serve_canary_*` metrics, dumps the flight recorder, refuses a swap
  commit typed (`CanaryFailed`, serve/service.py `prepare_swap` probes
  the STAGED bundle) and, post-commit, arms the `RollbackWatchdog`
  alongside the typed-error signal (serve/swap.py).

All mutable state lives under the ranked `serve.quality` lock (rank 19,
utils/locks.py): above `serve.session` (the store's evict hook calls
`session_gone` from under rank 16) and below the flight/metric leaves
the telemetry reports into. Canary probes themselves hold NO quality
lock — they run the public submit path; only the verdict bookkeeping is
locked.
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from dsin_tpu.serve.batcher import ServeError
from dsin_tpu.utils import locks as locks_lib


class CanaryFailed(ServeError):
    """The golden canary's output digests disagree with the model's
    recorded goldens — the model computes something other than what its
    publisher verified (degraded params, numerics drift, a loading
    bug). A swap prepare raising this refuses the commit: the service
    keeps serving the old, known-good model."""


def digest_bytes(data: bytes) -> str:
    """The canary's ONE digest: 16 hex chars of sha256, matching the
    repo's params_digest width (coding/loader.py)."""
    return hashlib.sha256(data).hexdigest()[:16]


def bucket_key(bucket: Tuple[int, int]) -> str:
    return f"{bucket[0]}x{bucket[1]}"


def canary_inputs(buckets: Sequence[Tuple[int, int]],
                  seed: int) -> Dict[Tuple[int, int], Tuple[np.ndarray,
                                                            np.ndarray]]:
    """Deterministic pinned probe inputs, one (image, side image) pair
    per EXISTING bucket shape — the canary must ride the warmed
    executables, never mint one. The image is structured (gradient +
    seeded noise: exercises both the smooth and the textured regimes of
    probclass) and the side image is the same content shifted two
    pixels, so the SI search has a genuinely correlated match to find.
    Keyed by (seed, bucket) so every replica and every publisher derives
    bit-identical inputs with no coordination."""
    out = {}
    for bh, bw in buckets:
        rng = np.random.default_rng((int(seed), int(bh), int(bw)))
        yy = np.linspace(0.0, 255.0, bh, dtype=np.float32)[:, None, None]
        xx = np.linspace(0.0, 255.0, bw, dtype=np.float32)[None, :, None]
        grad = 0.5 * yy + 0.5 * xx
        noise = rng.uniform(-64.0, 64.0, (bh, bw, 3)).astype(np.float32)
        img = np.clip(grad + noise, 0, 255).astype(np.uint8)
        side = np.roll(img, shift=(2, 2), axis=(0, 1))
        out[(bh, bw)] = (img, side)
    return out


# contract: pure
def goldens_struct(seed: int, buckets: Sequence[Tuple[int, int]],
                   digests: Dict[str, Dict[str, Optional[str]]]
                   ) -> Dict[str, Any]:
    """The `manifest_extra["canary"]` schema a checkpoint publisher
    records (train/checkpoint.py validates the shape at save): the
    input seed, the bucket ladder the digests cover, and per-bucket
    {"encode", "decode", "decode_si"} output digests ("decode_si" is
    None when published without the SI path)."""
    return {"seed": int(seed),
            "buckets": [list(b) for b in buckets],
            "digests": {k: dict(v) for k, v in sorted(digests.items())}}


# contract: pure
def validate_goldens(goldens: Any) -> Optional[str]:
    """Structural check of a manifest `canary` entry; returns a human
    reason when malformed, None when well-formed. Shared by the
    manifest writer (refuse publishing junk) and the swap-time reader
    (a malformed entry is a refusal, not a skip)."""
    if not isinstance(goldens, dict):
        return f"canary goldens must be a dict, got {type(goldens).__name__}"
    if not isinstance(goldens.get("seed"), int):
        return "canary goldens carry no integer 'seed'"
    bks = goldens.get("buckets")
    if (not isinstance(bks, list) or not bks
            or any(not isinstance(b, (list, tuple)) or len(b) != 2
                   for b in bks)):
        return "canary goldens carry no bucket ladder"
    digs = goldens.get("digests")
    if not isinstance(digs, dict) or not digs:
        return "canary goldens carry no per-bucket digests"
    for key, entry in digs.items():
        if not isinstance(entry, dict) or "encode" not in entry \
                or "decode" not in entry:
            return (f"canary goldens bucket {key!r} must record 'encode' "
                    f"and 'decode' digests")
    return None


# contract: pure
def compare_goldens(expected: Dict[str, Any],
                    observed: Dict[str, Dict[str, Optional[str]]], *,
                    seed: int,
                    buckets: Sequence[Tuple[int, int]]) -> List[str]:
    """Golden-vs-observed verdict; returns mismatch descriptions (empty
    = canary passes). The comparison REFUSES (reports) configuration
    skew it cannot verify across — a different canary seed or a bucket
    the goldens never covered — instead of silently skipping: goldens
    that cannot be checked protect nothing. `decode_si` compares only
    when both sides recorded it (a checkpoint published without the SI
    path still canaries its encode/decode on an SI-serving fleet)."""
    problems: List[str] = []
    bad = validate_goldens(expected)
    if bad is not None:
        return [bad]
    if int(expected["seed"]) != int(seed):
        return [f"goldens were recorded for canary seed "
                f"{expected['seed']}, this service probes seed {seed} — "
                f"different inputs cannot be compared"]
    want = expected["digests"]
    for bucket in buckets:
        key = bucket_key(bucket)
        if key not in want:
            problems.append(f"goldens record no digests for served "
                            f"bucket {key}")
            continue
        got = observed.get(key) or {}
        for op in ("encode", "decode", "decode_si"):
            exp_d = want[key].get(op)
            got_d = got.get(op)
            if exp_d is None or got_d is None:
                continue   # op not covered on one side: not comparable
            if exp_d != got_d:
                problems.append(f"{key} {op}: golden {exp_d}, "
                                f"observed {got_d}")
    return problems


# contract: pure
def wave_canary_verdict(quality: Optional[Dict[str, Any]],
                        expect_digest: str) -> Optional[bool]:
    """One member's aggregated quality roll-up -> wave-gate verdict for
    a just-committed digest (ISSUE 18: the rollout wave's canary gate,
    pure so the federation can poll it and tests can table-drive it).

    Returns False the moment ANY replica reports a failed/errored
    canary verdict AGAINST `expect_digest` — the probe ran through the
    new model's real serve path and mismatched, the one signal that
    must stop a promotion. Returns True only when every live canary
    verdict in the roll-up covers `expect_digest` and reports "ok"
    (verdicts still naming the OLD digest mean the prober simply has
    not rerun since the commit). Anything else — no verdicts yet,
    partial coverage, "busy"/"raced"/"skipped" statuses — is None:
    evidence still incomplete, keep polling until the gate's deadline
    (an expired deadline is the caller's typed failure, never a
    silent pass)."""
    canary = (quality or {}).get("canary") or {}
    if not canary:
        return None
    covering = {i: c for i, c in canary.items()
                if isinstance(c, dict)
                and c.get("digest") == expect_digest}
    if any(c.get("status") in ("failed", "error")
           for c in covering.values()):
        return False
    if (len(covering) == len(canary)
            and all(c.get("status") == "ok"
                    for c in covering.values())):
        return True
    return None


#: per-session score history bound: once a session has accumulated 2x
#: this many scores, its counters HALVE (an exponential decay in O(1)
#: state) — the running fraction then tracks roughly the last
#: _SI_WINDOW scores, so a long-healthy session whose side image stops
#: correlating alarms within ~one window instead of needing its whole
#: lifetime of good history outvoted. `min` stays all-time (the worst
#: score ever is forensic, not a rate).
_SI_WINDOW = 512


class _SiStats:
    """Per-session score accumulator (plain fields; the monitor's lock
    guards every access). `n`/`total`/`below` are decayed counts (see
    _SI_WINDOW); `seen` counts every score ever observed."""

    __slots__ = ("n", "seen", "total", "min", "below", "alarmed")

    def __init__(self):
        self.n = 0
        self.seen = 0
        self.total = 0.0
        self.min = float("inf")
        self.below = 0
        self.alarmed = False

    def fold(self, count: int, total: float, vmin: float,
             below: int) -> None:
        self.n += count
        self.seen += count
        self.total += total
        self.min = min(self.min, vmin)
        self.below += below
        if self.n >= 2 * _SI_WINDOW:
            self.n //= 2
            self.below = (self.below + 1) // 2
            self.total /= 2.0

    def summary(self, floor: float) -> Dict[str, float]:
        return {"n": self.seen,
                "mean": round(self.total / self.n, 4) if self.n else 0.0,
                "min": round(self.min, 4) if self.n else 0.0,
                "frac_below_floor": round(self.below / self.n, 4)
                if self.n else 0.0,
                "floor": floor,
                "alarmed": self.alarmed}


class QualityMonitor:
    """The dataplane-facing half of model-health telemetry: bpp export,
    sampled coding gap, and the per-session SI-match tracker. One
    instance per service; every `note_*` call runs on a dataplane
    thread (entropy pool task / worker finish) and touches only the
    `serve.quality` lock plus the flight/metric leaves above it."""

    def __init__(self, metrics, flight=None, enabled: bool = True,
                 gap_sample_rate: float = 1.0 / 16.0,
                 si_score_floor: float = 0.25,
                 si_alarm_frac: float = 0.5,
                 si_alarm_min_samples: int = 8):
        if not 0.0 <= gap_sample_rate <= 1.0:
            raise ValueError(f"gap_sample_rate must be in [0, 1], "
                             f"got {gap_sample_rate}")
        if not 0.0 < si_alarm_frac <= 1.0:
            raise ValueError(f"si_alarm_frac must be in (0, 1], "
                             f"got {si_alarm_frac}")
        if si_alarm_min_samples < 1:
            raise ValueError(f"si_alarm_min_samples must be >= 1, "
                             f"got {si_alarm_min_samples}")
        self.metrics = metrics
        self.flight = flight
        self._enabled = bool(enabled)
        self._lock = locks_lib.RankedLock("serve.quality")
        self._gap_n = 0                   # guarded-by: self._lock
        self._gap_rate = float(gap_sample_rate)  # guarded-by: self._lock
        self.si_score_floor = float(si_score_floor)
        self.si_alarm_frac = float(si_alarm_frac)
        self.si_alarm_min_samples = int(si_alarm_min_samples)
        self._si: Dict[str, _SiStats] = {}       # guarded-by: self._lock
        self._alarmed = 0                        # guarded-by: self._lock

    # -- knobs ---------------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self._enabled

    def set_enabled(self, on: bool) -> bool:
        """Flip observation (the bench's paired-overhead toggle).
        Executables never change — score outputs stay compiled in; only
        the host-side bookkeeping stops."""
        prev = self._enabled
        self._enabled = bool(on)
        return prev

    @property
    def gap_sample_rate(self) -> float:
        with self._lock:
            return self._gap_rate

    def set_gap_sample_rate(self, rate: float) -> float:
        """Retune the gap head sampler (benches force 1.0 to populate
        histograms in a short pass); returns the previous rate."""
        if not 0.0 <= rate <= 1.0:
            raise ValueError(f"gap_sample_rate must be in [0, 1], "
                             f"got {rate}")
        with self._lock:
            prev, self._gap_rate = self._gap_rate, float(rate)
        return prev

    # -- coding gap + bpp (encode path) --------------------------------------

    def sample_gap(self) -> bool:
        """The PR 11 deterministic head rotation at `gap_sample_rate`:
        the Nth encode is sampled iff floor((N+1)*r) > floor(N*r). The
        unsampled path is one lock-guarded counter bump."""
        if not self._enabled:
            return False
        with self._lock:
            rate = self._gap_rate
            if rate <= 0.0:
                return False
            n = self._gap_n
            self._gap_n = n + 1
            return int((n + 1) * rate) > int(n * rate)

    def note_encode(self, bucket: Tuple[int, int], shape: Tuple[int, int],
                    payload_bytes: int, wire_bytes: int) -> None:
        """Always-on bpp export (satellite: `EncodeResult.bpp` was
        computed then dropped): payload bpp (entropy-coded bits over
        ORIGINAL pixels) and wire bpp (the framed stream — DSRV header +
        CRC overhead visible) per bucket."""
        if not self._enabled:
            return
        h, w = shape
        px = max(1, h * w)
        key = bucket_key(bucket)
        self.metrics.histogram(f"serve_bpp_payload_{key}").observe(
            payload_bytes * 8.0 / px)
        self.metrics.histogram(f"serve_bpp_wire_{key}").observe(
            wire_bytes * 8.0 / px)

    def note_gap(self, bucket: Tuple[int, int], gap: Dict[str, float]
                 ) -> None:
        """Record one sampled gap measurement (`codec.coding_gap`'s
        dict) into the per-bucket histograms."""
        if not self._enabled:
            return
        key = bucket_key(bucket)
        self.metrics.histogram(f"serve_coding_gap_pct_{key}").observe(
            gap["gap_pct"])
        self.metrics.histogram("serve_coding_gap_bits").observe(
            gap["gap_bits"])
        self.metrics.counter("serve_coding_gap_samples").inc()

    def observe_gap(self, codec, volume: np.ndarray, stream: bytes,
                    bucket: Tuple[int, int]) -> Optional[Dict[str, float]]:
        """The sampled extra pass, called AFTER the request's future
        resolved (entropy-pool placement; pure numpy — the incremental
        engine holds no jax state, so this can never compile). A codec
        refusal (pathological stream) is swallowed into an error
        counter: telemetry must never fail a request that already
        succeeded."""
        if not self._enabled:
            return None
        try:
            gap = codec.coding_gap(volume, stream)
        except Exception:   # noqa: BLE001 — telemetry never hurts traffic
            self.metrics.counter("serve_coding_gap_errors").inc()
            return None
        self.note_gap(bucket, gap)
        return gap

    # -- SI-match quality (decode_si path) -----------------------------------

    def session_open(self, sid: str) -> None:
        """Register a session with the tracker (the service calls this
        right after the store `put`). Tracker entries exist ONLY
        between here and the store's evict hook: `note_si_scores` for
        an unknown sid drops the scores instead of lazily re-creating
        the entry — a batch finishing after its session was evicted
        must not resurrect a phantom session whose alarm nobody could
        ever clear."""
        with self._lock:
            self._si.setdefault(sid, _SiStats())

    def note_si_scores(self, sid: str, scores: np.ndarray) -> None:
        """Fold one request's winning per-patch scores into its
        session's summary and evaluate the alarm transition. Alarm
        semantics: once `si_alarm_min_samples` scores accumulated, a
        session with >= `si_alarm_frac` of them below `si_score_floor`
        ARMS (flight `quality_alarm` armed=True, transition counter,
        live-alarm gauge); recovery below half that fraction CLEARS —
        the hysteresis keeps a borderline session from flapping events.
        The counts decay past _SI_WINDOW scores, so a session's alarm
        latency is bounded by the window, not its lifetime. The
        no-transition fast path is O(1) under the lock (the live-alarm
        census is an incremental counter, never a scan)."""
        if not self._enabled:
            return
        scores = np.asarray(scores, dtype=np.float64).reshape(-1)
        if scores.size == 0:
            return
        floor = self.si_score_floor
        self.metrics.histogram("serve_si_match_score").observe(
            float(scores.mean()))
        self.metrics.histogram("serve_si_match_min_score").observe(
            float(scores.min()))
        transition = None
        with self._lock:
            st = self._si.get(sid)
            if st is None:
                # the session was evicted while this batch was in
                # flight (see session_open) — its summary is gone and
                # must stay gone
                return
            st.fold(scores.size, float(scores.sum()),
                    float(scores.min()), int((scores < floor).sum()))
            if st.seen >= self.si_alarm_min_samples:
                frac = st.below / st.n
                if not st.alarmed and frac >= self.si_alarm_frac:
                    st.alarmed = True
                    self._alarmed += 1
                    transition = ("armed", st.summary(floor),
                                  self._alarmed)
                elif st.alarmed and frac < self.si_alarm_frac / 2.0:
                    st.alarmed = False
                    self._alarmed -= 1
                    transition = ("cleared", st.summary(floor),
                                  self._alarmed)
        if transition is not None:
            state, summary, alarmed_now = transition
            self.metrics.counter("serve_si_match_alarm_transitions").inc()
            self.metrics.gauge("serve_si_match_alarms").set(alarmed_now)
            if self.flight is not None:
                self.flight.record("quality_alarm", signal="si_match",
                                   sid=sid, state=state, **summary)

    def session_gone(self, sid: str, reason: str) -> None:
        """SessionStore evict hook (runs under `serve.session`, rank 16
        — this lock ranks above it, so the nesting is legal): drop the
        session's stats and clear its live alarm."""
        with self._lock:
            st = self._si.pop(sid, None)
            if st is not None and st.alarmed:
                self._alarmed -= 1
            alarmed_now = self._alarmed
        if st is not None and st.alarmed:
            self.metrics.gauge("serve_si_match_alarms").set(alarmed_now)
            if self.flight is not None:
                self.flight.record("quality_alarm", signal="si_match",
                                   sid=sid, state="session_gone",
                                   reason=reason)

    def si_session_summaries(self) -> Dict[str, Dict[str, float]]:
        """{sid: {n, mean, min, frac_below_floor, floor, alarmed}} for
        /healthz, benches, and the chaos battery."""
        with self._lock:
            return {sid: st.summary(self.si_score_floor)
                    for sid, st in self._si.items()}


class CanaryState:
    """Baseline + verdict bookkeeping for the canary prober (the probes
    themselves run lock-free through the serve path; serve/service.py
    owns them). Baselines are keyed by SERVING DIGEST: a swap or
    rollback starts a fresh comparison — against the incoming model's
    manifest goldens when it carries comparable ones, else
    self-anchored at that model's first successful probe (drift
    detection without a publisher)."""

    def __init__(self, seed: int, metrics, flight=None):
        self.seed = int(seed)
        self.metrics = metrics
        self.flight = flight
        self._lock = locks_lib.RankedLock("serve.quality")
        # digest -> {"source": "manifest"|"self", "goldens": struct}
        self._baseline: Dict[str, Dict[str, Any]] = {}  # guarded-by: self._lock
        self._last: Optional[Dict[str, Any]] = None     # guarded-by: self._lock
        self._busy = False                              # guarded-by: self._lock

    def claim(self) -> bool:
        """One probe at a time (the background prober and an operator's
        manual `run_canary` must not interleave their serve-path
        requests): non-blocking — a loser returns False and skips."""
        with self._lock:
            if self._busy:
                return False
            self._busy = True
        return True

    def release(self) -> None:
        with self._lock:
            self._busy = False

    def baseline_for(self, model_digest: str, manifest: Optional[dict],
                     buckets: Sequence[Tuple[int, int]],
                     observed: Dict[str, Dict[str, Optional[str]]]
                     ) -> Tuple[str, List[str]]:
        """Resolve (anchoring if needed) the baseline for one probe's
        model and return ("manifest"|"self"|"anchored", mismatches)."""
        goldens = (manifest or {}).get("canary")
        # "comparable" means FULLY: well-formed, same input seed, and
        # covering every served bucket. The swap-time gate refuses a
        # partially-comparable manifest typed (adopting a NEW model
        # demands that strictness); the running prober instead
        # self-anchors — a healthy model serving a widened ladder must
        # drift-monitor, not page a permanent false canary failure.
        comparable = (goldens is not None
                      and validate_goldens(goldens) is None
                      and int(goldens.get("seed", -1)) == self.seed
                      and all(bucket_key(tuple(b)) in goldens["digests"]
                              for b in buckets))
        with self._lock:
            base = self._baseline.get(model_digest)
            if base is None:
                if comparable:
                    base = {"source": "manifest", "goldens": goldens}
                else:
                    # no comparable publisher truth: anchor on this
                    # first probe — later probes of the SAME digest
                    # must reproduce it bit for bit
                    base = {"source": "self",
                            "goldens": goldens_struct(
                                self.seed, buckets, observed)}
                    self._baseline[model_digest] = base
                    return "anchored", []
                self._baseline[model_digest] = base
            expected = base["goldens"]
        return base["source"], compare_goldens(
            expected, observed, seed=self.seed, buckets=buckets)

    def note_result(self, result: Dict[str, Any]) -> None:
        with self._lock:
            self._last = result
        self.metrics.set_info("serve_canary", result)

    @property
    def last(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._last
