"""Static shape buckets: arbitrary request shapes -> a fixed executable set.

XLA specializes every executable to exact shapes, so a service that jits
per request shape compiles without bound — the classic learned-codec
serving failure ("Evaluating the Practicality of Learned Image
Compression", PAPERS.md). The fix is the standard one: declare a SMALL
static set of padded bucket geometries up front, route every request to
the smallest bucket that fits, and pad. Steady-state executable count is
then `2 * len(buckets)` (one batched encode + one batched decode each),
which warm-up compiles once and `CompilationSentinel(budget=0)` pins
forever after (see tests/test_serve_service.py).

Padding uses edge replication, not zeros: the AE is convolutional, so a
hard black border would bleed ringing into the real pixels' receptive
fields AND cost rate (the context model would spend bits on the edge).
Replicated edges compress almost for free and are cropped away after
decode — the client only ever sees its original (h, w).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np

#: every bucket edge must divide by the AE's total subsampling factor so
#: the bottleneck grid is whole (coding/cli.py enforces the same for its
#: un-bucketed one-shot path)
SUBSAMPLING = 8

#: default geometry ladder: KITTI-ish wide shapes plus a square fallback,
#: all /8. Services with a known shape distribution pass their own.
DEFAULT_BUCKETS = ((128, 256), (256, 512), (384, 1280))


class NoBucketFits(ValueError):
    """Request larger than every configured bucket — a routing error the
    client must see immediately, not an OOM later."""


class BucketPolicy:
    """Maps (h, w) -> the smallest configured bucket that fits.

    "Smallest" means fewest padded pixels: buckets are tried in area
    order, ties broken by height, so a request never pays for a bigger
    executable than it needs.
    """

    def __init__(self, buckets: Sequence[Tuple[int, int]] = DEFAULT_BUCKETS):
        if not buckets:
            raise ValueError("need at least one bucket shape")
        seen = set()
        for bh, bw in buckets:
            if bh <= 0 or bw <= 0 or bh % SUBSAMPLING or bw % SUBSAMPLING:
                raise ValueError(
                    f"bucket {(bh, bw)} must be positive and divisible by "
                    f"the subsampling factor {SUBSAMPLING}")
            if (bh, bw) in seen:
                raise ValueError(f"duplicate bucket {(bh, bw)}")
            seen.add((bh, bw))
        self.buckets = tuple(sorted((tuple(b) for b in buckets),
                                    key=lambda b: (b[0] * b[1], b[0])))

    def bucket_for(self, h: int, w: int) -> Tuple[int, int]:
        if h <= 0 or w <= 0:
            # jaxlint: disable=contract-typed-raise -- synchronous arg
            # validation at the submission boundary (no future exists
            # yet); ValueError on malformed input is the documented
            # misuse contract
            raise ValueError(f"bad image shape ({h}, {w})")
        for bh, bw in self.buckets:
            if h <= bh and w <= bw:
                return (bh, bw)
        raise NoBucketFits(
            f"image ({h}, {w}) exceeds every bucket "
            f"{list(self.buckets)} — add a larger bucket to the service "
            f"config or downscale the request")

    def __repr__(self) -> str:
        return f"BucketPolicy({list(self.buckets)})"


def pad_to_bucket(img: np.ndarray, bucket: Tuple[int, int]) -> np.ndarray:
    """(h, w, 3) -> (bh, bw, 3) by edge replication (bottom/right).

    Always returns fresh storage, even on an exact fit: callers enqueue
    the result (serve/batcher.py), and an alias of the input would let a
    caller reusing its frame buffer corrupt work that is still queued."""
    h, w = img.shape[:2]
    bh, bw = bucket
    if h > bh or w > bw:
        # jaxlint: disable=contract-typed-raise -- unreachable on the
        # request path by construction: submit_encode picked this bucket
        # via bucket_for, which only returns covering buckets; defensive
        # invariant guard for direct callers
        raise ValueError(f"image ({h}, {w}) does not fit bucket {bucket}")
    if (h, w) == (bh, bw):
        return img.copy()
    return np.pad(img, ((0, bh - h), (0, bw - w), (0, 0)), mode="edge")


def crop_from_bucket(img: np.ndarray, shape: Tuple[int, int]) -> np.ndarray:
    """Inverse of pad_to_bucket: top-left (h, w) crop of the decoded
    bucket-sized reconstruction."""
    h, w = shape
    return img[:h, :w]
