"""Replica pipe protocol: the ONE place wire tuples are built and read.

The router parent and the replica child used to hand-build their pipe
messages at eight different call sites — three separate copies of the
stop tuple, two shapes of op send, and a parse in the child that had to
know both. That was survivable while the payload was always inline; the
shm lane transport (serve/shmlane.py) adds a second payload encoding
(a `LaneRef` descriptor standing in for the bytes), and a descriptor
op hand-built at one site but parsed by another's rules is exactly the
drift this module exists to make impossible. Router and child both
import these helpers; neither touches tuple indices directly.

Wire shapes (unchanged from the pre-shm protocol — the descriptor rides
in the payload SLOT, never a new tuple shape):

    request:  (op, rid, payload, priority, deadline_ms, trace)
    control:  (op, rid, payload, None, None)          # swap/rollback
    stop:     ("stop", None, None, None, None)
    answer:   (tag, rid, payload)    # "ready"/"failed"/"ok"/"err"/"bye"

Payload encoding: `wire_payload(ring, obj)` returns a LaneRef when the
ring accepts the pickled object into a lane (big enough to be worth it,
a lane free), else the object itself — the per-message inline fallback
IS the pipe path, bit-for-bit. `resolve_payload(ring, obj)` inverts it
on the receiving side; resolving a descriptor without a ring is a typed
refusal, never a silent pass-through of the wrong type.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

from dsin_tpu.serve import shmlane

#: pipe ops that drive the two-phase hot swap instead of carrying a
#: request; they target a SPECIFIC replica and are never rerouted on
#: death — a dead replica fails its swap phase, typed
CONTROL_OPS = frozenset(
    {"swap_prepare", "swap_commit", "swap_abort", "rollback"})

#: ops that carry a request payload eligible for the lane transport
REQUEST_OPS = frozenset({"encode", "decode", "decode_si"})

SESSION_OPS = frozenset({"session_open", "session_close"})

STOP = "stop"


def stop_msg() -> Tuple:
    """The graceful-shutdown frame (always inline, always tiny)."""
    return (STOP, None, None, None, None)


def control_msg(op: str, rid: int, payload: Any) -> Tuple:
    """A swap-phase/session control frame: 5-tuple, no deadline, no
    trace, payload always inline (digests and paths, never images)."""
    return (op, rid, payload, None, None)


def request_msg(op: str, rid: int, payload: Any,
                priority: Optional[str], deadline_ms: Optional[float],
                trace) -> Tuple:
    """A routed request frame. `payload` may be the object itself or a
    LaneRef from `wire_payload` — the tuple shape does not change."""
    return (op, rid, payload, priority, deadline_ms, trace)


def parse_request(msg: Tuple):
    """Child-side parse -> (op, rid, payload, priority, deadline_ms,
    trace). Control frames parse through the same shape (their last two
    slots are None and they carry no trace)."""
    op, rid, payload, priority, deadline_ms = msg[:5]
    trace = msg[5] if len(msg) > 5 else None
    return op, rid, payload, priority, deadline_ms, trace


def wire_payload(ring: Optional[shmlane.LaneRing], obj: Any) -> Any:
    """Encode one payload for the pipe: into a shm lane when the ring
    takes it (returns the LaneRef descriptor), else the object itself.
    A None ring is the pipe transport — always inline. Never raises on
    lane pressure; exhaustion/oversize fall back inline by contract."""
    if ring is None:
        return obj
    ref = ring.put_obj(obj)
    return obj if ref is None else ref


def resolve_payload(ring: Optional[shmlane.LaneRing], obj: Any,
                    *, free: bool = True) -> Any:
    """Decode one payload off the pipe: a LaneRef copies out of the
    ring (CRC-verified, lane freed unless the sender retains it), any
    other object IS the payload. Raises ShmLaneError on a descriptor
    with no ring to resolve it against — that is protocol drift, not a
    payload."""
    if not isinstance(obj, shmlane.LaneRef):
        return obj
    if ring is None:
        raise shmlane.ShmLaneError(
            "received a shm lane descriptor on a pipe-transport "
            "connection — sender and receiver disagree about the "
            "transport")
    return ring.take_obj(obj, free=free)
