"""Shared-memory lane transport: big payloads by descriptor, not by pipe.

Every router→replica dispatch and every process-entropy-pool task used
to round-trip its payload through a multiprocessing pipe: pickle, copy
into a kernel buffer, copy out, unpickle — two full copies per hop for
multi-MB image tensors, serialized behind the same file descriptor the
*control* traffic rides on. This module moves the bytes out of band: a
fixed set of **lanes** (fixed-size slots, grouped into size classes
sized from the bucket geometry) lives in one
`multiprocessing.shared_memory` segment per direction, the payload is
written into a free lane exactly once, and only a tiny `LaneRef`
descriptor — (ring, class, lane, offset, length) — travels over the
existing pipe. The receiver copies out of the mapped segment directly.

Discipline, in the same spirit as the DSIM/DSRV stream framing:

* **Every lane is framed**: `[length:u32le][crc:u32le][payload]` with
  the CRC32 chain from utils/integrity.py over (length-field, payload).
  A flipped bit anywhere in the frame fails `verify_crc` and raises the
  same typed `IntegrityError` the stream parsers use — shared memory is
  just another place bytes rot.
* **Geometry liars are caught before the CRC**: the descriptor carries
  the payload length; if the frame header inside the lane disagrees,
  `take()` raises IntegrityError without trusting either number.
* **Oversize or exhausted → per-message fallback**: `put()` returns
  None instead of blocking or tearing; the caller ships the payload
  inline over the pipe exactly as the pipe transport would (typed,
  counted via `serve_shm_fallback_*`, flight-recorded by the caller).
  The transport degrades to the pipe path message-by-message, never
  wedges on it.
* **One allocator process per ring, receiver frees**: lane state bytes
  (0 = free, 1 = claimed) live *inside* the segment. Exactly one
  process allocates on a given ring (the router for request rings, the
  replica's sender thread for result rings, the service parent for
  entropy task+reply rings); in-process allocator races are serialized
  by the rank-7 `serve.shmlane` RankedLock. The *receiver* frees a lane
  by storing 0 after copy-out — a single cross-process byte store. The
  allocator's free-scan may observe a stale 1 (missed free → transient
  exhaustion → inline fallback, benign); it can never observe a false
  0, because only the receiver writes 0 and only after it is done with
  the bytes.
* **Creator unlinks**: the creating process owns the segment name and
  is the only one that `unlink()`s. Attaching processes deregister from
  the resource tracker so a dying child cannot tear the segment out
  from under the parent (Python 3.10 has no `track=False`).

Metrics (registered by callers that pass a registry): serve_shm_sends,
serve_shm_bytes, serve_shm_frees, serve_shm_fallbacks plus the split
serve_shm_fallback_oversize / serve_shm_fallback_exhausted reasons.
"""

from __future__ import annotations

import pickle
import secrets
import struct
from dataclasses import dataclass
from multiprocessing import resource_tracker, shared_memory
from typing import Any, Dict, List, Optional, Sequence, Tuple

from dsin_tpu.utils import faults as faults_lib
from dsin_tpu.utils import locks as locks_lib
from dsin_tpu.utils.integrity import IntegrityError, frame_crc, verify_crc

#: Frame header: payload length (u32le) + CRC32 (u32le).
_HDR = struct.Struct("<II")
FRAME_OVERHEAD = _HDR.size

#: Lane sizes are rounded up to this many bytes.
_LANE_ALIGN = 4096

#: Payloads whose pickle is smaller than this are never worth a lane —
#: the descriptor + copy-out bookkeeping costs more than the pipe.
SMALL_INLINE_MAX = 16384


class ShmLaneError(RuntimeError):
    """A lane-transport invariant was violated (bad descriptor target,
    double free, segment gone). Distinct from IntegrityError, which
    means the *bytes* are suspect rather than the bookkeeping."""


@dataclass(frozen=True)
class LaneClass:
    """One size class inside a ring: `n_lanes` lanes of `lane_bytes`
    payload capacity each (frame overhead is accounted on top)."""

    name: str
    lane_bytes: int
    n_lanes: int

    def __post_init__(self):
        if self.lane_bytes <= 0 or self.n_lanes <= 0:
            raise ValueError(
                f"lane class {self.name!r} must have positive geometry "
                f"(lane_bytes={self.lane_bytes}, n_lanes={self.n_lanes})")


@dataclass(frozen=True)
class LaneRef:
    """Picklable descriptor for one claimed lane: this is what crosses
    the pipe instead of the payload. `offset` addresses the frame start
    inside the segment; `length` is the *payload* length the sender
    wrote (the in-lane header must agree or `take()` refuses)."""

    ring: str
    cls: str
    lane: int
    offset: int
    length: int


def derive_lane_classes(
    byte_bounds: Sequence[Tuple[str, int]], n_lanes: int,
) -> List[LaneClass]:
    """Build lane classes from (name, max_payload_bytes) bounds — one
    class per bucket/bound, each rounded up to the lane alignment, each
    with `n_lanes` lanes. Callers derive `byte_bounds` from the bucket
    geometry (HxWx3 at the widest dtype the results ship)."""
    classes = []
    for name, bound in byte_bounds:
        need = int(bound) + FRAME_OVERHEAD
        size = ((need + _LANE_ALIGN - 1) // _LANE_ALIGN) * _LANE_ALIGN
        classes.append(LaneClass(name, size, max(1, int(n_lanes))))
    return classes


class LaneRing:
    """One shared-memory segment holding every lane of one direction.

    Layout: `[state bytes, one per lane][pad to 64][class0 lanes]
    [class1 lanes]...` — derived deterministically from the class list,
    so `attach()` needs only the manifest (segment name + classes).
    """

    def __init__(self, shm: shared_memory.SharedMemory,
                 classes: Sequence[LaneClass], *, owner: bool,
                 metrics=None):
        self._shm = shm
        self._classes = list(classes)
        self._owner = owner
        self._metrics = metrics
        #: optional `(reason, payload_len) -> None` hook the owner sets
        #: to flight-record fallbacks (metrics alone lose the timeline)
        self.on_fallback = None
        self._closed = False
        # Serializes in-process allocators (claim/free-scan). Cross-
        # process frees bypass it by design — see module docstring.
        self._lock = locks_lib.RankedLock("serve.shmlane")
        self._layout: Dict[str, Tuple[int, int, int]] = {}  # name -> (state0, lane0, class)
        state = 0
        data = (sum(c.n_lanes for c in self._classes) + 63) // 64 * 64
        for i, c in enumerate(self._classes):
            self._layout[c.name] = (state, data, i)
            state += c.n_lanes
            data += c.n_lanes * c.lane_bytes
        self._size = data

    # -- construction ---------------------------------------------------

    @classmethod
    def create(cls, name_hint: str, classes: Sequence[LaneClass],
               metrics=None) -> "LaneRing":
        """Create the segment (creator = owner = the only unlinker) and
        zero the lane state bytes."""
        probe = cls(_NullShm(), classes, owner=True)
        name = f"dsin-{name_hint}-{secrets.token_hex(4)}"
        shm = shared_memory.SharedMemory(
            name=name, create=True, size=max(probe._size, _LANE_ALIGN))
        ring = cls(shm, classes, owner=True, metrics=metrics)
        n_states = sum(c.n_lanes for c in classes)
        shm.buf[:n_states] = bytes(n_states)
        return ring

    @classmethod
    def attach(cls, manifest: Dict[str, Any], metrics=None) -> "LaneRing":
        """Attach to an existing ring from its picklable manifest. The
        attach is deregistered from the resource tracker so this
        process's exit cannot unlink the creator's segment (3.10 has no
        SharedMemory(track=False))."""
        shm = shared_memory.SharedMemory(name=manifest["name"], create=False)
        try:  # pragma: no cover - tracker layout is an implementation detail
            resource_tracker.unregister(shm._name, "shared_memory")
        except Exception:
            pass
        classes = [LaneClass(*c) for c in manifest["classes"]]
        return cls(shm, classes, owner=False, metrics=metrics)

    def set_metrics(self, metrics) -> None:
        """Late-bind a registry (an attaching child builds its service
        — and so its registry — after the ring attach)."""
        self._metrics = metrics

    def manifest(self) -> Dict[str, Any]:
        return {
            "name": self._shm.name,
            "classes": [(c.name, c.lane_bytes, c.n_lanes)
                        for c in self._classes],
        }

    @property
    def name(self) -> str:
        return self._shm.name

    # -- allocation (one allocator process per ring) --------------------

    def claim(self, payload_len: int) -> Optional[LaneRef]:
        """Claim the smallest free lane that fits `payload_len` bytes of
        payload, or None (oversize / exhausted → caller falls back to
        the inline pipe path). Does not write the frame."""
        if self._closed:
            return None
        need = payload_len + FRAME_OVERHEAD
        fits_any = False
        with self._lock:  # guarded-by: serve.shmlane
            buf = self._shm.buf
            for c in self._classes:
                if c.lane_bytes < need:
                    continue
                fits_any = True
                state0, lane0, _ = self._layout[c.name]
                for i in range(c.n_lanes):
                    if buf[state0 + i] == 0:
                        buf[state0 + i] = 1
                        return LaneRef(self._shm.name, c.name, i,
                                       lane0 + i * c.lane_bytes,
                                       payload_len)
        reason = "exhausted" if fits_any else "oversize"
        self._count("serve_shm_fallbacks")
        self._count(f"serve_shm_fallback_{reason}")
        if self.on_fallback is not None:
            self.on_fallback(reason, payload_len)
        return None

    def put(self, data: bytes) -> Optional[LaneRef]:
        """Claim a lane and write the CRC-framed payload into it."""
        ref = self.claim(len(data))
        if ref is None:
            return None
        return self.write_into(ref, data)

    def put_obj(self, obj: Any) -> Optional[LaneRef]:
        """Pickle `obj` into a lane. Small pickles stay inline (None)
        without counting as a fallback — the lane would cost more than
        the pipe for them."""
        blob = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        if len(blob) < SMALL_INLINE_MAX:
            return None
        return self.put(blob)

    def write_into(self, ref: LaneRef, data: bytes) -> LaneRef:
        """Write the frame for `data` into an already-claimed lane (the
        reply-lane pattern: parent claims, worker writes). Returns a
        descriptor carrying the actual written length."""
        cls = self._class_of(ref)
        if len(data) + FRAME_OVERHEAD > cls.lane_bytes:
            raise ShmLaneError(
                f"payload of {len(data)} B does not fit lane class "
                f"{cls.name!r} ({cls.lane_bytes} B)")
        out = LaneRef(ref.ring, ref.cls, ref.lane, ref.offset, len(data))
        len_field = struct.pack("<I", len(data))
        crc = frame_crc(len_field, data)
        buf = self._shm.buf
        _HDR.pack_into(buf, ref.offset, len(data), crc)
        buf[ref.offset + FRAME_OVERHEAD:
            ref.offset + FRAME_OVERHEAD + len(data)] = data
        self._count("serve_shm_sends")
        self._count("serve_shm_bytes", len(data))
        return out

    # -- receive --------------------------------------------------------

    def take(self, ref: LaneRef, *, free: bool = True) -> bytes:
        """Copy the payload out of a lane, verifying the frame first:
        descriptor/header geometry must agree, then the CRC must hold.
        With `free=True` (receiver side) the lane state byte is released
        after copy-out; pass free=False when the allocator retains
        ownership (entropy task lanes, freed by the parent)."""
        cls = self._class_of(ref)
        if not (0 <= ref.lane < cls.n_lanes):
            raise ShmLaneError(
                f"descriptor names lane {ref.lane} of class {cls.name!r} "
                f"which has only {cls.n_lanes} lanes")
        state0, lane0, _ = self._layout[cls.name]
        offset = lane0 + ref.lane * cls.lane_bytes
        if offset != ref.offset:
            raise IntegrityError(
                f"shm lane {cls.name}[{ref.lane}]: descriptor offset "
                f"{ref.offset} disagrees with ring layout ({offset}) — "
                f"refusing to read through a lying descriptor")
        buf = self._shm.buf
        stored_len, stored_crc = _HDR.unpack_from(buf, offset)
        if stored_len != ref.length:
            raise IntegrityError(
                f"shm lane {cls.name}[{ref.lane}]: frame header claims "
                f"{stored_len} B but the descriptor promised "
                f"{ref.length} B — geometry liar; refusing to trust "
                f"either")
        if stored_len + FRAME_OVERHEAD > cls.lane_bytes:
            raise IntegrityError(
                f"shm lane {cls.name}[{ref.lane}]: frame header claims "
                f"{stored_len} B which overflows the {cls.lane_bytes} B "
                f"lane")
        data = bytes(buf[offset + FRAME_OVERHEAD:
                         offset + FRAME_OVERHEAD + stored_len])
        data = faults_lib.corrupt("serve.shm.lane", data)
        verify_crc(stored_crc, f"shm lane {cls.name}[{ref.lane}]",
                   struct.pack("<I", stored_len), data)
        if free:
            buf[state0 + ref.lane] = 0
            self._count("serve_shm_frees")
        return data

    def take_obj(self, ref: LaneRef, *, free: bool = True) -> Any:
        return pickle.loads(self.take(ref, free=free))

    def free(self, ref: LaneRef) -> None:
        """Release a claimed lane without reading it (send failed, or
        the parent reclaims a task/reply lane after the future settles).
        Idempotent from the sole allocator's point of view."""
        if self._closed:
            return
        cls = self._class_of(ref)
        state0, _, _ = self._layout[cls.name]
        with self._lock:  # guarded-by: serve.shmlane
            self._shm.buf[state0 + ref.lane] = 0
        self._count("serve_shm_frees")

    # -- lifecycle ------------------------------------------------------

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._shm.close()
        except Exception:
            pass

    def unlink(self) -> None:
        """Remove the segment name (creator only; attached processes
        keep valid mappings until they close). Safe to call twice."""
        self.close()
        if not self._owner:
            return
        try:  # pragma: no cover - tracker bookkeeping
            # keep the resource tracker balanced: a same-process attach
            # (tests, benches) unregistered the name; unlink() below
            # unregisters once more, and an unmatched unregister makes
            # the tracker daemon whine at interpreter exit. register()
            # is set-dedup'd, so this is a no-op in the common case.
            resource_tracker.register(self._shm._name, "shared_memory")
        except Exception:
            pass
        try:
            self._shm.unlink()
        except FileNotFoundError:
            pass
        except Exception:
            pass

    # -- internals ------------------------------------------------------

    def _class_of(self, ref: LaneRef) -> LaneClass:
        if self._closed:
            raise ShmLaneError("lane ring is closed")
        if ref.ring != self._shm.name:
            raise ShmLaneError(
                f"descriptor is for ring {ref.ring!r}, this is "
                f"{self._shm.name!r}")
        entry = self._layout.get(ref.cls)
        if entry is None:
            raise ShmLaneError(f"unknown lane class {ref.cls!r}")
        return self._classes[entry[2]]

    def _count(self, name: str, n: int = 1) -> None:
        if self._metrics is not None:
            self._metrics.counter(name).inc(n)


class _NullShm:
    """Size-probe stand-in so LaneRing.__init__ can compute the layout
    before the real segment exists."""

    name = "<probe>"
    buf = memoryview(b"")

    def close(self):
        pass
