"""Federated fleet tier (ISSUE 18): a router-of-routers with staged
rollout waves, wave-gated canary promotion, and partition-tolerant
auto-rollback.

Everything below this module is ONE host's fleet: a `FrontDoorRouter`
over N shared-nothing replica processes, with fleet-wide two-phase
swaps, an autoscaler, and a fleet-health rollback driver (PRs 8-14).
The ROADMAP north star — millions of users — means N such hosts behind
a global tier, and the single-fleet swap is all-or-nothing: one
unanimous commit with no blast-radius control. This module lifts every
existing ingredient exactly one tier:

* **FederatedRouter** treats each host's `FrontDoorRouter` as one
  `Member`. Health comes from the member's `AggregatedMetrics` roll-up
  with the SAME staleness veto the router applies to replica scrapes
  (member snapshots carry their own `seq`/`captured_at`; a frozen or
  cached member response replays the identical pair and is flagged,
  never merged). Members are evicted after `evict_after` consecutive
  failed health evidence polls and readmitted on one healthy poll —
  UNLESS their serving digest skews from the federation's, in which
  case readmission is refused (`federation_digest_skew`) exactly like
  the router refuses a skewed replica... with one addition, see
  "partition healing" below.

* **Sessions stay host-sticky.** Sids are globally unique (each
  service mints uuid-grade ids), so the federation pins sid -> member
  the same way the router pins sid -> replica. A pinned member that is
  not currently live answers typed `SessionExpired` at the federation
  door — the prep lives in exactly one process on exactly one host.

* **Admission budgets split hierarchically.** The federation door
  holds the AGGREGATE per-class budget (sum of live members' own
  fleet budgets, which are themselves replica-scaled) and re-derives
  it on every membership change — the same rescale-with-the-fleet
  discipline as the router's `_admission_per_replica`.

* **Checkpoint distribution** rides the CRC-verified
  `replicate_checkpoint` (train/checkpoint.py): a member with a
  `ckpt_root` gets the manifest staged into its own root before its
  swap — every payload byte verified on both sides, rotate+rename so a
  kill mid-distribution never leaves a torn destination.

* **Rollout waves** replace the unanimous single-fleet swap. A
  `RolloutPlan` names waves of members; each wave (a) distributes +
  two-phase-swaps its members, (b) holds at the CANARY GATE — polling
  each member's quality roll-up until the PR 12 golden-canary prober
  has probed the NEW digest through that member's real serve path
  (`quality.wave_canary_verdict`: verdicts still naming the old digest
  are "not yet", never "pass"), then (c) holds a SOAK window driving
  the PR 14 `FleetHealthPolicy` over each member's live health
  evidence. Any wave failure auto-rolls-back that wave (and, when the
  plan says so, the already-committed prior waves) CONDITIONALLY —
  `rollback(expect_digest=<new>)` per member, so a member whose own
  watchdog/driver already rolled itself back refuses typed and is
  counted converged, never fought — and raises typed `RolloutAborted`
  naming every wave's every member's outcome.

* **Partition healing.** A member partitioned away mid-rollout fails
  its scrapes and refuses control ops (typed `MemberUnreachable`,
  counted per member); the wave abort records the digest it rolled the
  federation away from. When the partition heals, the poll loop finds
  the member healthy but possibly serving that aborted digest — digest
  skew that would normally refuse readmission. Because the digest is
  in the aborted set, the federation instead RECONCILES: one
  conditional rollback (`expect_digest=<aborted>`) converges the
  member typed (or finds it already converged), and only then readmits
  — so "zero torn versions across the federation" holds through the
  partition without ever fighting a member-local driver.

* **Traces stitch across both router tiers.** The federation mints the
  `TraceContext` (its head sampling decision is honored downstream),
  records the `federation.dispatch` span, and passes the context into
  the member router (`submit_* (trace=...)`), which records
  `router.dispatch` and ships it over the replica pipe — one trace id
  indexes federation + router + replica spans, merged wall-clock by
  `FederatedTraces`.

Locks: the single `serve.federation` rung (rank 1, utils/locks.py) —
the OUTERMOST rank of all, because a federation control op legitimately
calls into member router machinery (serve.autoscale 2, serve.frontdoor
4, serve.replica 6) — guarding only the member table, pin map, and
rollout bookkeeping. No blocking call (member op, scrape, executor
wait) ever runs under it.

The transport seam: `Member` wraps an in-process `FrontDoorRouter`
handle the way an RPC client wraps a remote host — every federation ->
member call goes through `Member.call()`, which enforces a BOUNDED
timeout and answers typed `MemberUnreachable` (counted per member) on
timeout or partition. `partition()`/`heal()` flip the seam for chaos
batteries: a partitioned member's scrapes die and its control ops are
refused while the member itself keeps serving its own local traffic —
exactly a network partition's shape. A real multi-host deployment
replaces `Member.call`'s in-process invoke with an HTTP/RPC stub; the
federation logic above the seam is transport-blind.

Chaos-gated by `tools/chaos_bench.py --federation_only` (partition
mid-rollout, wave canary failure, member death with pinned sessions,
torn-version sweep) and load-gated by the serve_bench federation leg.
"""

from __future__ import annotations

import threading
import time
from concurrent import futures as cf
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import (Any, Callable, Dict, List, Mapping, Optional,
                    Sequence, Set, Tuple)

from dsin_tpu.serve import metrics as metrics_lib
from dsin_tpu.serve import trace as trace_lib
from dsin_tpu.serve.autoscale import (FleetHealthPolicy,
                                      health_from_snapshot)
from dsin_tpu.serve.batcher import (Future, ServeError,
                                    ServiceOverloaded,
                                    ServiceUnavailable)
from dsin_tpu.serve.quality import wave_canary_verdict
from dsin_tpu.serve.router import (AdmissionController, FleetSwapError,
                                   FrontDoorRouter)
from dsin_tpu.serve.session import SessionExpired
from dsin_tpu.utils import locks as locks_lib


class FederationError(RuntimeError):
    """A federation control op was refused (unknown member, a second
    rollout while one is in flight, a plan that names nobody). The
    federation keeps serving its current state — a refused control op
    is an operator error, never an outage."""


class MemberUnreachable(ServeError):
    """A federation -> member call could not complete: the member is
    partitioned away, or the bounded call timeout expired. Typed as a
    ServeError so dataplane callers shed/reroute it like any other
    serving refusal; carries `member` for the operator."""

    def __init__(self, msg: str, member: Optional[str] = None):
        super().__init__(msg)
        self.member = member


class RolloutAborted(FederationError):
    """A rollout wave failed its gate (swap refusal, canary mismatch
    through the new model's real serve path, soak-window health fire,
    or a member lost mid-wave) and the federation auto-rolled the wave
    back. Carries `digest` (the manifest being promoted), `wave` (the
    0-based failing wave), `reason`, and `per_wave` — {wave_idx:
    {member: outcome-str}} covering every member the rollout touched —
    so the operator sees exactly where the promotion stopped and what
    every member converged to."""

    def __init__(self, msg: str, *, digest: Optional[str] = None,
                 wave: Optional[int] = None, reason: str = "",
                 per_wave: Optional[Dict[int, Dict[str, str]]] = None):
        super().__init__(msg)
        self.digest = digest
        self.wave = wave
        self.reason = reason
        self.per_wave = {w: dict(m) for w, m in (per_wave or {}).items()}


class Member:
    """One host's fleet, as the federation sees it: a name, the
    `FrontDoorRouter` handle (the in-process stand-in for an RPC
    client), an optional `ckpt_root` the checkpoint distribution
    stages manifests into, and the partitionable call seam.

    `call(kind, fn, timeout_s)` is the ONLY way the federation invokes
    member machinery: it refuses immediately when the member is
    partitioned and otherwise runs `fn` on the member's own
    single-thread executor with a bounded wait — a call that outlives
    its timeout answers typed `MemberUnreachable` (the executor thread
    keeps draining, mirroring an RPC whose response is abandoned).
    Every refusal/timeout increments the per-member failure counter on
    the federation registry (the satellite-2 audit: no unbounded
    cross-host call, every failure typed AND counted)."""

    def __init__(self, name: str, router: FrontDoorRouter, *,
                 ckpt_root: Optional[str] = None,
                 control_timeout_s: float = 60.0):
        if not name:
            raise FederationError("a member needs a non-empty name")
        self.name = str(name)
        self.router = router
        self.ckpt_root = ckpt_root
        self.control_timeout_s = float(control_timeout_s)
        self._partitioned = threading.Event()
        # a small pool of call lanes, like an RPC channel pool: a slow
        # control op (a swap's prepare runs minutes) must not starve
        # the concurrent health polls into spurious evictions
        self._pool = ThreadPoolExecutor(
            max_workers=4, thread_name_prefix=f"fed-member-{name}")
        #: set by FederatedRouter.attach — failures count on the
        #: federation's registry so the roll-up carries them
        self.metrics: Optional[metrics_lib.MetricsRegistry] = None

    # -- the partition seam --------------------------------------------------

    def partition(self) -> None:
        """Model a network partition: every federation->member call
        (scrape, health, control op, dataplane handoff) is refused
        typed until `heal()`. The member itself keeps serving its own
        local traffic — the federation lost the HOST, the host did not
        lose its fleet."""
        self._partitioned.set()

    def heal(self) -> None:
        self._partitioned.clear()

    @property
    def partitioned(self) -> bool:
        return self._partitioned.is_set()

    # -- the bounded, typed call surface -------------------------------------

    def _count_failure(self, kind: str) -> None:
        if self.metrics is not None:
            self.metrics.counter(
                f"federation_member_call_failures_{self.name}").inc()
            self.metrics.counter(
                f"federation_member_call_failures_{self.name}_{kind}"
            ).inc()

    def call(self, kind: str, fn: Callable[[], Any],
             timeout_s: Optional[float] = None) -> Any:
        """Invoke one member operation, bounded + typed (see class
        docstring). `kind` labels the failure counter and the error."""
        if self._partitioned.is_set():
            self._count_failure(kind)
            raise MemberUnreachable(
                f"member {self.name!r} is partitioned away "
                f"({kind} refused)", member=self.name)
        budget = (self.control_timeout_s if timeout_s is None
                  else float(timeout_s))
        fut = self._pool.submit(fn)
        try:
            return fut.result(timeout=budget)
        except (cf.TimeoutError, TimeoutError):
            self._count_failure(kind)
            raise MemberUnreachable(
                f"member {self.name!r} did not answer {kind} within "
                f"{budget}s", member=self.name) from None

    def close(self) -> None:
        self._pool.shutdown(wait=False)


@dataclass(frozen=True)
class RolloutPlan:
    """A staged promotion: `waves` are tuples of member names promoted
    together; every wave must pass its canary gate AND its soak window
    before the next wave starts. `soak_s=0` skips the soak (the canary
    gate still holds). `rollback_prior_waves` extends a wave failure's
    auto-rollback to the already-committed waves — blast-radius policy
    is the OPERATOR's call, so both behaviors are first-class."""

    ckpt_dir: str
    waves: Tuple[Tuple[str, ...], ...]
    #: wave canary gate: poll member quality roll-ups until every wave
    #: member's prober has verdicts covering the NEW digest
    canary_timeout_s: float = 120.0
    poll_s: float = 0.05
    #: post-commit soak window per wave (0 = skip)
    soak_s: float = 0.0
    #: member swap/rollback call budgets (prepare loads + warms a model)
    swap_timeout_s: float = 600.0
    rollback_timeout_s: float = 60.0
    rollback_prior_waves: bool = False
    #: stage the manifest into each member's ckpt_root first (members
    #: without one swap straight from `ckpt_dir` — one shared
    #: filesystem, the single-host test shape)
    distribute: bool = True

    def validate(self, known: Sequence[str]) -> None:
        if not self.waves or any(not w for w in self.waves):
            raise FederationError(
                f"a rollout plan needs non-empty waves, got "
                f"{self.waves!r}")
        seen: Set[str] = set()
        for wave in self.waves:
            for name in wave:
                if name not in known:
                    raise FederationError(
                        f"rollout names unknown member {name!r} "
                        f"(members: {sorted(known)})")
                if name in seen:
                    raise FederationError(
                        f"member {name!r} appears in two waves — a "
                        f"member promotes exactly once per rollout")
                seen.add(name)


class FederatedRouter:
    """The router-of-routers (see module docstring). Members are
    handed in started; the federation owns NO member lifecycle — it
    routes, polls, promotes, and rolls back. `drain()` stops only the
    federation's own machinery (each host drains its own fleet)."""

    def __init__(self, members: Sequence[Member], *,
                 admission_limits: Optional[Mapping[str, int]] = None,
                 poll_every_s: float = 0.25, evict_after: int = 2,
                 health_timeout_s: float = 2.0,
                 trace_sample_rate: float = 0.0,
                 trace_capacity: int = 4096,
                 flight_dir: Optional[str] = None):
        if not members:
            raise FederationError("a federation needs at least one "
                                  "member")
        names = [m.name for m in members]
        if len(set(names)) != len(names):
            raise FederationError(f"member names must be unique, got "
                                  f"{names}")
        if evict_after < 1:
            raise FederationError(
                f"evict_after must be >= 1, got {evict_after}")
        self.poll_every_s = float(poll_every_s)
        self.evict_after = int(evict_after)
        self.health_timeout_s = float(health_timeout_s)
        self.metrics = metrics_lib.MetricsRegistry()
        self._members: Dict[str, Member] = {}
        for m in members:
            m.metrics = self.metrics
            self._members[m.name] = m
        # the member class sets must agree — a heterogeneous class map
        # cannot split one budget hierarchically
        class_sets = {tuple(sorted(m.router.admission.limits))
                      for m in members}
        if len(class_sets) != 1:
            raise FederationError(
                f"members disagree on priority classes: "
                f"{sorted(class_sets)}")
        self._class_names = list(members[0].router._class_names)
        #: per-member per-class budgets, captured at attach — the
        #: hierarchical split's denominators (a member's own budget is
        #: already replica-scaled by its router)
        self._member_limits: Dict[str, Dict[str, int]] = {
            m.name: dict(m.router.admission.limits) for m in members}
        self._explicit_limits = (dict(admission_limits)
                                 if admission_limits is not None
                                 else None)
        self._lock = locks_lib.RankedLock("serve.federation")
        self._state: Dict[str, str] = {
            m.name: "live" for m in members}  # guarded-by: self._lock
        self._fails: Dict[str, int] = {
            m.name: 0 for m in members}       # guarded-by: self._lock
        self._digests: Dict[str, Optional[str]] = {
            m.name: None for m in members}    # guarded-by: self._lock
        self._rr: Dict[str, int] = {}         # guarded-by: self._lock
        # sid -> member name: the host-sticky pin table
        self._sessions: Dict[str, str] = {}   # guarded-by: self._lock
        self._rolling = False                 # guarded-by: self._lock
        #: digests a failed/aborted rollout rolled the federation away
        #: from — the partition-healing reconcile set (never shrinks;
        #: a digest aborted once must never be readmitted silently)
        self._aborted: Set[str] = set()       # guarded-by: self._lock
        self.params_digest: Optional[str] = None
        self.admission = self._build_admission()
        self.tracer = trace_lib.Tracer(
            sample_rate=trace_sample_rate, capacity=trace_capacity,
            metrics=self.metrics)
        self.flight = trace_lib.FlightRecorder(
            dump_dir=flight_dir, metrics=self.metrics)
        self.aggregate = FederatedMetrics(self)
        self.traces = FederatedTraces(self)
        self._stop = threading.Event()
        self._poller: Optional[threading.Thread] = None
        self._started = False

    # -- admission (hierarchical split) --------------------------------------

    def _build_admission(self) -> AdmissionController:
        return AdmissionController(self._derive_limits(),
                                   metrics=self.metrics)

    def _derive_limits(self) -> Dict[str, int]:
        """Aggregate per-class budget = sum of LIVE members' own fleet
        budgets (floor 1: AdmissionController refuses a 0 cap — with
        no live member the door sheds on routing, not on the cap)."""
        if self._explicit_limits is not None:
            return dict(self._explicit_limits)
        with self._lock:
            live = [n for n, s in self._state.items() if s == "live"]
        totals = {c: 0 for c in self._class_names}
        for name in live:
            for c, n in self._member_limits[name].items():
                totals[c] += int(n)
        return {c: max(1, n) for c, n in totals.items()}

    def _rescale_admission(self) -> None:
        if self._explicit_limits is None:
            self.admission.set_limits(self._derive_limits())

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "FederatedRouter":
        if self._started:
            return self
        # learn the federation digest from the members (unanimous or
        # UNKNOWN — the poll loop re-learns it like the router does
        # after an all-skipped rollback)
        digests = set()
        for name, member in self._members.items():
            try:
                h = member.call("health", member.router.health,
                                self.health_timeout_s)
            except MemberUnreachable:
                continue
            d = h.get("params_digest")
            with self._lock:
                self._digests[name] = d
            if d is not None:
                digests.add(d)
        if len(digests) == 1:
            self.params_digest = digests.pop()
        self._publish_members()
        self._poller = threading.Thread(target=self._poll_loop,
                                        name="federation-poller",
                                        daemon=True)
        self._started = True
        self._poller.start()
        return self

    def drain(self, timeout_s: float = 30.0) -> None:
        """Stop the federation machinery (poll loop, member call
        lanes, flight flush). Members keep serving — each host owns
        its own fleet's drain."""
        self._stop.set()
        if self._poller is not None:
            self._poller.join(timeout=timeout_s)
        for member in self._members.values():
            member.close()
        with self._lock:
            leftovers = len(self._sessions)
            self._sessions.clear()
        if leftovers:
            self.metrics.counter(
                "federation_sessions_dropped_drain").inc(leftovers)
        self.flight.flush(timeout=5.0)

    def __enter__(self) -> "FederatedRouter":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.drain()

    # -- health / membership -------------------------------------------------

    def members(self) -> List[str]:
        return sorted(self._members)

    def member(self, name: str) -> Member:
        m = self._members.get(name)
        if m is None:
            raise FederationError(
                f"unknown member {name!r} (members: "
                f"{sorted(self._members)})")
        return m

    def _publish_members(self) -> None:
        with self._lock:
            live = sum(1 for s in self._state.values() if s == "live")
        self.metrics.gauge("federation_members_live").set(live)
        self.metrics.gauge("federation_members").set(
            len(self._members))

    def _member_evidence(self, member: Member):
        """One bounded health poll -> (ok, serving digest). Healthy
        means the member ANSWERED and has at least one live replica —
        a host whose fleet is gone is not a routing target even if its
        front door still replies."""
        try:
            h = member.call("health", member.router.health,
                            self.health_timeout_s)
        except MemberUnreachable:
            return False, None
        except Exception:   # noqa: BLE001 — any poll failure is a failure
            return False, None
        return bool(h.get("live", 0) >= 1), h.get("params_digest")

    def _poll_loop(self) -> None:
        """Member eviction/readmission on scrape evidence, one tier
        above the router's replica poll loop — with the partition-
        healing reconcile (module docstring) grafted onto the digest-
        skew refusal."""
        while not self._stop.wait(self.poll_every_s):
            for name, member in list(self._members.items()):
                ok, digest = self._member_evidence(member)
                reconcile_digest: Optional[str] = None
                with self._lock:
                    state = self._state[name]
                    if ok:
                        self._fails[name] = 0
                        self._digests[name] = digest
                        if (self.params_digest is None
                                and digest is not None
                                and state == "live"):
                            # re-learn an UNKNOWN federation digest
                            # from the first live member that answers
                            self.params_digest = digest
                        if state == "evicted":
                            if (digest is not None
                                    and self.params_digest is not None
                                    and digest != self.params_digest):
                                if digest in self._aborted:
                                    # healed partition serving a digest
                                    # a failed rollout rolled away from:
                                    # reconcile OUTSIDE the lock, then
                                    # let the next poll readmit
                                    reconcile_digest = digest
                                else:
                                    self.metrics.counter(
                                        "federation_digest_skew").inc()
                            else:
                                self._state[name] = "live"
                                self.metrics.counter(
                                    "federation_member_readmissions"
                                ).inc()
                    else:
                        self._fails[name] += 1
                        if (self._fails[name] >= self.evict_after
                                and state == "live"):
                            self._state[name] = "evicted"
                            self.metrics.counter(
                                "federation_member_evictions").inc()
                            self.flight.record("member_evicted",
                                               member=name)
                if reconcile_digest is not None:
                    self._reconcile(member, reconcile_digest)
            self._publish_members()
            # membership drives the hierarchical budget: an evicted
            # member's share must stop being admitted at the door
            self._rescale_admission()

    def _reconcile(self, member: Member, sick: str) -> None:
        """Converge a healed member off an aborted digest: ONE
        conditional rollback — a member already off it (its own driver
        won the race, or the swap never landed) refuses typed and
        counts converged. Success or converged-refusal both leave the
        member one healthy poll away from readmission; any other
        failure leaves it evicted with the skew counter telling the
        operator why."""
        try:
            member.call(
                "reconcile_rollback",
                lambda: member.router.rollback(expect_digest=sick))
            self.metrics.counter("federation_reconciles").inc()
            self.flight.record("reconcile", member=member.name,
                               rolled_from=sick)
        except MemberUnreachable:
            return      # partition re-opened: next poll re-evaluates
        except FleetSwapError as e:
            self.metrics.counter(
                "federation_reconcile_failures").inc()
            self.flight.note_error(e)

    def health(self) -> dict:
        with self._lock:
            states = dict(self._state)
            digests = dict(self._digests)
        live = sum(1 for s in states.values() if s == "live")
        status = ("ok" if live and live == len(states)
                  else "degraded" if live else "unhealthy")
        return {"status": status, "live": live, "members": states,
                "member_digests": digests,
                "outstanding": self.admission.outstanding(),
                "params_digest": self.params_digest}

    # -- dataplane -----------------------------------------------------------

    def _pick(self, cls: str) -> Optional[Member]:
        with self._lock:
            live = [self._members[n] for n in sorted(self._members)
                    if self._state[n] == "live"]
            if not live:
                return None
            i = self._rr.get(cls, 0)
            self._rr[cls] = i + 1
            return live[i % len(live)]

    def _attach_span(self, fut: Future, ctx, op: str, cls: str,
                     member_name: str, t0: float) -> None:
        def _resolved(f):
            exc = f.exception(timeout=0)
            self.tracer.span_for(ctx, trace_lib.SPAN_FEDERATION, t0,
                                 time.monotonic(), op=op, cls=cls,
                                 member=member_name)
            if exc is not None and isinstance(exc, (ServeError,
                                                    ValueError)):
                self.tracer.error(ctx, exc)
                self.flight.note_error(
                    exc, trace_id=ctx.trace_id if ctx else None)

        fut.add_done_callback(_resolved)

    def _submit(self, op: str, payload, priority: Optional[str],
                deadline_ms: Optional[float]) -> Future:
        assert self._started, "start() the federation before submitting"
        cls = priority or self._class_names[0]
        try:
            self.admission.admit(cls)   # the federation's own door
        except ServiceOverloaded:
            self.flight.record("shed", reason="admission", cls=cls)
            raise
        ctx = self.tracer.mint(origin="federation")
        t0 = time.monotonic()
        last: Optional[BaseException] = None
        for _ in range(len(self._members)):
            member = self._pick(cls)
            if member is None:
                break
            try:
                if member.partitioned:
                    member._count_failure(op)
                    raise MemberUnreachable(
                        f"member {member.name!r} is partitioned away",
                        member=member.name)
                # the handoff itself is non-blocking member-side (the
                # router sheds or accepts at ITS door), so it runs
                # inline — the bounded-call lane is for ops that wait
                submit = (member.router.submit_encode if op == "encode"
                          else member.router.submit_decode)
                fut = submit(payload, deadline_ms, priority=cls,
                             trace=ctx)
            except (MemberUnreachable, ServiceUnavailable,
                    ServiceOverloaded) as e:
                # a member-level refusal is not a federation failure
                # while another member can take the request
                last = e
                continue
            self.admission.attach(cls, fut)
            self._attach_span(fut, ctx, op, cls, member.name, t0)
            self.metrics.counter(f"federation_routed_{cls}").inc()
            self.metrics.counter(
                f"federation_routed_m_{member.name}").inc()
            return fut
        self.admission.release(cls)
        exc = ServiceUnavailable(
            f"no live federation member accepted {op!r} "
            f"({len(self._members)} member(s); last refusal: "
            f"{last!r}) — retry shortly")
        self.flight.note_error(exc)
        raise exc

    # contract: request-path — every reachable raise must be a typed error
    def submit_encode(self, img, deadline_ms: Optional[float] = None,
                      priority: Optional[str] = None) -> Future:
        return self._submit("encode", img, priority, deadline_ms)

    # contract: request-path — every reachable raise must be a typed error
    def submit_decode(self, blob: bytes,
                      deadline_ms: Optional[float] = None,
                      priority: Optional[str] = None) -> Future:
        return self._submit("decode", blob, priority, deadline_ms)

    def encode(self, img, deadline_ms: Optional[float] = None,
               timeout: Optional[float] = 120.0,
               priority: Optional[str] = None):
        return self.submit_encode(img, deadline_ms,
                                  priority=priority).result(timeout)

    def decode(self, blob: bytes, deadline_ms: Optional[float] = None,
               timeout: Optional[float] = 120.0,
               priority: Optional[str] = None):
        return self.submit_decode(blob, deadline_ms,
                                  priority=priority).result(timeout)

    # -- host-sticky sessions ------------------------------------------------

    def open_session(self, side_img,
                     timeout: Optional[float] = 120.0) -> str:
        """Open on ONE member (round-robin over live members) and pin
        the sid there. Sids are globally unique, so the pin table
        needs no member qualifier."""
        assert self._started, "start() the federation first"
        budget = 120.0 if timeout is None else float(timeout)
        for _ in range(len(self._members)):
            member = self._pick("_session")
            if member is None:
                break
            try:
                sid = member.call(
                    "session_open",
                    lambda m=member: m.router.open_session(
                        side_img, timeout), budget + 5.0)
            except (MemberUnreachable, ServiceUnavailable):
                continue
            with self._lock:
                self._sessions[sid] = member.name
            self.metrics.counter("federation_sessions_opened").inc()
            self._publish_pins()
            return sid
        raise ServiceUnavailable(
            f"no live federation member to open a session on "
            f"({len(self._members)} member(s)) — retry shortly")

    def close_session(self, session_id: str,
                      timeout: Optional[float] = 30.0) -> bool:
        assert self._started, "start() the federation first"
        with self._lock:
            name = self._sessions.pop(session_id, None)
        self._publish_pins()
        if name is None:
            return False
        member = self._members[name]
        try:
            return bool(member.call(
                "session_close",
                lambda: member.router.close_session(session_id,
                                                    timeout),
                (30.0 if timeout is None else timeout) + 5.0))
        except (MemberUnreachable, ServiceUnavailable, ServeError):
            return False    # the pin is dropped either way

    # contract: request-path — every reachable raise must be a typed error
    def submit_decode_si(self, blob: bytes, session_id: str,
                         deadline_ms: Optional[float] = None,
                         priority: Optional[str] = None) -> Future:
        """SI decode against a host-sticky pin. An unknown pin or a
        pinned member that is not currently live answers typed
        `SessionExpired` — the prep exists in one process on one host,
        so 're-open the session' is the only recovery (mirrors the
        router's replica-pin contract exactly, one tier up)."""
        assert self._started, "start() the federation first"
        with self._lock:
            name = self._sessions.get(session_id)
            state = None if name is None else self._state.get(name)
        if name is None or state != "live":
            raise SessionExpired(
                f"session {session_id!r} is not pinned to a live "
                f"federation member ("
                f"{'its member is ' + str(state) if name else 'unknown sid'}"
                f") — re-open it")
        cls = priority or self._class_names[0]
        try:
            self.admission.admit(cls)
        except ServiceOverloaded:
            self.flight.record("shed", reason="admission", cls=cls)
            raise
        ctx = self.tracer.mint(origin="federation")
        t0 = time.monotonic()
        member = self._members[name]
        try:
            if member.partitioned:
                member._count_failure("decode_si")
                raise MemberUnreachable(
                    f"member {name!r} is partitioned away",
                    member=name)
            fut = member.router.submit_decode_si(
                blob, session_id, deadline_ms, priority=cls, trace=ctx)
        except (MemberUnreachable, ServiceUnavailable,
                SessionExpired) as e:
            self.admission.release(cls)
            exc = (e if isinstance(e, SessionExpired) else
                   SessionExpired(
                       f"session {session_id!r}'s member {name!r} is "
                       f"unreachable — its prep lives there; re-open "
                       f"the session ({e})"))
            self.flight.note_error(exc)
            raise exc from e
        self.admission.attach(cls, fut)
        self._attach_span(fut, ctx, "decode_si", cls, name, t0)
        self.metrics.counter(f"federation_routed_{cls}").inc()
        return fut

    def decode_si(self, blob: bytes, session_id: str,
                  deadline_ms: Optional[float] = None,
                  timeout: Optional[float] = 120.0,
                  priority: Optional[str] = None):
        return self.submit_decode_si(blob, session_id, deadline_ms,
                                     priority=priority).result(timeout)

    def _publish_pins(self) -> None:
        with self._lock:
            n = len(self._sessions)
        self.metrics.gauge("federation_sessions_pinned").set(n)

    def _drop_member_pins(self, name: str, reason: str) -> None:
        with self._lock:
            stale = [sid for sid, m in self._sessions.items()
                     if m == name]
            for sid in stale:
                del self._sessions[sid]
        if stale:
            self.metrics.counter(
                f"federation_sessions_dropped_{reason}").inc(len(stale))
        self._publish_pins()

    # -- rollout waves -------------------------------------------------------

    def rollout(self, plan: RolloutPlan,
                health_policy: Optional[Callable[
                    [], FleetHealthPolicy]] = None) -> dict:
        """Promote `plan.ckpt_dir` wave by wave (module docstring).
        Returns {"digest", "waves": [[names...]...], "per_member":
        {name: "committed"}} on full promotion; raises typed
        `RolloutAborted` (after auto-rolling the failing wave — and
        optionally the prior waves — back) on any wave-gate failure.
        `health_policy` builds one fresh soak-window policy per member
        per wave (default: fire fast — 2 consecutive sick checks, no
        cooldown: a soak window exists to catch, not to damp)."""
        assert self._started, "start() the federation before a rollout"
        plan.validate(list(self._members))
        with self._lock:
            if self._rolling:
                raise FederationError(
                    "a rollout is already in flight — one at a time")
            self._rolling = True
        make_policy = health_policy or (
            lambda: FleetHealthPolicy(hysteresis_checks=2,
                                      cooldown_s=0.0))
        try:
            return self._rollout_locked_out(plan, make_policy)
        finally:
            with self._lock:
                self._rolling = False

    def _rollout_locked_out(self, plan: RolloutPlan,
                            make_policy) -> dict:
        per_wave: Dict[int, Dict[str, str]] = {}
        committed: List[Tuple[int, Tuple[str, ...]]] = []
        digest: Optional[str] = None
        self.metrics.counter("federation_rollouts").inc()
        for w, wave in enumerate(plan.waves):
            per_wave[w] = {}
            # strict: every wave member must be LIVE at its wave start
            # (promoting onto an evicted/partitioned member would tear
            # the wave's version the moment it heals)
            with self._lock:
                not_live = [n for n in wave
                            if self._state.get(n) != "live"]
            if not_live:
                for n in wave:
                    per_wave[w][n] = ("not live at wave start"
                                      if n in not_live else "untouched")
                self._abort_rollout(plan, per_wave, committed, w,
                                    digest, f"member(s) {not_live} "
                                    f"not live at wave start")
            swapped: List[str] = []
            failed_reason: Optional[str] = None
            for name in wave:
                member = self._members[name]
                try:
                    local_dir = self._distribute(plan, member)
                    res = member.call(
                        "swap",
                        lambda m=member, d=local_dir:
                        m.router.swap_model(
                            d, prepare_timeout_s=plan.swap_timeout_s),
                        plan.swap_timeout_s + 30.0)
                except Exception as e:  # noqa: BLE001 — every member-op failure fails the wave typed
                    per_wave[w][name] = f"swap failed: {e}"
                    failed_reason = (f"wave {w} swap failed on "
                                     f"{name!r}: {e}")
                    break
                if digest is None:
                    digest = res["digest"]
                elif res["digest"] != digest:
                    per_wave[w][name] = (
                        f"swap committed digest {res['digest']!r} != "
                        f"rollout digest {digest!r}")
                    swapped.append(name)
                    failed_reason = (f"wave {w} digest disagreement "
                                     f"on {name!r}")
                    break
                per_wave[w][name] = "committed"
                swapped.append(name)
                # a committed member invalidated its session stores
                self._drop_member_pins(name, "rollout")
            if failed_reason is None:
                failed_reason = self._wave_gates(
                    plan, w, wave, digest, make_policy, per_wave)
            if failed_reason is not None:
                self._rollback_wave(plan, w, swapped, digest, per_wave)
                self._abort_rollout(plan, per_wave, committed, w,
                                    digest, failed_reason)
            committed.append((w, wave))
            self.metrics.counter("federation_rollout_waves").inc()
            self.flight.record("rollout_wave", wave=w,
                               members=list(wave), digest=digest)
        self.params_digest = digest
        self.metrics.counter("federation_rollout_promotions").inc()
        return {"digest": digest,
                "waves": [list(wave) for wave in plan.waves],
                "per_member": {n: "committed"
                               for wave in plan.waves for n in wave}}

    def _distribute(self, plan: RolloutPlan, member: Member) -> str:
        """Stage the manifest into the member's own checkpoint root
        (CRC-verified both sides, rotate+rename) and return the dir
        the member swaps from."""
        if not plan.distribute or member.ckpt_root is None:
            return plan.ckpt_dir
        from dsin_tpu.train.checkpoint import replicate_checkpoint

        def _stage():
            replicate_checkpoint(plan.ckpt_dir, member.ckpt_root)
            return member.ckpt_root

        return member.call("distribute", _stage, plan.swap_timeout_s)

    def _member_quality(self, member: Member) -> Optional[dict]:
        """One bounded scrape -> the member's aggregated snapshot, or
        None (unreachable — the gate decides what that means)."""
        try:
            return member.call("scrape",
                               member.router.aggregate.snapshot,
                               self.health_timeout_s
                               + member.router.health_timeout_s)
        except Exception:  # noqa: BLE001 — a dead scrape is data
            return None

    def _wave_gates(self, plan: RolloutPlan, w: int,
                    wave: Tuple[str, ...], digest: Optional[str],
                    make_policy, per_wave) -> Optional[str]:
        """Canary gate + soak window for one committed wave; returns
        the failure reason or None (wave passes)."""
        # -- canary gate: the PR 12 prober must probe the NEW digest
        # through each wave member's real serve path
        deadline = time.monotonic() + plan.canary_timeout_s
        pending = set(wave)
        while pending:
            for name in sorted(pending):
                snap = self._member_quality(self._members[name])
                if snap is None:
                    continue    # unreachable: the deadline judges it
                verdict = wave_canary_verdict(
                    snap.get("info", {}).get("quality"), digest)
                if verdict is False:
                    per_wave[w][name] = (f"canary FAILED against "
                                         f"{digest!r}")
                    return (f"wave {w} canary gate: member {name!r} "
                            f"canary failed against {digest!r}")
                if verdict is True:
                    pending.discard(name)
            if not pending:
                break
            if time.monotonic() > deadline:
                for name in sorted(pending):
                    per_wave[w][name] = "canary verdict never covered " \
                                        "the new digest"
                return (f"wave {w} canary gate timed out after "
                        f"{plan.canary_timeout_s}s waiting on "
                        f"{sorted(pending)}")
            time.sleep(plan.poll_s)
        # -- soak window: PR 14 fleet-health evidence per member
        if plan.soak_s <= 0:
            return None
        policies = {name: make_policy() for name in wave}
        soak_end = time.monotonic() + plan.soak_s
        while time.monotonic() < soak_end:
            for name in wave:
                snap = self._member_quality(self._members[name])
                if snap is None:
                    continue    # partition mid-soak: the poll loop
                    # evicts it; the NEXT wave's liveness check (or
                    # the operator) owns that — a silent member is
                    # not health EVIDENCE against the model
                reason = policies[name].observe(
                    time.monotonic(), health_from_snapshot(snap))
                if reason is not None:
                    per_wave[w][name] = (f"soak health fired "
                                         f"({reason})")
                    return (f"wave {w} soak window: member {name!r} "
                            f"fleet-health fired ({reason})")
            time.sleep(plan.poll_s)
        return None

    def _rollback_wave(self, plan: RolloutPlan, w: int,
                       swapped: List[str], digest: Optional[str],
                       per_wave) -> None:
        """Auto-rollback one failed wave's committed members,
        CONDITIONALLY (never fight a member-local driver)."""
        if digest is not None:
            with self._lock:
                self._aborted.add(digest)
        for name in swapped:
            per_wave[w][name] = self._rollback_member(
                self._members[name], digest, plan.rollback_timeout_s)
        self.metrics.counter("federation_rollout_wave_rollbacks").inc()

    def _rollback_member(self, member: Member,
                         expect_digest: Optional[str],
                         timeout_s: float) -> str:
        """One member's conditional rollback -> outcome string. An
        unreachable member converges LATER through the healing
        reconcile (the aborted-digest set); any other failure evicts
        the member so the skew machinery re-checks it before it can
        take traffic again."""
        try:
            res = member.call(
                "rollback",
                lambda: member.router.rollback(
                    expect_digest=expect_digest), timeout_s)
        except MemberUnreachable:
            return ("unreachable — reconciles through the aborted-"
                    "digest set on heal")
        except FleetSwapError as e:
            with self._lock:
                if self._state.get(member.name) == "live":
                    self._state[member.name] = "evicted"
                    self.metrics.counter(
                        "federation_member_evictions").inc()
            self.flight.note_error(e)
            return f"rollback failed (member evicted): {e}"
        except Exception as e:  # noqa: BLE001 — recorded, member evicted below
            with self._lock:
                if self._state.get(member.name) == "live":
                    self._state[member.name] = "evicted"
            self.flight.note_error(e)
            return f"rollback failed (member evicted): {e}"
        self._drop_member_pins(member.name, "rollback")
        if res.get("skipped") and not res.get("replicas"):
            return "already converged (conditional rollback skipped)"
        return f"rolled back to {res.get('digest')!r}"

    def _abort_rollout(self, plan: RolloutPlan, per_wave, committed,
                       wave_idx: int, digest: Optional[str],
                       reason: str) -> None:
        """Finish a failed rollout: optionally roll prior committed
        waves back, then raise typed. The promoted-then-aborted digest
        always enters the reconcile set FIRST — a partitioned member
        that committed it before the abort must converge on heal even
        when the failing wave itself had nothing to roll back."""
        if digest is not None:
            with self._lock:
                self._aborted.add(digest)
        if plan.rollback_prior_waves:
            for w, wave in reversed(committed):
                for name in wave:
                    per_wave.setdefault(w, {})[name] = \
                        self._rollback_member(
                            self._members[name], digest,
                            plan.rollback_timeout_s)
        elif committed:
            for w, wave in committed:
                for name in wave:
                    per_wave.setdefault(w, {})[name] = \
                        "committed (prior wave kept by plan)"
        self.metrics.counter("federation_rollout_aborts").inc()
        exc = RolloutAborted(
            f"rollout aborted at wave {wave_idx}: {reason} — the wave "
            f"was rolled back conditionally"
            + (", prior waves too" if plan.rollback_prior_waves
               and committed else
               f", {len(committed)} prior wave(s) kept"),
            digest=digest, wave=wave_idx, reason=reason,
            per_wave=per_wave)
        self.flight.note_error(exc)
        self.flight.record("rollout_abort", wave=wave_idx,
                           reason=reason, digest=digest)
        raise exc

    # -- federation-wide conditional rollback --------------------------------

    def rollback(self, expect_digest: Optional[str] = None,
                 timeout_s: float = 60.0) -> dict:
        """Roll EVERY live member back (the federation-health driver's
        action, and an operator surface). Conditional per member when
        `expect_digest` is given — a member already off the sick
        digest counts converged. Returns {"digest", "rolled",
        "skipped", "failed": {name: outcome}}."""
        assert self._started, "start() the federation first"
        if expect_digest is not None:
            with self._lock:
                self._aborted.add(expect_digest)
        with self._lock:
            live = [n for n, s in self._state.items() if s == "live"]
        rolled, skipped, failed = [], [], {}
        for name in sorted(live):
            outcome = self._rollback_member(
                self._members[name], expect_digest, timeout_s)
            if outcome.startswith("rolled back"):
                rolled.append(name)
            elif outcome.startswith("already converged"):
                skipped.append(name)
            else:
                failed[name] = outcome
        self.metrics.counter("federation_rollbacks").inc()
        # re-learn the federation digest from the survivors
        digests = set()
        for name in rolled + skipped:
            with self._lock:
                d = self._digests.get(name)
            if d is not None and d != expect_digest:
                digests.add(d)
        self.params_digest = (digests.pop() if len(digests) == 1
                              else None)
        return {"digest": self.params_digest, "rolled": rolled,
                "skipped": skipped, "failed": failed}


# -- federation metrics roll-up (ISSUE 18) ------------------------------------

class FederatedMetrics:
    """ONE federation-wide metrics view: the federation's own registry
    merged with a bounded scrape of every member's `AggregatedMetrics`
    roll-up — the same merge rules (shared helpers, serve/metrics.py)
    and the same staleness veto (seq equality + capture age on the
    member snapshot's own top-level `seq`/`captured_at`) the router
    applies to replica scrapes, one tier up. Duck-types the
    `MetricsRegistry` surface (`snapshot()`/`render_text()`)."""

    #: capture-timestamp slack before a member scrape counts as stale
    stale_after_s = 5.0

    def __init__(self, federation: FederatedRouter):
        self._fed = federation
        self._seq_lock = locks_lib.RankedLock("metrics.registry")
        self._last_seq: Dict[str, int] = {}   # guarded-by: self._seq_lock

    def _is_stale(self, name: str, snap: dict, now: float) -> bool:
        """Same verdict as AggregatedMetrics._is_stale: only POSITIVE
        evidence flags a member; the seq test is EQUALITY (a frozen/
        cached response replays the identical seq; a restart going
        backwards is fresh numbers)."""
        seq = snap.get("seq")
        captured = snap.get("captured_at")
        stale = False
        if seq is not None:
            with self._seq_lock:
                prev = self._last_seq.get(name)
                if prev is not None and seq == prev:
                    stale = True
                else:
                    self._last_seq[name] = seq
        if captured is not None and now - captured > self.stale_after_s:
            stale = True
        return stale

    def snapshot(self) -> dict:
        fed = self._fed
        own = fed.metrics.snapshot()
        counters = dict(own["counters"])
        gauges = dict(own["gauges"])
        accumulators = dict(own["accumulators"])
        hist = metrics_lib.hist_partials(own["histograms"])
        names = sorted(fed._members)

        def _safe_scrape(name):
            return fed._member_quality(fed._members[name])

        # concurrent fan-out: N partitioned members must cost ~one
        # bounded timeout total, not N in series
        with ThreadPoolExecutor(max_workers=max(1, len(names))) as pool:
            snaps = list(pool.map(_safe_scrape, names))
        now = time.time()
        with fed._lock:
            member_states = dict(fed._state)
            member_digests = dict(fed._digests)
        per_member: Dict[str, dict] = {}
        unreachable: List[str] = []
        stale: List[str] = []
        member_errors: Dict[str, dict] = {}
        canary: Dict[str, Any] = {}
        canary_failing: List[str] = []
        for name, snap in zip(names, snaps):
            if snap is None:
                unreachable.append(name)
                continue
            if self._is_stale(name, snap, now):
                stale.append(name)
                continue
            metrics_lib.merge_numeric_sections(
                counters, gauges, accumulators, hist, snap)
            info = snap.get("info", {})
            per_member[name] = info
            q = info.get("quality", {})
            ok = q.get("fleet_canary_ok")
            canary[name] = {
                "fleet_canary_ok": ok,
                "replicas_canary_failing":
                    q.get("replicas_canary_failing", []),
            }
            if ok is False:
                canary_failing.append(name)
            # member-level typed-error window evidence: the federation
            # health driver needs the SKEW across MEMBERS, so each
            # member's per-replica counters sum into one member window
            errs = q.get("replica_errors", {})
            member_errors[name] = {
                "typed_errors": sum(e.get("typed_errors", 0)
                                    for e in errs.values()),
                "resolved": sum(e.get("resolved", 0)
                                for e in errs.values()),
            }
        reported = [n for n in canary
                    if canary[n]["fleet_canary_ok"] is not None]
        return {
            "info": {
                "federation": own["info"],
                "member_digests": member_digests,
                "member_states": member_states,
                "per_member": per_member,
                "members_scraped": len(per_member),
                "members_unreachable": unreachable,
                "members_stale": stale,
                "quality": {
                    "canary": canary,
                    "members_canary_failing": sorted(canary_failing),
                    "federation_canary_ok": ((not canary_failing)
                                             if reported else None),
                    "member_errors": member_errors,
                },
            },
            "counters": dict(sorted(counters.items())),
            "gauges": dict(sorted(gauges.items())),
            "accumulators": dict(sorted(accumulators.items())),
            "histograms": metrics_lib.fold_hist_partials(hist),
            "locks": own["locks"],
            "lock_order_inversions": own["lock_order_inversions"],
            "seq": own.get("seq"),
            "captured_at": own.get("captured_at"),
        }

    def render_text(self) -> str:
        return metrics_lib.render_snapshot_text(self.snapshot())


# -- federation trace stitching (ISSUE 18) ------------------------------------

class FederatedTraces:
    """ONE federation-wide trace view: the federation's own span ring
    merged with every member's (already replica-merged) trace view —
    one trace id follows a request federation -> router -> replica on
    one wall-clock timeline. Mirrors `AggregatedTraces`' semantics:
    fresh fan-out per call, unreachable members reported, bounded +
    concurrent so dead members cost ~one timeout total."""

    def __init__(self, federation: FederatedRouter):
        self._fed = federation

    def snapshot(self, trace_id: Optional[str] = None) -> dict:
        fed = self._fed
        own = fed.tracer.snapshot(trace_id=trace_id)
        names = sorted(fed._members)

        def _safe(name):
            member = fed._members[name]
            try:
                return member.call(
                    "trace_scrape",
                    lambda: member.router.traces.snapshot(trace_id),
                    fed.health_timeout_s
                    + member.router.health_timeout_s)
            except Exception:  # noqa: BLE001 — a dead scrape is data
                return None

        parts = [own]
        unreachable: List[str] = []
        scraped = 0
        with ThreadPoolExecutor(max_workers=max(1, len(names))) as pool:
            snaps = list(pool.map(_safe, names))
        for name, snap in zip(names, snaps):
            if snap is None:
                unreachable.append(name)
                continue
            scraped += 1
            parts.append(snap)
        return {
            "spans": trace_lib.merge_trace_snapshots(parts),
            "federation_spans": len(own["spans"]),
            "members_scraped": scraped,
            "members_unreachable": unreachable,
            "flight": fed.flight.meta(),
        }

    def http_snapshot(self, params: Mapping[str, str]) -> object:
        snap = self.snapshot(trace_id=params.get("id"))
        if params.get("format") == "chrome":
            return trace_lib.chrome_trace(snap["spans"])
        return snap
