"""Device-resident side-information session cache (ISSUE 10).

The paper's product is decoder side information, and the siFinder search
has a large request-INVARIANT half: everything derived from the side
image y alone — the AE reconstruction ŷ, its H1H2H3/LAB color
transform, the window statistics behind the Pearson denominator, the
Gaussian prior factors, and (on TPU) the padded side tensor the fused
Pallas kernel slices. Serving the SI path naively re-pays all of that
on EVERY request of a stereo/burst session that reuses the same y. A
session registers y ONCE; the service computes the whole y-half into an
immutable `ops.sifinder.SidePrep` (serve/service.py owns the jitted
build) and this store keeps it device-resident across requests —
amortized prep, the compute-reuse win that makes learned codecs
deployable (PAPERS.md arXiv 2207.14524 / 1912.08771).

The store is a bounded LRU with byte accounting and an optional idle
TTL:

* **LRU + capacity**: at most `max_sessions` entries and `max_bytes` of
  per-session device arrays; inserting past either bound evicts the
  least-recently-USED session (a `get` refreshes recency). A single
  prep larger than `max_bytes` is refused typed (`SessionOverCapacity`)
  — it could only ever be cached by evicting everyone else.
* **TTL**: with `ttl_s`, a session idle longer than that is expired —
  lazily at access and swept at every insert, so an abandoned session
  cannot pin device memory forever.
* **Typed misses**: every way a session can be gone — never opened,
  LRU-evicted, TTL-expired, invalidated by a model hot swap, replica
  death (serve/router.py) — answers `SessionExpired`; the client's
  recovery is always the same: re-open the session.

Sessions are MODEL-VERSIONED: a SidePrep embeds ŷ, which depends on the
serving params, so `SessionEntry.digest` records the model digest the
prep was built against and the service invalidates the store on every
hot-swap commit/rollback (serve/service.py) — a stale prep must never
silently search against new-model reconstructions.

All store state lives under the ranked `serve.session` lock (rank 16,
utils/locks.py — above `serve.placement`, below `serve.model`; metric
updates from under it reach only the metrics leaf rungs). The
`serve.session` fault site fires on every lookup, so chaos_bench can
inject typed faults exactly where a corrupted/raced session slot would
surface (tools/chaos_bench.py `sessions` battery).
"""

from __future__ import annotations

import secrets
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from dsin_tpu.serve.batcher import ServeError
from dsin_tpu.utils import faults
from dsin_tpu.utils import locks as locks_lib


class SessionError(ServeError):
    """Base for the session-cache failure modes."""


class SessionExpired(SessionError):
    """The session is gone — never opened, LRU/TTL-evicted, invalidated
    by a model swap, or stranded on a dead replica. Re-open it (register
    the side image again); nothing else recovers a lost prep."""


class SessionOverCapacity(SessionError):
    """One side image's prep alone exceeds the store's byte budget —
    caching it would require evicting every other session. Raise the
    budget or serve that geometry per-request."""


@dataclass(frozen=True)
class SessionEntry:
    """One registered side image: the immutable prep plus the facts the
    dataplane checks before using it."""
    sid: str
    prep: Any                 # ops.sifinder.SidePrep (device arrays)
    bucket: Tuple[int, int]   # geometry the prep was built at — requests
    #                           must route to the SAME bucket
    nbytes: int               # per-session device bytes (byte accounting)
    digest: Optional[str]     # model digest the prep was built against


class _Slot:
    """Mutable store-side wrapper: entry + recency stamp."""

    __slots__ = ("entry", "last_used")

    def __init__(self, entry: SessionEntry, now: float):
        self.entry = entry
        self.last_used = now


class SessionStore:
    """Bounded LRU + TTL + byte-accounted session cache (thread-safe)."""

    def __init__(self, max_sessions: int, max_bytes: int,
                 ttl_s: Optional[float] = None, metrics=None,
                 clock=time.monotonic, flight=None, on_evict=None):
        if max_sessions < 1:
            raise ValueError(f"max_sessions must be >= 1, "
                             f"got {max_sessions}")
        if max_bytes < 1:
            raise ValueError(f"max_bytes must be >= 1, got {max_bytes}")
        if ttl_s is not None and ttl_s <= 0:
            raise ValueError(f"ttl_s must be > 0 (or None), got {ttl_s}")
        self.max_sessions = int(max_sessions)
        self.max_bytes = int(max_bytes)
        self.ttl_s = ttl_s
        self.metrics = metrics
        #: optional serve/trace.py FlightRecorder: evictions are exactly
        #: the "why did my session vanish" events an incident timeline
        #: needs (its ring lock ranks above serve.session, so recording
        #: from under this store's lock is legal)
        self.flight = flight
        #: optional `fn(sid, reason)` fired on EVERY way a session
        #: leaves the store (evict/TTL/swap/clear) — the quality
        #: monitor (serve/quality.py) drops its per-session SI-match
        #: stats here so a dead session cannot pin tracker memory or a
        #: stale alarm. Runs under this store's lock: the hook must
        #: touch only ranks above serve.session (serve.quality, 19,
        #: does).
        self.on_evict = on_evict
        self._clock = clock
        self._lock = locks_lib.RankedLock("serve.session")
        # insertion/recency order: first = least recently used
        self._slots: "OrderedDict[str, _Slot]" = OrderedDict()  # guarded-by: self._lock
        self._bytes = 0            # guarded-by: self._lock
        self._counter = 0          # guarded-by: self._lock

    # -- metrics (leaf rungs; legal from under serve.session) ---------------

    def _publish_locked(self) -> None:
        if self.metrics is None:
            return
        self.metrics.gauge("serve_sessions_live").set(len(self._slots))
        self.metrics.gauge("serve_session_bytes").set(self._bytes)

    def _note_eviction(self, reason: str, n: int = 1) -> None:
        if self.metrics is None or n == 0:
            return
        self.metrics.counter("serve_session_evictions").inc(n)
        self.metrics.counter(f"serve_session_evictions_{reason}").inc(n)

    # -- API ----------------------------------------------------------------

    def next_sid(self) -> str:
        """Generated ids carry a random suffix so they are unique ACROSS
        stores: the session-pinning router (serve/router.py) keys its
        fleet-wide pin table by sid, and two replicas minting the same
        counter value would silently overwrite each other's pins."""
        with self._lock:
            self._counter += 1
            return f"sess-{self._counter:06d}-{secrets.token_hex(4)}"

    def _evict_locked(self, sid: str, reason: str) -> bool:
        slot = self._slots.pop(sid, None)
        if slot is None:
            return False
        self._bytes -= slot.entry.nbytes
        self._note_eviction(reason)
        if self.flight is not None:
            self.flight.record("session_evict", sid=sid, reason=reason,
                               bucket=list(slot.entry.bucket))
        if self.on_evict is not None:
            self.on_evict(sid, reason)
        return True

    def _sweep_ttl_locked(self, now: float) -> None:
        if self.ttl_s is None:
            return
        dead = [sid for sid, slot in self._slots.items()
                if now - slot.last_used > self.ttl_s]
        for sid in dead:
            self._evict_locked(sid, "ttl")

    def put(self, entry: SessionEntry) -> List[str]:
        """Insert (or replace) a session; returns the sids evicted to
        make room. Eviction order: TTL-dead first, then LRU until both
        the session-count and byte bounds hold."""
        if entry.nbytes > self.max_bytes:
            raise SessionOverCapacity(
                f"session {entry.sid!r} prep is {entry.nbytes} bytes — "
                f"larger than the whole store budget ({self.max_bytes}); "
                f"raise session_max_bytes or serve this geometry "
                f"per-request")
        now = self._clock()
        with self._lock:
            before = set(self._slots)
            self._sweep_ttl_locked(now)
            # replacing an existing sid is not an "eviction" — the caller
            # re-registered the same session
            if entry.sid in self._slots:
                old = self._slots.pop(entry.sid)
                self._bytes -= old.entry.nbytes
            self._slots[entry.sid] = _Slot(entry, now)
            self._bytes += entry.nbytes
            while len(self._slots) > self.max_sessions:
                lru = next(iter(self._slots))
                self._evict_locked(lru, "lru")
            while self._bytes > self.max_bytes:
                lru = next(iter(self._slots))
                self._evict_locked(lru, "bytes")
            self._publish_locked()
            return sorted((before - set(self._slots)) - {entry.sid})

    def get(self, sid: str) -> SessionEntry:
        """Look a session up (refreshing its recency) or raise typed
        `SessionExpired`. The `serve.session` fault site fires here —
        outside the lock, so an injected delay cannot serialize the
        store."""
        faults.inject("serve.session")
        now = self._clock()
        with self._lock:
            slot = self._slots.get(sid)
            if slot is None:
                self._publish_locked()
                raise SessionExpired(
                    f"session {sid!r} is not registered (never opened, "
                    f"evicted, or invalidated) — re-open it")
            if self.ttl_s is not None and now - slot.last_used > self.ttl_s:
                self._evict_locked(sid, "ttl")
                self._publish_locked()
                raise SessionExpired(
                    f"session {sid!r} idle past its {self.ttl_s}s TTL — "
                    f"re-open it")
            slot.last_used = now
            self._slots.move_to_end(sid)
            return slot.entry

    def evict(self, sid: str, reason: str = "closed") -> bool:
        with self._lock:
            out = self._evict_locked(sid, reason)
            self._publish_locked()
            return out

    def clear(self, reason: str) -> int:
        """Evict everything (model hot swap / rollback / drain). Returns
        the number of sessions dropped."""
        with self._lock:
            dropped = list(self._slots)
            n = len(dropped)
            self._slots.clear()
            self._bytes = 0
            self._note_eviction(reason, n)
            if self.flight is not None and n:
                self.flight.record("sessions_cleared", reason=reason,
                                   count=n)
            if self.on_evict is not None:
                for sid in dropped:
                    self.on_evict(sid, reason)
            self._publish_locked()
            return n

    @property
    def live(self) -> int:
        with self._lock:
            return len(self._slots)

    @property
    def bytes_used(self) -> int:
        with self._lock:
            return self._bytes

    def snapshot(self) -> Dict[str, dict]:
        """{sid: {bucket, nbytes, idle_s}} for /healthz and tests."""
        now = self._clock()
        with self._lock:
            return {sid: {"bucket": list(slot.entry.bucket),
                          "nbytes": slot.entry.nbytes,
                          "idle_s": round(now - slot.last_used, 3)}
                    for sid, slot in self._slots.items()}
