"""Live model hot-swap state machine: versioned bundles, instant rollback.

A running CompressionService (serve/service.py) must adopt a retrained
checkpoint without dropping a request and roll back in milliseconds when
the new model misbehaves (ROADMAP "Live operations"; the deployment-
mechanics argument of PAPERS.md arXiv 2207.14524). The hard part is not
the pointer swap — it is that "the model" is FOUR coupled things the
dataplane reads at different moments: per-device replicated params for
the jitted stages, the host-side params the codec's context model codes
entropy with, the per-thread codec clones of the entropy pool, and (for
the process entropy backend) a pool of worker-resident codecs in child
processes. A swap that changes them non-atomically produces TORN
batches: device stage on model A, entropy stage on model B, emitting a
stream no model can decode.

This module makes the whole set one value:

* **ModelBundle** — an immutable snapshot of one model version: host
  state, codec, per-device replicas, digest, and (process backend) its
  OWN worker pool built from its own CodecSpec. A worker captures ONE
  bundle reference at batch start and threads it through every stage,
  so a batch is coherent by construction no matter when the swap lands;
  in-flight batches simply finish on the bundle they started with.

* **SwapCoordinator** — the three-slot state machine under the ranked
  `serve.model` lock (rank 17): `current` (serving), `staged` (prepared
  by a background load+warm, waiting for commit), `prev` (the last
  served bundle, kept WARM for instant rollback). Transitions are
  pointer swaps — O(1) under the lock, nothing blocking — and every
  displaced bundle is handed back to the caller for retirement OUTSIDE
  the lock (a process pool shutdown must never run under a ranked
  lock). Counters/gauge: `serve_swaps`, `serve_rollbacks`,
  `serve_swap_errors`, `serve_swap_state` (0 idle / 1 preparing /
  2 staged), and the `serve_model_digest` info entry (current/prev/
  staged digests + checkpoint paths) every scrape carries.

The coordinator never builds or warms bundles — the service owns model
construction and the census warm (and runs them on the CALLER's thread,
concurrent with serving traffic; "background" means background to the
dataplane, not async). Two-phase FLEET swaps (serve/router.py) compose
these primitives: prepare = stage on every replica, commit = unanimous
pointer swap, abort = discard staged, rollback = swap back to prev.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Dict, List, Optional

from dsin_tpu.utils import locks as locks_lib

#: serve_swap_state gauge values
SWAP_IDLE = 0
SWAP_PREPARING = 1
SWAP_STAGED = 2


class SwapError(RuntimeError):
    """A hot-swap transition was refused (no staged bundle to commit,
    nothing to roll back to, digest disagreement at commit, a second
    swap while one is in flight). The service keeps serving its current
    bundle — a refused swap is an operator error, never an outage."""


class ConditionalRollbackRefused(SwapError):
    """A CONDITIONAL rollback (`expect_current=`) found the service
    already serving a different digest — this replica never committed
    the model being rolled away, so refusing is CONVERGENCE, not
    failure. Typed as its own class (ISSUE 18) so fleet- and
    federation-tier drivers can classify the refusal structurally; the
    message keeps the historical "conditional rollback refused" stem
    callers already string-match across the replica pipe."""


class ModelBundle:
    """One model version, whole: everything any dataplane stage reads.

    Immutable after construction except the process-backend pool slot,
    which the child-death rebuild swaps under the shared
    `serve.entropy_proc` rank (same discipline as the pre-swap service;
    instances share that rung's ledger). `epoch` increases monotonically
    across bundles in one service — rollback re-instates an OLD epoch
    rather than minting a new one, so "which model produced this" stays
    answerable from the epoch alone.
    """

    __slots__ = ("epoch", "digest", "ckpt", "state", "codec",
                 "device_state", "proc_initargs", "manifest", "_proc",
                 "_proc_lock")

    def __init__(self, epoch: int, digest: str, state, codec, device_state,
                 *, ckpt: Optional[str] = None, proc_initargs=None,
                 manifest: Optional[Dict[str, Any]] = None):
        self.epoch = int(epoch)
        self.digest = digest
        self.ckpt = ckpt
        self.state = state
        self.codec = codec
        self.device_state = device_state
        self.proc_initargs = proc_initargs
        self.manifest = manifest
        self._proc_lock = locks_lib.RankedLock("serve.entropy_proc")
        self._proc = None              # guarded-by: self._proc_lock

    # -- process-backend pool slot -------------------------------------------

    def proc(self):
        with self._proc_lock:
            return self._proc

    def set_proc(self, pool) -> None:
        with self._proc_lock:
            self._proc = pool

    def swap_proc_if(self, seen, factory) -> bool:
        """Child-death rebuild: the first bridge thread to report `seen`
        swaps in `factory()`; later reporters find it already replaced.
        The factory runs UNDER the slot lock — it only constructs an
        executor object (spawns are lazy), the same cost profile as the
        pre-swap service's rebuild path."""
        with self._proc_lock:
            if self._proc is not seen:
                return False
            self._proc = factory()
        return True

    def retire(self) -> None:
        """Release what this bundle exclusively owns (its process pool,
        if any). Idempotent; called OUTSIDE any ranked lock. In-flight
        tasks already submitted to the pool run to completion —
        shutdown(wait=False) only refuses new work — so a batch that
        captured this bundle still resolves."""
        with self._proc_lock:
            pool, self._proc = self._proc, None
        if pool is not None:
            pool.shutdown(wait=False)

    def __repr__(self) -> str:
        return (f"ModelBundle(epoch={self.epoch}, digest={self.digest!r}, "
                f"ckpt={self.ckpt!r})")


class SwapCoordinator:
    """current/staged/prev bundle slots + the transition rules.

    All methods are O(pointer swap) under `serve.model`; displaced
    bundles come back in the returned list for the caller to retire
    outside the lock. Exactly one prepare may be in flight (`begin_
    prepare` claims, `stage`/`abandon_prepare` releases) — a second
    swapper is refused typed, mirroring the rebalance claim flag.
    """

    def __init__(self, current: ModelBundle, metrics):
        self._lock = locks_lib.RankedLock("serve.model")
        self._current = current            # guarded-by: self._lock
        self._prev: Optional[ModelBundle] = None     # guarded-by: self._lock
        self._staged: Optional[ModelBundle] = None   # guarded-by: self._lock
        self._preparing = False            # guarded-by: self._lock
        self._next_epoch = current.epoch + 1         # guarded-by: self._lock
        # abort() during an IN-FLIGHT prepare cannot release the claim
        # (the preparing thread owns it) — it instead cancels every
        # epoch claimed so far; that prepare's stage() is then refused
        # typed and its own cleanup releases the claim. Without this, a
        # fleet abort racing a slow replica prepare would let the late
        # stage park a bundle nobody will ever commit or abort again.
        self._cancelled_before = 0         # guarded-by: self._lock
        self.metrics = metrics
        with self._lock:
            snap = self._snapshot_locked()
        self._publish_locked_out(snap)

    # -- reads ---------------------------------------------------------------

    @property
    def current(self) -> ModelBundle:
        with self._lock:
            return self._current

    @property
    def staged(self) -> Optional[ModelBundle]:
        """The prepared-but-uncommitted bundle, if any — the canary
        goldens publisher (serve/service.py `canary_goldens(staged=
        True)`) probes it to record what an incoming model SHOULD
        produce before anyone commits it."""
        with self._lock:
            return self._staged

    def live_epochs(self) -> List[int]:
        """Epochs a dataplane thread may still legitimately touch —
        the thread-local codec-clone caches prune against this."""
        with self._lock:
            return [b.epoch for b in (self._current, self._prev,
                                      self._staged) if b is not None]

    def all_bundles(self) -> List[ModelBundle]:
        with self._lock:
            return [b for b in (self._current, self._prev, self._staged)
                    if b is not None]

    def _snapshot_locked(self) -> Dict[str, Any]:
        swap_state = (SWAP_STAGED if self._staged is not None
                      else SWAP_PREPARING if self._preparing else SWAP_IDLE)
        return {
            "digest": self._current.digest,
            "epoch": self._current.epoch,
            "ckpt": self._current.ckpt,
            "prev_digest": self._prev.digest if self._prev else None,
            "staged_digest": self._staged.digest if self._staged else None,
            "swap_state": swap_state,
        }

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return self._snapshot_locked()

    def _publish_locked_out(self, snap: Dict[str, Any]) -> None:
        """Export the transition to /metrics — called with the snapshot
        already taken, AFTER the lock is released (metric locks are leaf
        rungs, but keeping the swap lock's hold time at pointer-swap
        cost is the contract)."""
        self.metrics.gauge("serve_swap_state").set(snap["swap_state"])
        self.metrics.set_info("serve_model_digest", snap)

    def _publish(self) -> None:
        with self._lock:
            snap = self._snapshot_locked()
        self._publish_locked_out(snap)

    # -- transitions ---------------------------------------------------------

    def begin_prepare(self) -> int:
        """Claim the single prepare slot; returns the epoch the incoming
        bundle must carry. Refused typed while another prepare runs or a
        staged bundle awaits its commit/abort."""
        with self._lock:
            if self._preparing:
                raise SwapError("a model swap is already preparing — one "
                                "swap at a time")
            if self._staged is not None:
                raise SwapError(
                    f"a prepared bundle (digest "
                    f"{self._staged.digest!r}) is already staged — "
                    f"commit or abort it before preparing another")
            self._preparing = True
            epoch = self._next_epoch
            self._next_epoch += 1
        self._publish()
        return epoch

    def abandon_prepare(self) -> None:
        """Release the prepare claim after a failed load/warm (the
        error path; the bundle never staged)."""
        with self._lock:
            self._preparing = False
        self.metrics.counter("serve_swap_errors").inc()
        self._publish()

    def stage(self, bundle: ModelBundle) -> None:
        """Prepared bundle parked, awaiting commit. The prepare claim
        converts into the staged slot — unless an abort() landed while
        the prepare was loading, in which case staging is refused typed
        (the preparer's cleanup retires the bundle and releases the
        claim)."""
        with self._lock:
            if not self._preparing:
                raise SwapError("stage() without begin_prepare()")
            if bundle.epoch < self._cancelled_before:
                raise SwapError(
                    f"swap prepare (epoch {bundle.epoch}) was aborted "
                    f"while it was still loading — not staging it")
            self._preparing = False
            self._staged = bundle
        self._publish()

    def commit(self, expect_digest: Optional[str] = None
               ) -> List[ModelBundle]:
        """staged -> current, current -> prev; returns displaced bundles
        (the old prev) for retirement. Instant: every expensive thing
        happened at prepare. `expect_digest` pins WHICH model the caller
        believes it is committing (the fleet two-phase contract)."""
        with self._lock:
            staged = self._staged
            if staged is None:
                raise SwapError("no staged bundle to commit — prepare "
                                "first")
            if expect_digest is not None and staged.digest != expect_digest:
                raise SwapError(
                    f"staged bundle digest {staged.digest!r} is not the "
                    f"expected {expect_digest!r} — refusing to commit a "
                    f"model the caller did not verify")
            displaced = [b for b in (self._prev,) if b is not None]
            self._staged = None
            self._prev = self._current
            self._current = staged
            snap = self._snapshot_locked()
        self.metrics.counter("serve_swaps").inc()
        self._publish_locked_out(snap)
        return displaced

    def abort(self) -> List[ModelBundle]:
        """Discard the staged bundle (prepare failed fleet-wide, digest
        disagreement, operator abort). No-op when nothing is staged —
        abort must be safe to broadcast. An abort that lands while a
        prepare is still LOADING cancels it: the late stage() is
        refused and the preparer cleans itself up (the claim is never
        force-released here, so a racing second prepare cannot
        interleave with the dying one)."""
        with self._lock:
            staged, self._staged = self._staged, None
            if self._preparing:
                self._cancelled_before = self._next_epoch
            snap = self._snapshot_locked()
        if staged is not None:
            self.metrics.counter("serve_swap_errors").inc()
        self._publish_locked_out(snap)
        return [staged] if staged is not None else []

    def rollback(self, expect_current: Optional[str] = None
                 ) -> List[ModelBundle]:
        """current <-> prev: instant, both bundles warm. Symmetric — a
        second rollback re-instates the rolled-away model (operator
        ping-pong is safe); nothing is displaced. `expect_current`
        guards a CONDITIONAL rollback (the fleet commit-failure
        recovery): it only runs if the serving digest IS the one being
        rolled away — a replica whose commit never landed refuses
        typed instead of blindly re-instating some older model."""
        with self._lock:
            if self._prev is None:
                raise SwapError("nothing to roll back to (no previous "
                                "model bundle is retained)")
            if expect_current is not None \
                    and self._current.digest != expect_current:
                raise ConditionalRollbackRefused(
                    f"conditional rollback refused: serving digest "
                    f"{self._current.digest!r} is not the expected "
                    f"{expect_current!r} (this replica never committed "
                    f"the model being rolled back)")
            self._current, self._prev = self._prev, self._current
            snap = self._snapshot_locked()
        self.metrics.counter("serve_rollbacks").inc()
        self._publish_locked_out(snap)
        return []


class RollbackWatchdog:
    """Post-swap automatic rollback trigger (ISSUE 11 satellite; the
    ROADMAP elastic-fleet item PR 9 deferred).

    The one health signal a just-committed model cannot fake is its
    typed-error rate against live traffic. The watchdog keeps a short
    sliding window of (time, typed_errors, resolved) counter samples —
    the supervisor feeds it one sample per tick — and on every
    `commit_swap` ARMS a comparison: the typed-error rate over the
    `window_s` BEFORE the commit (the old model's baseline) versus the
    rate over the first `min_requests`-plus resolutions AFTER it. Once
    the post window has both elapsed and seen enough traffic to judge,
    `evaluate` returns a verdict exactly once; a post-minus-pre rate
    jump beyond `threshold` tells the service to call
    `rollback(expect_current=<committed digest>)` — CONDITIONAL, so a
    watchdog racing an operator who already rolled back refuses typed
    instead of double-flipping models.

    Canary watch (ISSUE 13): `arm` also pins the committed digest for
    the golden canary, and keeps watching it even after a HEALTHY
    error-rate verdict — a numerically degraded model emits wrong
    BYTES, not typed errors, so the rate comparison can come back clean
    while the canary is still probing. `note_canary_failure(digest)`
    against the watched digest makes the next `evaluate` fire
    immediately (reason "canary"); the watch clears on disarm/rollback
    or the next arm.

    Pure bookkeeping: this class never touches the swap coordinator or
    metrics itself — the service samples the counters, and acts on the
    verdict OUTSIDE this object's lock (the `serve.watchdog` rank sits
    below `serve.workers`, and rollback's `serve.model` acquisition
    must never nest under it)."""

    def __init__(self, window_s: float, threshold: float,
                 min_requests: int):
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        if threshold <= 0:
            raise ValueError(f"threshold must be > 0, got {threshold}")
        if min_requests < 1:
            raise ValueError(f"min_requests must be >= 1, "
                             f"got {min_requests}")
        self.window_s = float(window_s)
        self.threshold = float(threshold)
        self.min_requests = int(min_requests)
        self._lock = locks_lib.RankedLock("serve.watchdog")
        # (t, typed_errors, resolved) samples, oldest first
        self._samples: deque = deque()   # guarded-by: self._lock
        self._armed: Optional[Dict[str, Any]] = None  # guarded-by: self._lock
        # the canary watch (ISSUE 13) outlives the error-rate verdict:
        # a healthy error rate clears `_armed` within one window, but
        # the first canary probe of a numerically degraded model can
        # take LONGER than that window (the errors it makes are wrong
        # BYTES, not typed failures) — so the committed digest stays
        # watched until disarm/rollback/next arm, and a canary failure
        # against it fires whenever it lands
        self._watch_digest: Optional[str] = None   # guarded-by: self._lock
        self._canary_failed = False                # guarded-by: self._lock

    @staticmethod
    def _rate(errors: int, resolved: int) -> float:
        return (errors / resolved) if resolved > 0 else 0.0

    def sample(self, now: float, typed_errors: int, resolved: int) -> None:
        """One supervisor-tick counter observation; old samples beyond
        2x the window age out (bounded memory at any tick rate)."""
        with self._lock:
            self._samples.append((now, typed_errors, resolved))
            horizon = now - 2.0 * self.window_s
            while len(self._samples) > 1 and self._samples[0][0] < horizon:
                self._samples.popleft()

    def arm(self, now: float, digest: str, typed_errors: int,
            resolved: int) -> None:
        """Called at commit: pin the committed digest, the post-window
        baseline counters, and the PRE-swap error rate computed from
        the sample window ending now."""
        with self._lock:
            base_t, base_e, base_r = now, typed_errors, resolved
            # oldest sample still inside the pre window = the baseline
            pre_e = pre_r = 0
            for t, e, r in self._samples:
                if t >= now - self.window_s:
                    pre_e, pre_r = typed_errors - e, resolved - r
                    break
            self._armed = {
                "digest": digest,
                "t_commit": base_t,
                "base_errors": base_e,
                "base_resolved": base_r,
                "pre_rate": self._rate(pre_e, pre_r),
            }
            self._watch_digest = digest
            self._canary_failed = False

    def disarm(self) -> None:
        """Manual swap/rollback supersedes a pending comparison AND the
        canary watch — never judge a model that already left."""
        with self._lock:
            self._armed = None
            self._watch_digest = None
            self._canary_failed = False

    def note_canary_failure(self, digest: str) -> bool:
        """Second firing signal (ISSUE 13): the golden canary observed
        a digest mismatch on the WATCHED model (the last committed
        digest — watched until disarm/rollback/next arm, even after the
        error-rate comparison came back healthy). Canary evidence is
        definitive (pinned inputs through deterministic executables),
        so the next `evaluate` fires immediately — no error-rate window
        to wait out. Ignored (False) when nothing is watched or the
        failure names a different digest (a stale probe racing a
        rollback must not condemn the model that replaced it)."""
        with self._lock:
            if self._watch_digest is None or self._watch_digest != digest:
                return False
            self._canary_failed = True
        return True

    @property
    def armed(self) -> bool:
        with self._lock:
            return self._armed is not None

    def evaluate(self, now: float, typed_errors: int,
                 resolved: int) -> Optional[Dict[str, Any]]:
        """The post-window judgement, returned at most once per arm:
        None while the window is still open or the post-commit traffic
        is below `min_requests` (too little evidence to roll back a
        model over); else {"fire", "pre_rate", "post_rate", "digest"}
        and the watchdog disarms."""
        with self._lock:
            if self._canary_failed:
                # canary evidence stands alone: fire now, regardless of
                # traffic volume or whether the error-rate comparison
                # already returned healthy (wrong BYTES are not typed
                # errors — the rate never sees them)
                digest = self._watch_digest
                self._armed = None
                self._watch_digest = None
                self._canary_failed = False
                return {
                    "fire": True,
                    "reason": "canary",
                    "digest": digest,
                    "window_s": self.window_s,
                }
            armed = self._armed
            if armed is None:
                return None
            if now < armed["t_commit"] + self.window_s:
                return None
            post_resolved = resolved - armed["base_resolved"]
            if post_resolved < self.min_requests:
                return None
            post_rate = self._rate(typed_errors - armed["base_errors"],
                                   post_resolved)
            # the error-rate verdict is returned exactly once; the
            # canary watch on this digest persists (see __init__)
            self._armed = None
        return {
            "fire": post_rate - armed["pre_rate"] > self.threshold,
            "reason": "error_rate",
            "pre_rate": round(armed["pre_rate"], 4),
            "post_rate": round(post_rate, 4),
            "post_resolved": post_resolved,
            "digest": armed["digest"],
            "window_s": self.window_s,
        }
