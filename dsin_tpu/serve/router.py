"""Multi-replica front door: admission control + shared-nothing scale-out.

Aggregate serve throughput was capped at ONE Python interpreter: PRs 4-7
pipelined the dataplane, sharded the bucket ladder across devices, and
escaped the GIL on the entropy stage, but every request still funneled
through one process and (until ISSUE 8) one FIFO-ish queue. This module
is the layer "Evaluating the Practicality of Learned Image Compression"
(PAPERS.md, arXiv 2207.14524) says decides deployment viability:

* **AdmissionController** — the front-door gate. Tracks per-class
  OUTSTANDING work (queued + in-flight, incremented at admit and
  released by a `Future.add_done_callback` the moment the answer
  lands) and sheds BEFORE anything is enqueued, pickled, or shipped to
  a replica: a rejected request costs one counter read, never zombie
  work. Sheds raise the same typed per-class `ServiceOverloaded` the
  batcher uses; per-class `serve_admitted_<cls>` /
  `serve_shed_admission_<cls>` counters export the decisions.

* **FrontDoorRouter** — one lightweight router process (the caller's)
  in front of N SHARED-NOTHING service replicas. Each replica is a full
  `CompressionService` in its own spawn process (spawn, not fork: a
  forked jax runtime is a deadlock lottery) that warms its OWN codec,
  executables, and persistent compile cache; the picklable
  `ServiceConfig` is the entire bootstrap, and each replica answers a
  `coding/loader.py` `params_digest` at the ready handshake so the
  router REFUSES a fleet whose replicas built different models (the
  cross-replica bit-identity contract, pinned end to end by
  tests/test_serve_router.py and serve_bench's frontdoor probe).
  Routing is round-robin PER CLASS over the live replicas; per-replica
  `/healthz` polling (each replica runs its own metrics endpoint)
  feeds eviction after `evict_after` consecutive failures and
  readmission on the next healthy poll. A replica that DIES with
  requests in flight does not fail its callers: the reader thread
  drains its in-flight map and re-dispatches each request once to a
  live replica (encode/decode are pure, so the retry is safe), failing
  typed `ServiceUnavailable` only when no replica remains. Every
  future resolves exactly once.

Topology (shared-nothing: no state crosses the dashed line except the
pipe messages and the config):

    caller ──> AdmissionController ──> FrontDoorRouter (per-class rr)
                                         │ pipe        │ pipe
                                   ┌─────┴─────┐ ┌─────┴─────┐
                                   │ replica 0 │ │ replica 1 │  ...
                                   │ service + │ │ service + │
                                   │ /healthz  │ │ /healthz  │
                                   └───────────┘ └───────────┘

Fleet-coordinated hot swap (ISSUE 9): `swap_model(ckpt_dir)` drives the
service-level swap primitives (serve/swap.py) as a TWO-PHASE commit so
the fleet can never settle on two models. Phase 1 (prepare): every live
replica loads + manifest-verifies + warms the incoming checkpoint in
the background of its own traffic and reports the digest it built.
Phase 2 (commit): only on a UNANIMOUS digest match, the router briefly
gates new dispatches (commits are O(1) pointer swaps, so the gate holds
for milliseconds) and tells every replica to commit exactly that
digest. Any prepare failure — a typed ManifestMismatch, a replica dying
mid-prepare — aborts the whole fleet (staged bundles discarded, old
params keep serving); a commit failure rolls the already-committed
replicas BACK, converging on the old model rather than a split fleet.
Control ops ride the same pipes as requests but are never rerouted on
replica death — a dead replica fails ITS phase, typed. `rollback()`
fan-outs the instant per-replica rollback the same way. Router-side
evidence: `serve_router_swaps` / `serve_router_swap_aborts` /
`serve_router_rollbacks` counters and the refreshed fleet digest.

Session pinning (ISSUE 10): SI sessions are REPLICA-LOCAL state — the
device-resident SidePrep lives in exactly one replica's store
(serve/session.py), so the router PINS each session at open:
`open_session` round-robins the open onto a live replica and records
sid -> replica; every `submit_decode_si` for that sid dispatches to its
pinned replica only. A dead pinned replica cannot be rerouted around
(no other replica holds the prep): its in-flight SI work and all later
submits for its sessions fail typed `SessionExpired` — the client's
one recovery everywhere — and the pins are dropped so the slots never
hang. `serve_router_sessions_pinned` gauges the live pin table;
`serve_router_session_orphans` counts pins lost to replica death.

Router-level /metrics aggregation (the PR 8 follow-up): pass
`metrics_port` and the router serves ONE endpoint merging every
replica's snapshot — counters/gauges/accumulators summed, histograms
merged (count-weighted mean; p50/p99 as the fleet-wide max, the
conservative operator view), per-replica model digests + scrape health
in the info section — so operators stop polling N ports. Snapshot
FRESHNESS is verified (ISSUE 11 satellite): every registry snapshot
carries a monotonic `seq` + `captured_at`, and a replica whose seq
failed to advance since the previous scrape (or whose capture
timestamp is old) is flagged in `replicas_stale` and EXCLUDED from the
merge instead of silently contributing frozen numbers.

Elastic fleet (ISSUE 14): the replica set is MUTABLE at runtime.
`add_replica()` spawns one cold replica and admits it to the rotation
only after the full warm-before-admit handshake — the child builds +
warms its whole executable census (the persistent compile cache makes
that cheap) and answers its `params_digest`, which must equal the
fleet's or the newcomer is killed and refused typed (`FleetScaleError`)
BEFORE a single request can route to it: scale-up can never split the
fleet or compile in steady state. `drain_replica()` is the graceful
inverse: the victim leaves the dispatch rotation immediately (state
"draining" — `_pick` skips it, pinned SI submits answer typed
`SessionExpired` at the door), its in-flight work finishes on it within
a bounded window, its pinned sessions typed-fail through the SAME
"replica leaves rotation" path a crash uses (`_leave_rotation`: pin
orphaning and in-flight re-dispatch are literally one code path for
death and drain), then the process is reaped. One scale op at a time
(`FleetScaleError`), and scale ops are mutually exclusive with fleet
swaps — a replica admitted mid-commit could land on either side of the
digest. `serve/autoscale.py` closes the loop: its Autoscaler watches
the aggregated signals and calls add/drain itself, and its fleet-health
watchdog drives `rollback(expect_digest=...)` — CONDITIONAL per
replica, so it converges with (never fights) a per-replica
RollbackWatchdog that already rolled its own service back.

Tracing (ISSUE 11): the router mints the front-door `TraceContext`
(serve/trace.py) at `_submit` — its head sampling decision rides the
pipe with every (re)dispatch and is honored replica-side, so one trace
id indexes the router hop AND the replica-internal spans. The
`router.dispatch` span covers intake -> future resolution; the fleet
`/trace` endpoint (AggregatedTraces) merges the router's ring with a
live scrape of every replica's `/trace` onto one wall-clock timeline.
Replica deaths and admission sheds land in the router's own
FlightRecorder ring, dumping next to the replicas' own artifacts.

Locks (utils/locks.py ranks): `serve.frontdoor` (4) guards the replica
state table and the per-class rr counters; `serve.replica` (6) guards
each replica's in-flight map and serializes its pipe sends;
`serve.admission` (14) guards the per-class outstanding counts — rank
ABOVE the batcher (10) because the release callback may run under the
batcher condition (a shed resolves the victim's future there).
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
import urllib.request
from dataclasses import replace
from typing import Any, Dict, List, Mapping, Optional, Tuple

from dsin_tpu.serve import metrics as metrics_lib
from dsin_tpu.serve import protocol
from dsin_tpu.serve import shmlane
from dsin_tpu.serve import trace as trace_lib
from dsin_tpu.serve.batcher import (DeadlineExceeded, Future, ServeError,
                                    ServiceOverloaded, ServiceUnavailable,
                                    UnknownPriorityClass)
from dsin_tpu.serve.session import SessionExpired
from dsin_tpu.serve.swap import SwapError
from dsin_tpu.utils import locks as locks_lib

#: re-exported from serve/protocol.py (the one shared definition the
#: router parent and the replica child both parse by)
CONTROL_OPS = protocol.CONTROL_OPS

#: how long _dispatch will wait on the commit gate before proceeding
#: anyway (fail-open: a wedged swap must degrade to pre-swap routing,
#: never to a frozen front door)
_SWAP_GATE_TIMEOUT_S = 10.0


class FleetSwapError(RuntimeError):
    """A fleet-coordinated swap did not converge on the NEW model: a
    prepare failed or disagreed (fleet aborted, old model serving), or
    a commit failed partway (committed replicas rolled back). Carries
    `per_replica` — {replica_idx: outcome-or-exception} — so the
    operator sees exactly which replica refused and why."""

    def __init__(self, msg: str, per_replica: Optional[Dict] = None):
        super().__init__(msg)
        self.per_replica = dict(per_replica or {})


class FleetScaleError(RuntimeError):
    """A runtime fleet mutation (add_replica/drain_replica) was refused
    or failed: the newcomer built a DIFFERENT model than the fleet
    serves (it was killed before it could take traffic), a second scale
    op raced the first, a scale op raced a fleet swap, or a drain would
    empty the fleet. The current rotation keeps serving either way."""


def default_admission_limits(config) -> Dict[str, int]:
    """ONE process's worth of admissible backlog per class: the class's
    queue bound plus everything the executor pipelines can hold in
    flight — max_batch * workers * pipeline_depth * devices (workers
    are PER-DEVICE executor threads). Shared by the in-process service
    gate and the front door (which scales it by replica count) so the
    two derivations cannot drift."""
    slack = (config.max_batch * max(1, config.workers)
             * max(1, config.pipeline_depth)
             * (1 if getattr(config, "devices", None) is None
                else max(1, config.devices)))
    classes = getattr(config, "priority_classes", None)
    if classes:
        return {pc.name: pc.max_queue + slack for pc in classes}
    return {"default": config.max_queue + slack}


class AdmissionController:
    """Per-class outstanding-work caps, enforced at the door.

    `limits` maps class name -> max outstanding (queued + in-flight)
    requests. `admit(cls)` either takes a slot or raises a typed
    per-class ServiceOverloaded — cheap rejection, nothing enqueued;
    `attach(cls, future)` arranges the release on resolution (success,
    shed, expiry, crash — any resolution frees the slot)."""

    def __init__(self, limits: Mapping[str, int],
                 metrics: Optional[metrics_lib.MetricsRegistry] = None):
        if not limits:
            raise ValueError("admission control needs at least one "
                             "class limit")
        bad = {c: n for c, n in limits.items() if int(n) < 1}
        if bad:
            raise ValueError(f"admission limits must be >= 1: {bad}")
        self.limits: Dict[str, int] = {str(c): int(n)
                                       for c, n in limits.items()}
        self.metrics = (metrics if metrics is not None
                        else metrics_lib.MetricsRegistry())
        self._lock = locks_lib.RankedLock("serve.admission")
        self._outstanding: Dict[str, int] = {
            c: 0 for c in self.limits}     # guarded-by: self._lock

    def admit(self, cls: str) -> None:
        limit = self.limits.get(cls)
        if limit is None:
            raise UnknownPriorityClass(
                f"unknown priority class {cls!r} "
                f"(admission classes: {sorted(self.limits)})")
        with self._lock:
            n = self._outstanding[cls]
            shed = n >= limit
            if not shed:
                self._outstanding[cls] = n + 1
        if shed:
            self.metrics.counter(f"serve_shed_admission_{cls}").inc()
            raise ServiceOverloaded(
                f"admission control: class {cls!r} at capacity "
                f"({n}/{limit} outstanding) — shed before enqueue",
                priority=cls, depth=n)
        self.metrics.counter(f"serve_admitted_{cls}").inc()

    def release(self, cls: str) -> None:
        with self._lock:
            self._outstanding[cls] = max(0, self._outstanding[cls] - 1)

    def set_limits(self, limits: Mapping[str, int]) -> None:
        """Resize the per-class caps in place (ISSUE 14: the router
        rescales its derived aggregate caps when the fleet grows or
        shrinks — scaled-up capacity behind the old cap would shed the
        very load the scale-up was fired to absorb). The CLASS SET is
        fixed at construction; shrinking below the current outstanding
        simply sheds new admits until the backlog drains."""
        bad = {c: n for c, n in limits.items() if int(n) < 1}
        if bad:
            # jaxlint: disable=contract-typed-raise -- operator reconfig
            # validation (the autoscale rescale hook), not client request
            # data: it fails the reconfig call synchronously, no request
            # future exists to hang
            raise ValueError(f"admission limits must be >= 1: {bad}")
        with self._lock:
            if set(map(str, limits)) != set(self._outstanding):
                # jaxlint: disable=contract-typed-raise -- operator
                # reconfig validation, same boundary as above: fails the
                # reconfig call, never a request future
                raise ValueError(
                    f"admission classes are fixed at construction "
                    f"(have {sorted(self._outstanding)}, got "
                    f"{sorted(map(str, limits))})")
            self.limits = {str(c): int(n) for c, n in limits.items()}

    def attach(self, cls: str, future: Future) -> None:
        """Release the class slot the moment `future` resolves (runs on
        the resolving thread; the admission rung ranks above the
        batcher's so the callback is legal under it)."""
        future.add_done_callback(lambda _f: self.release(cls))

    def outstanding(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._outstanding)


# -- replica child ------------------------------------------------------------

def _picklable_exc(exc: BaseException) -> BaseException:
    """Exceptions cross the pipe; one that cannot pickle (exotic ctor)
    degrades to a RuntimeError carrying its repr rather than killing
    the sender."""
    import pickle
    try:
        pickle.loads(pickle.dumps(exc))
        return exc
    except Exception:
        return RuntimeError(f"{type(exc).__name__}: {exc}")


def _replica_main(conn, config, replica_id: int, lanes=None) -> None:
    """Spawn target: one full shared-nothing service replica.

    Builds + warms its own CompressionService from the picklable
    ServiceConfig (own codec, own executables, own persistent-compile-
    cache warm — `CompilationSentinel(budget=0)` holds per replica
    because warmup is the same per-process warmup every service runs),
    starts its own /healthz endpoint (metrics_port=0 -> ephemeral), and
    answers the ready handshake with its pid, healthz port, and params
    digest. Then: one reader loop (submit requests, answer via future
    callbacks through a single sender thread so pipe writes never
    interleave and never run under a ranked lock) until "stop" or
    router death (EOF), then a graceful drain.

    `lanes` (shm transport) carries the manifests of the two lane rings
    the ROUTER created for this replica: requests arrive as LaneRef
    descriptors resolved (and freed) here, and the sender thread — the
    sole allocator of the result ring — lanes big "ok" payloads back.
    The child only attaches; the router owns segment lifetime."""
    from dsin_tpu.serve.service import CompressionService
    from dsin_tpu.utils import recompile
    req_ring = res_ring = None
    try:
        if lanes is not None:
            req_ring = shmlane.LaneRing.attach(lanes["req"])
            res_ring = shmlane.LaneRing.attach(lanes["res"])
        cfg = replace(config, metrics_port=0)
        service = CompressionService(cfg).start()
        warm = service.warmup()
        info = {"replica": replica_id, "pid": os.getpid(),
                "healthz_port": service._metrics_server.port,
                "warmup_compiles": warm["compiles"],
                "warmup_cache_hits": warm["cache_hits"],
                # this child's ABSOLUTE compile count the moment it is
                # warm (ISSUE 14): serve_bench's autoscale leg gates
                # `serve_xla_compiles(end of serving life) - this == 0`
                # per replica — the exact warm-before-admit evidence
                "compiles_at_ready": recompile.compilation_count(),
                # the service's cached bundle digest IS
                # coding/loader.py params_digest over (params,
                # batch_stats) — one digest story everywhere
                "params_digest": service.model_digest}
        if res_ring is not None:
            res_ring.set_metrics(service.metrics)
    except BaseException as e:  # noqa: BLE001 — the router needs the cause
        try:
            conn.send(("failed", replica_id, _picklable_exc(e)))
        finally:
            conn.close()
            for ring in (req_ring, res_ring):
                if ring is not None:
                    ring.close()
        return
    outq: "queue.Queue" = queue.Queue()

    def _sender():
        # the ONE result-ring allocator: laning happens here, on a
        # single thread, so "ok" payloads never race for lanes and a
        # pipe death can still free what it just claimed
        while True:
            item = outq.get()
            if item is None:
                return
            wire = None
            if res_ring is not None and item[0] == "ok":
                wire = protocol.wire_payload(res_ring, item[2])
                item = (item[0], item[1], wire)
            try:
                conn.send(item)
            except (OSError, ValueError, BrokenPipeError):
                if isinstance(wire, shmlane.LaneRef):
                    res_ring.free(wire)
                return     # router gone; the reader will see EOF too

    sender = threading.Thread(target=_sender, daemon=True,
                              name=f"replica-{replica_id}-send")
    sender.start()
    outq.put(("ready", replica_id, info))

    def _complete(rid, fut):
        exc = fut.exception(timeout=0)
        if exc is None:
            outq.put(("ok", rid, fut.result(timeout=0)))
        else:
            outq.put(("err", rid, _picklable_exc(exc)))

    def _run_control(op, rid, payload):
        """One hot-swap phase against this replica's service; the
        outcome (or its typed error — ManifestMismatch, SwapError)
        crosses the pipe like any response."""
        try:
            if op == "swap_prepare":
                res = service.prepare_swap(payload)
            elif op == "swap_commit":
                res = service.commit_swap(expect_digest=payload)
            elif op == "swap_abort":
                res = service.abort_swap()
            else:                            # "rollback"
                # payload = digest to roll AWAY from (conditional, the
                # fleet commit-failure recovery) or None (operator)
                res = service.rollback(expect_current=payload)
            outq.put(("ok", rid, res))
        except BaseException as e:  # noqa: BLE001 — router needs the cause
            outq.put(("err", rid, _picklable_exc(e)))

    try:
        while True:
            try:
                msg = conn.recv()
            except (EOFError, OSError):
                break              # router died: drain and exit
            if msg[0] == protocol.STOP:
                break
            # request messages carry a 6th element since ISSUE 11 (the
            # front-door TraceContext); control ops stay 5-tuples
            op, rid, payload, priority, deadline_ms, trace = \
                protocol.parse_request(msg)
            try:
                # identity for inline payloads; a LaneRef copies out of
                # the request ring (CRC-verified) and frees the lane —
                # the receiver-frees half of the lane contract
                payload = protocol.resolve_payload(req_ring, payload)
            except (ValueError, shmlane.ShmLaneError) as e:
                # IntegrityError (corrupt lane / geometry liar) or a
                # descriptor with no ring: answer typed, keep serving
                outq.put(("err", rid, _picklable_exc(e)))
                continue
            if op in CONTROL_OPS:
                if op == "swap_prepare":
                    # prepare is the slow phase (load + census warm):
                    # run it OFF the recv loop so requests keep flowing
                    # — the zero-downtime half of the contract. The
                    # service's own claim flag serializes overlapping
                    # prepares (the second fails typed).
                    threading.Thread(
                        target=_run_control, args=(op, rid, payload),
                        name=f"replica-{replica_id}-swap",
                        daemon=True).start()
                else:
                    # commit/abort/rollback are O(1) pointer swaps —
                    # inline keeps them ordered with request intake
                    _run_control(op, rid, payload)
                continue
            if op in ("session_open", "session_close"):
                # session control (ISSUE 10). close is an O(1) store
                # evict — inline. open runs the per-bucket prep
                # executable (AE reconstruction of the side image +
                # device upload — real device time at big buckets), so
                # it runs OFF the recv loop like swap_prepare: request
                # intake must not head-of-line block behind a session
                # registration. A failure (over-capacity, bad shape)
                # crosses the pipe typed like any response.
                def _session_ctl(op_=op, rid_=rid, payload_=payload):
                    try:
                        res = (service.open_session(payload_)
                               if op_ == "session_open"
                               else service.close_session(payload_))
                    except BaseException as e:  # noqa: BLE001 — typed
                        outq.put(("err", rid_, _picklable_exc(e)))
                    else:
                        outq.put(("ok", rid_, res))
                if op == "session_open":
                    threading.Thread(
                        target=_session_ctl,
                        name=f"replica-{replica_id}-session",
                        daemon=True).start()
                else:
                    _session_ctl()
                continue
            try:
                if op == "encode":
                    fut = service.submit_encode(
                        payload, deadline_ms=deadline_ms,
                        priority=priority, trace=trace)
                elif op == "decode":
                    fut = service.submit_decode(
                        payload, deadline_ms=deadline_ms,
                        priority=priority, trace=trace)
                elif op == "decode_si":
                    fut = service.submit_decode_si(
                        payload[0], payload[1], deadline_ms=deadline_ms,
                        priority=priority, trace=trace)
                else:
                    raise ValueError(f"unknown replica op {op!r}")
            except BaseException as e:  # noqa: BLE001 — typed door rejects
                outq.put(("err", rid, _picklable_exc(e)))
                continue
            fut.add_done_callback(
                lambda f, rid=rid: _complete(rid, f))
    finally:
        service.drain()
        # "bye" goes through the sender queue like every other message:
        # a main-thread conn.send here could interleave with an
        # in-progress sender write and corrupt the stream
        outq.put(("bye", replica_id, None))
        outq.put(None)
        sender.join(timeout=10)
        if not sender.is_alive():
            conn.close()
            # close (never unlink — the router owns the segments) only
            # once the sender cannot be mid-write into a lane
            for ring in (req_ring, res_ring):
                if ring is not None:
                    ring.close()
        # a wedged sender keeps the fd — closing under its write would
        # be the same interleaving; process exit reclaims it


def _spawn_launcher(config, idx: int, ctx, lanes=None):
    """Default replica launcher: a real spawn process + duplex pipe.
    Tests substitute a launcher whose far end is driven in-process.
    `lanes` (shm transport) is the picklable {req, res} ring-manifest
    pair the child attaches to."""
    parent, child = ctx.Pipe(duplex=True)
    proc = ctx.Process(target=_replica_main,
                       args=(child, config, idx, lanes),
                       name=f"serve-replica-{idx}", daemon=True)
    proc.start()
    child.close()
    return proc, parent


# -- router (parent) ----------------------------------------------------------

class _Pending:
    """One routed request: everything needed to re-dispatch it if its
    replica dies mid-flight (encode/decode are pure — a retry is
    safe), plus the caller's future. Exactly-once resolution is owned
    by whoever pops it from an in-flight map. The deadline is pinned
    ABSOLUTE at intake (`expires_at`) so a reroute forwards only the
    REMAINING budget instead of restarting the clock. `trace` (ISSUE
    11) is the front-door TraceContext that crosses the pipe with every
    (re)dispatch — a rerouted request keeps its trace id."""

    __slots__ = ("op", "payload", "priority", "expires_at", "future",
                 "retries", "trace")

    def __init__(self, op, payload, priority, deadline_ms, retries,
                 trace=None):
        self.op = op
        self.payload = payload
        self.priority = priority
        self.expires_at = (None if deadline_ms is None
                           else time.monotonic() + deadline_ms / 1000.0)
        self.future = Future()
        self.future.trace = trace
        self.retries = retries
        self.trace = trace

    def remaining_ms(self) -> Optional[float]:
        """Budget left right now; None = no deadline, <= 0 = expired."""
        if self.expires_at is None:
            return None
        return (self.expires_at - time.monotonic()) * 1000.0


class _Replica:
    """Parent-side replica handle: process, pipe, and the in-flight map
    (rid -> _Pending) under the per-replica `serve.replica` lock, which
    also serializes pipe sends (interleaved Connection writes corrupt
    the stream). With the shm transport, `rings` holds the two lane
    rings the ROUTER created for this replica ("req": router allocates,
    child frees; "res": child's sender allocates, router's reader
    frees) — created before spawn, unlinked exactly once when the
    replica leaves for good."""

    __slots__ = ("idx", "proc", "conn", "info", "lock", "inflight",
                 "reader", "rings")

    def __init__(self, idx: int, proc, conn, rings=None):
        self.idx = idx
        self.proc = proc
        self.conn = conn
        self.info: Optional[dict] = None
        self.lock = locks_lib.RankedLock("serve.replica")
        self.inflight: Dict[int, _Pending] = {}   # guarded-by: self.lock
        self.reader: Optional[threading.Thread] = None
        self.rings: Optional[Dict[str, shmlane.LaneRing]] = rings

    def ring(self, which: str) -> Optional[shmlane.LaneRing]:
        rings = self.rings
        return None if rings is None else rings.get(which)

    def close_rings(self) -> None:
        """Unlink both segments (idempotent; creator side only — the
        router created them). Attached children keep valid mappings
        until they close; the NAME disappears now, so a /dev/shm census
        goes clean the moment the replica leaves the rotation."""
        rings, self.rings = self.rings, None
        if rings:
            for ring in rings.values():
                ring.unlink()


class FrontDoorRouter:
    """N shared-nothing service replicas behind one in-process front
    door: admission gate -> per-class round-robin -> replica pipe.

    Lifecycle: start() (spawns + waits for every ready handshake,
    refuses digest mismatches) -> submit_encode/submit_decode/encode/
    decode -> drain(). `launcher(config, idx, ctx) -> (proc|None, conn)`
    is injectable for tests (fake replicas driven in-process)."""

    def __init__(self, config, replicas: int = 2,
                 admission_limits: Optional[Mapping[str, int]] = None,
                 poll_every_s: float = 0.25, evict_after: int = 2,
                 death_retries: int = 1, health_timeout_s: float = 2.0,
                 start_timeout_s: float = 600.0, launcher=None,
                 metrics_port: Optional[int] = None,
                 trace_sample_rate: float = 0.0,
                 trace_capacity: int = 4096,
                 flight_dir: Optional[str] = None,
                 transport: Optional[str] = None,
                 prewarm_template: bool = False,
                 shm_lanes_per_class: Optional[int] = None):
        if replicas < 1:
            raise ValueError(f"need at least one replica, got {replicas}")
        if evict_after < 1:
            raise ValueError(f"evict_after must be >= 1, got {evict_after}")
        # router->replica payload transport: None inherits the config's
        # (which governs the service->entropy-pool hop the same way)
        self.transport = (transport if transport is not None
                          else getattr(config, "transport", "pipe"))
        if self.transport not in ("pipe", "shm"):
            raise ValueError(
                f"transport must be 'pipe' or 'shm', "
                f"got {self.transport!r}")
        self._shm_lanes_per_class = shm_lanes_per_class
        self.config = config
        self.num_replicas = int(replicas)
        self.poll_every_s = float(poll_every_s)
        self.evict_after = int(evict_after)
        self.death_retries = int(death_retries)
        self.health_timeout_s = float(health_timeout_s)
        self.start_timeout_s = float(start_timeout_s)
        self.metrics = metrics_lib.MetricsRegistry()
        classes = getattr(config, "priority_classes", None)
        self._class_names: List[str] = (
            [pc.name for pc in classes] if classes else ["default"])
        # class default deadlines resolve HERE, at the front door, so a
        # reroute off a dead replica spends the remaining budget rather
        # than letting the replacement replica restart the default clock
        self._default_deadline_ms: Dict[str, Optional[float]] = (
            {pc.name: pc.default_deadline_ms for pc in classes}
            if classes else {})
        if admission_limits is None:
            # default: every replica can hold a full class queue plus
            # its pipelines in flight (shared derivation with the
            # service's own gate) — the cap is on the AGGREGATE
            # backlog, and it RESCALES with the live fleet (ISSUE 14:
            # add/drain/death re-derive it; an operator-given explicit
            # map never moves)
            self._admission_per_replica: Optional[Dict[str, int]] = \
                dict(default_admission_limits(config))
            admission_limits = {
                c: self.num_replicas * per_replica
                for c, per_replica in
                self._admission_per_replica.items()}
        else:
            self._admission_per_replica = None
        self.admission = AdmissionController(admission_limits,
                                             metrics=self.metrics)
        self._launcher = launcher or _spawn_launcher
        self._lock = locks_lib.RankedLock("serve.frontdoor")
        # APPEND-ONLY at runtime (ISSUE 14): a drained/dead replica
        # keeps its slot (its idx stays a stable key for pins, metrics,
        # per-replica info) in a terminal state; add_replica appends.
        self._replicas: List[_Replica] = []   # guarded-by: self._lock
        self._state: Dict[int, str] = {}   # guarded-by: self._lock
        self._fails: Dict[int, int] = {}   # guarded-by: self._lock
        self._rr: Dict[str, int] = {}      # guarded-by: self._lock
        self._rid = 0                      # guarded-by: self._lock
        # one runtime scale op (add/drain) at a time; also excludes
        # fleet swaps (a replica admitted mid-commit could land on
        # either side of the digest)
        self._scaling = False              # guarded-by: self._lock
        # sid -> replica idx: the session-affinity pin table (ISSUE 10)
        self._sessions: Dict[str, int] = {}  # guarded-by: self._lock
        self._stop = threading.Event()
        self._poller: Optional[threading.Thread] = None
        self._started = False
        self.params_digest: Optional[str] = None
        self._swapping = False             # guarded-by: self._lock
        # set = dispatch flows; cleared only for the fleet COMMIT window
        # (O(1) per replica), so "the fleet serves two models at once"
        # has no dispatch to land in. Fail-open after a bounded wait.
        self._swap_gate = threading.Event()
        self._swap_gate.set()
        self.metrics_port = metrics_port
        self._metrics_server: Optional[metrics_lib.MetricsServer] = None
        #: the fleet-merged metrics view (the one-endpoint aggregation);
        #: usable directly (`.snapshot()`) or served via `metrics_port`
        self.aggregate = AggregatedMetrics(self)
        # observability (ISSUE 11): the router mints the FRONT-DOOR
        # trace context (its head sampling decision rides the pipe and
        # is honored by the replica), records the router.dispatch span,
        # and keeps its own flight ring (sheds, replica deaths)
        self.tracer = trace_lib.Tracer(
            sample_rate=trace_sample_rate, capacity=trace_capacity,
            metrics=self.metrics)
        self.flight = trace_lib.FlightRecorder(
            dump_dir=flight_dir, metrics=self.metrics)
        #: the fleet-merged trace view: the router's own spans + a live
        #: /trace scrape of every replica, stitched onto one timeline
        self.traces = AggregatedTraces(self)
        # pre-warmed replica template (cold-start attack): one paused,
        # census-warmed spawn held in reserve OUTSIDE the rotation (no
        # reader thread — nothing routes to it), so add_replica becomes
        # digest-handshake + unpause. Stock/admit/discard run under the
        # rank-3 serve.template lock (BELOW frontdoor: admit walks into
        # the replica-table machinery while holding it).
        self._template_enabled = bool(prewarm_template)
        self._template_lock = locks_lib.RankedLock("serve.template")
        self._template: Optional[_Replica] = None  # guarded-by: self._template_lock
        self._template_thread: Optional[threading.Thread] = None  # guarded-by: self._template_lock

    # -- lifecycle ----------------------------------------------------------

    def _lane_classes(self) -> List[shmlane.LaneClass]:
        """Ring geometry for ONE replica direction: a lane class per
        bucket (sized for the widest payload a bucket produces —
        float32 HxWx3 plus pickle slack) and a small class for the
        blobs between the inline threshold and the smallest bucket.
        Oversize falls back inline by contract, so the bound only has
        to be right for the common case, not a guarantee."""
        per = self._shm_lanes_per_class
        if per is None:
            per = min(16, max(4, self.config.max_batch
                              * max(1, self.config.workers)
                              * max(1, self.config.pipeline_depth)))
        bounds = [("small", shmlane.SMALL_INLINE_MAX * 4)]
        for (bh, bw) in self.config.buckets:
            bounds.append((f"b{bh}x{bw}", bh * bw * 3 * 4 + 65536))
        return shmlane.derive_lane_classes(bounds, per)

    def _launch(self, idx: int, ctx, tag: str = "") -> _Replica:
        """Launch one replica through the injectable launcher. With the
        shm transport the router creates the replica's two lane rings
        FIRST (it owns segment lifetime end to end — one process to
        blame for a /dev/shm leak) and ships their manifests to the
        child, which only attaches."""
        if self.transport != "shm":
            proc, conn = self._launcher(self.config, idx, ctx)
            return _Replica(idx, proc, conn)
        classes = self._lane_classes()
        rings = {
            "req": shmlane.LaneRing.create(f"{tag}r{idx}q", classes,
                                           metrics=self.metrics),
            "res": shmlane.LaneRing.create(f"{tag}r{idx}s", classes,
                                           metrics=self.metrics),
        }
        # the fallback contract is typed + counted + FLIGHT-RECORDED:
        # the counter says how often, the timeline says when and why
        rings["req"].on_fallback = (
            lambda reason, size, _idx=idx: self.flight.record(
                "shm_fallback", replica=_idx, reason=reason,
                payload_bytes=size))
        try:
            proc, conn = self._launcher(
                self.config, idx, ctx,
                lanes={"req": rings["req"].manifest(),
                       "res": rings["res"].manifest()})
        except BaseException:
            for ring in rings.values():
                ring.unlink()
            raise
        return _Replica(idx, proc, conn, rings=rings)

    def start(self) -> "FrontDoorRouter":
        if self._started:
            return self
        import multiprocessing
        ctx = multiprocessing.get_context("spawn")
        replicas = []
        for i in range(self.num_replicas):
            replicas.append(self._launch(i, ctx))
        with self._lock:
            self._replicas = replicas
        deadline = time.monotonic() + self.start_timeout_s
        digests = []
        try:
            for rep in replicas:
                rep.info = self._wait_ready(rep, deadline)
                digests.append(rep.info.get("params_digest"))
        except BaseException:
            self._kill_all()
            raise
        if len(set(digests)) > 1:
            self._kill_all()
            raise RuntimeError(
                f"replicas built DIFFERENT models (params digests "
                f"{digests}) — refusing a fleet whose members would "
                f"answer the same request with different bytes")
        self.params_digest = digests[0]
        with self._lock:
            for rep in replicas:
                self._state[rep.idx] = "live"
                self._fails[rep.idx] = 0
        for rep in replicas:
            rep.reader = threading.Thread(
                target=self._reader, args=(rep,),
                name=f"router-reader-{rep.idx}", daemon=True)
            rep.reader.start()
        self._poller = threading.Thread(target=self._poll_loop,
                                        name="router-health", daemon=True)
        self._poller.start()
        self._publish_replica_gauges()
        if self.metrics_port is not None:
            self._metrics_server = metrics_lib.MetricsServer(
                self.aggregate, self.health,
                port=self.metrics_port,
                trace=self.traces.http_snapshot).start()
        self._started = True
        self._kick_restock()
        return self

    def _all_replicas(self) -> List[_Replica]:
        """Snapshot of the replica list (append-only, but iterating the
        live list while add_replica appends is still a data race)."""
        with self._lock:
            return list(self._replicas)

    def _publish_replica_gauges(self) -> None:
        with self._lock:
            states = [self._state.get(rep.idx) for rep in self._replicas]
            live = sum(1 for s in states if s == "live")
            if self._admission_per_replica is not None:
                # the aggregate admission cap tracks the LIVE fleet: a
                # scaled-up fleet behind the old cap would shed exactly
                # the load the scale-up was meant to absorb. Applied
                # UNDER the frontdoor lock (4 -> admission 14, legal)
                # so two concurrent publishers cannot apply stale live
                # counts last-writer-wins.
                self.admission.set_limits(
                    {c: max(1, live) * per for c, per in
                     self._admission_per_replica.items()})
            # gauges too: publishes only happen on scale/death events,
            # so a last-writer-wins stale count would stand until the
            # NEXT fleet mutation (4 -> metrics 90, legal)
            self.metrics.gauge("serve_router_replicas").set(live)
            self.metrics.gauge("serve_router_replicas_total").set(
                len(states))

    def _wait_ready(self, rep: _Replica, deadline: float,
                    abort_on_stop: bool = False) -> dict:
        while True:
            if abort_on_stop and self._stop.is_set():
                raise RuntimeError(
                    "router is draining — abandoning replica startup")
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"replica {rep.idx} not ready within "
                    f"{self.start_timeout_s}s")
            try:
                if rep.conn.poll(min(remaining, 0.5)):
                    tag, _idx, payload = rep.conn.recv()
                    if tag == "ready":
                        return payload
                    if tag == "failed":
                        raise RuntimeError(
                            f"replica {rep.idx} failed to start"
                            ) from payload
                    continue
            except EOFError:
                raise RuntimeError(
                    f"replica {rep.idx} died during startup") from None
            if rep.proc is not None and not rep.proc.is_alive():
                raise RuntimeError(
                    f"replica {rep.idx} exited (code "
                    f"{rep.proc.exitcode}) during startup")

    def _kill_all(self) -> None:
        for rep in self._all_replicas():
            if rep.proc is not None and rep.proc.is_alive():
                rep.proc.terminate()
            try:
                rep.conn.close()
            except OSError:
                pass
            rep.close_rings()

    def __enter__(self) -> "FrontDoorRouter":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.drain()

    # -- intake -------------------------------------------------------------

    # NOTE: parameter order mirrors CompressionService.submit_* /
    # encode/decode exactly — the router is a drop-in front door, so
    # positional calls written against one must mean the same thing
    # against the other.

    # contract: request-path — every reachable raise must be a typed error
    def submit_encode(self, img, deadline_ms: Optional[float] = None,
                      priority: Optional[str] = None,
                      trace=None) -> Future:
        return self._submit("encode", img, priority, deadline_ms,
                            trace=trace)

    # contract: request-path — every reachable raise must be a typed error
    def submit_decode(self, blob: bytes,
                      deadline_ms: Optional[float] = None,
                      priority: Optional[str] = None,
                      trace=None) -> Future:
        return self._submit("decode", blob, priority, deadline_ms,
                            trace=trace)

    def encode(self, img, deadline_ms: Optional[float] = None,
               timeout: Optional[float] = 120.0,
               priority: Optional[str] = None):
        return self.submit_encode(img, deadline_ms,
                                  priority=priority).result(timeout)

    def decode(self, blob: bytes, deadline_ms: Optional[float] = None,
               timeout: Optional[float] = 120.0,
               priority: Optional[str] = None):
        return self.submit_decode(blob, deadline_ms,
                                  priority=priority).result(timeout)

    def _submit(self, op: str, payload, priority: Optional[str],
                deadline_ms: Optional[float], trace=None) -> Future:
        assert self._started, "start() the router before submitting"
        cls = priority or self._class_names[0]
        try:
            self.admission.admit(cls)   # sheds HERE, before any enqueue
        except ServiceOverloaded:
            self.flight.record("shed", reason="admission", cls=cls)
            raise
        if deadline_ms is None:
            deadline_ms = self._default_deadline_ms.get(cls)
        # an externally-minted context (the federation tier, ISSUE 18)
        # rides through unchanged — its head sampling decision already
        # happened, so one trace id stitches across both router tiers
        pending = _Pending(op, payload, cls, deadline_ms,
                           self.death_retries,
                           trace=(trace if trace is not None else
                                  self.tracer.mint(origin="router")))
        self.admission.attach(cls, pending.future)
        self._attach_trace(pending, op, cls)
        try:
            self._dispatch(pending)
        except ServiceUnavailable as e:
            # resolve the (admission-attached) future so the slot frees,
            # then still raise at the door like the single-process path
            pending.future.set_exception(e)
            raise
        self.metrics.counter(f"serve_router_routed_{cls}").inc()
        return pending.future

    def _attach_trace(self, pending: _Pending, op: str,
                      cls: str) -> None:
        """Router-hop observability (ISSUE 11): the router.dispatch
        span covers front-door intake -> future resolution (reroutes
        included — it is the caller-visible hop), and a typed-error
        resolution records into the router's flight ring like the
        service's own callback does replica-side."""
        ctx = pending.trace
        t0 = time.monotonic()

        def _resolved(fut):
            exc = fut.exception(timeout=0)
            self.tracer.span_for(ctx, trace_lib.SPAN_ROUTER, t0,
                                 time.monotonic(), op=op, cls=cls)
            if exc is not None and isinstance(exc, (ServeError,
                                                    ValueError)):
                self.tracer.error(ctx, exc)
                self.flight.note_error(
                    exc, trace_id=ctx.trace_id if ctx else None)

        pending.future.add_done_callback(_resolved)

    # -- side-information sessions (ISSUE 10) --------------------------------

    def _send_pinned(self, rep: _Replica, op: str,
                     pending: _Pending) -> bool:
        """Targeted send to a SPECIFIC replica (no re-pick on failure —
        session state lives only there). Returns False when the pipe is
        already gone; the caller owns the typed answer."""
        with self._lock:
            rid = self._next_rid_locked()
        # lane the payload OUTSIDE rep.lock (pickling a side image under
        # the send lock would serialize it against every other send);
        # claiming a lane acquires serve.shmlane(7) — legal under 6 too
        ring = rep.ring("req")
        wire = protocol.wire_payload(ring, pending.payload)
        with rep.lock:
            rep.inflight[rid] = pending
            try:
                # jaxlint: disable=lockgraph-blocking-reachable-under-lock -- conn.send under serve.replica(6): the child recv-loop drains unconditionally and admission bounds in-flight frames, so the pipe buffer cannot back up; a dead child raises BrokenPipeError instead of blocking
                rep.conn.send(protocol.request_msg(
                    op, rid, wire, pending.priority,
                    pending.remaining_ms(), pending.trace))
                return True
            except (OSError, ValueError, BrokenPipeError):
                del rep.inflight[rid]
        if isinstance(wire, shmlane.LaneRef):
            ring.free(wire)   # nobody will ever take it
        return False

    def _publish_pins(self) -> None:
        with self._lock:
            n = len(self._sessions)
        self.metrics.gauge("serve_router_sessions_pinned").set(n)

    def _drop_all_pins(self, reason: str) -> None:
        """Flush the whole pin table — every replica just invalidated
        its session store (a fleet swap commit or rollback), so every
        pin is stale: answering SessionExpired at the door beats paying
        a replica round trip to learn the same thing, and a long-lived
        router must not leak pins across model versions."""
        with self._lock:
            n = len(self._sessions)
            self._sessions.clear()
        if n:
            self.metrics.counter(
                f"serve_router_sessions_dropped_{reason}").inc(n)
        self._publish_pins()

    def open_session(self, side_img,
                     timeout: Optional[float] = 120.0) -> str:
        """Register a side image on ONE replica and pin the session to
        it: round-robin over live replicas at open time, then every
        decode_si for the returned sid routes there. A replica-side
        refusal (SessionOverCapacity, bad shape) raises typed here.

        A reply that times out AFTER the replica registered the prep
        leaves that prep unpinned on the replica (the router never
        learned its sid). That slot is not leaked forever — the store's
        LRU bound reclaims it under pressure and `session_ttl_s` ages it
        out — but deployments relying on opens-under-timeout should run
        with a TTL configured."""
        assert self._started, "start() the router before opening sessions"
        for _ in range(self.num_replicas):
            picked = self._pick("_session")
            if picked is None:
                break
            rep, _rid = picked
            pending = _Pending("session_open", side_img, "control",
                               None, 0)
            if not self._send_pinned(rep, "session_open", pending):
                self._on_disconnect(rep)
                continue
            sid = pending.future.result(timeout)
            with self._lock:
                self._sessions[sid] = rep.idx
            self.metrics.counter("serve_router_sessions_opened").inc()
            self._publish_pins()
            return sid
        raise ServiceUnavailable(
            f"no live replica to open a session on "
            f"({self.num_replicas} configured) — retry shortly")

    def close_session(self, session_id: str,
                      timeout: Optional[float] = 30.0) -> bool:
        """Unpin + free a session; False if it was already gone."""
        assert self._started, "start() the router first"
        with self._lock:
            idx = self._sessions.pop(session_id, None)
            rep = None if idx is None else self._replicas[idx]
        self._publish_pins()
        if rep is None:
            return False
        pending = _Pending("session_close", session_id, "control", None, 0)
        if not self._send_pinned(rep, "session_close", pending):
            self._on_disconnect(rep)
            return False    # replica gone: its store died with it
        try:
            return bool(pending.future.result(timeout))
        except Exception:   # noqa: BLE001 — the pin is dropped either way
            return False

    # contract: request-path — every reachable raise must be a typed error
    def submit_decode_si(self, blob: bytes, session_id: str,
                         deadline_ms: Optional[float] = None,
                         priority: Optional[str] = None,
                         trace=None) -> Future:
        """SI decode against a pinned session. An unknown pin, an
        evicted/dead pinned replica, or the replica dying mid-flight
        all answer typed `SessionExpired` — the prep existed in exactly
        one process, so 're-open the session' is the only recovery."""
        assert self._started, "start() the router before submitting"
        with self._lock:
            idx = self._sessions.get(session_id)
            state = None if idx is None else self._state.get(idx)
        if idx is None or state != "live":
            raise SessionExpired(
                f"session {session_id!r} is not pinned to a live replica "
                f"(never opened, closed, or its replica "
                f"{'died' if idx is not None else 'is unknown'}) — "
                f"re-open it")
        cls = priority or self._class_names[0]
        try:
            self.admission.admit(cls)   # sheds HERE, before any enqueue
        except ServiceOverloaded:
            self.flight.record("shed", reason="admission", cls=cls)
            raise
        if deadline_ms is None:
            deadline_ms = self._default_deadline_ms.get(cls)
        pending = _Pending("decode_si", (blob, session_id), cls,
                           deadline_ms, 0,
                           trace=(trace if trace is not None else
                                  self.tracer.mint(origin="router")))
        self.admission.attach(cls, pending.future)
        self._attach_trace(pending, "decode_si", cls)
        self._swap_gate.wait(_SWAP_GATE_TIMEOUT_S)
        with self._lock:
            rep = self._replicas[idx]
        if not self._send_pinned(rep, "decode_si", pending):
            self._on_disconnect(rep)
            exc = SessionExpired(
                f"session {session_id!r}'s replica {idx} is gone — "
                f"its prep died with it; re-open the session")
            pending.future.set_exception(exc)
            raise exc
        self.metrics.counter(f"serve_router_routed_{cls}").inc()
        self.metrics.counter(f"serve_router_routed_r{rep.idx}").inc()
        return pending.future

    def decode_si(self, blob: bytes, session_id: str,
                  deadline_ms: Optional[float] = None,
                  timeout: Optional[float] = 120.0,
                  priority: Optional[str] = None):
        return self.submit_decode_si(blob, session_id, deadline_ms,
                                     priority=priority).result(timeout)

    # -- routing ------------------------------------------------------------

    def _next_rid_locked(self) -> int:
        self._rid += 1
        return self._rid

    def _pick(self, cls: str) -> Optional[Tuple[_Replica, int]]:
        with self._lock:
            live = [rep for rep in self._replicas
                    if self._state[rep.idx] == "live"]
            if not live:
                return None
            i = self._rr.get(cls, 0)
            self._rr[cls] = i + 1
            return live[i % len(live)], self._next_rid_locked()

    def _dispatch(self, pending: _Pending) -> None:
        """Route to the class's next live replica; a send that discovers
        a dead pipe marks the replica and moves on. Raises typed
        ServiceUnavailable when no live replica accepts the send.
        Briefly parks on the swap gate during a fleet commit (the
        never-two-models window), failing OPEN after a bounded wait."""
        self._swap_gate.wait(_SWAP_GATE_TIMEOUT_S)
        for _ in range(self.num_replicas):
            picked = self._pick(pending.priority)
            if picked is None:
                break
            rep, rid = picked
            sent = False
            # lane the payload per-TARGET (a reroute re-encodes on the
            # new replica's ring — _Pending keeps the original object,
            # never a descriptor), outside rep.lock
            ring = rep.ring("req")
            wire = protocol.wire_payload(ring, pending.payload)
            with rep.lock:
                rep.inflight[rid] = pending
                try:
                    # forward the REMAINING budget: on a reroute the
                    # replacement replica must not restart the clock
                    # (the trace context rides every (re)dispatch, so
                    # a rerouted request keeps one stitched timeline)
                    # jaxlint: disable=lockgraph-blocking-reachable-under-lock -- conn.send under serve.replica(6): child recv-loop drains unconditionally, admission bounds in-flight; dead child -> BrokenPipeError, not a stuck write
                    rep.conn.send(protocol.request_msg(
                        pending.op, rid, wire, pending.priority,
                        pending.remaining_ms(), pending.trace))
                    sent = True
                except (OSError, ValueError, BrokenPipeError):
                    del rep.inflight[rid]
            if not sent and isinstance(wire, shmlane.LaneRef):
                ring.free(wire)   # nobody will ever take it
            if sent:
                self.metrics.counter(
                    f"serve_router_routed_r{rep.idx}").inc()
                return
            self._on_disconnect(rep)
        raise ServiceUnavailable(
            f"no live replica for class {pending.priority!r} "
            f"({self.num_replicas} configured) — retry shortly")

    def _reader(self, rep: _Replica) -> None:
        """Per-replica response pump. EOF (or 'bye') means the replica
        is gone — its in-flight work reroutes."""
        while True:
            try:
                msg = rep.conn.recv()
            except (EOFError, OSError):
                break
            tag = msg[0]
            if tag == "bye":
                break
            if tag not in ("ok", "err"):
                continue
            _tag, rid, payload = msg
            with rep.lock:
                pending = rep.inflight.pop(rid, None)
            if pending is None:
                continue   # already rerouted by a death race: drop, the
                #            live dispatch owns the future now
            if tag == "ok":
                try:
                    # identity for inline results; a LaneRef copies out
                    # of the result ring (CRC-verified) and frees the
                    # lane. A corrupt lane answers TYPED — the caller
                    # gets IntegrityError, never plausible wrong bytes.
                    payload = protocol.resolve_payload(
                        rep.ring("res"), payload)
                except (ValueError, shmlane.ShmLaneError) as e:
                    self.metrics.counter(
                        "serve_shm_integrity_errors").inc()
                    self.flight.record("shm_integrity", replica=rep.idx,
                                       error=f"{type(e).__name__}: {e}")
                    pending.future.set_exception(e)
                    continue
                pending.future.set_result(payload)
            else:
                if isinstance(payload, DeadlineExceeded):
                    self.metrics.counter(
                        f"serve_router_expired_{pending.priority}").inc()
                pending.future.set_exception(payload)
        self._on_disconnect(rep)

    def _on_disconnect(self, rep: _Replica) -> None:
        """Transport loss: classify it and run the ONE leave-rotation
        path. Only a replica that was already TOLD to stop
        ('stopping', or terminal 'drained') leaves as a graceful
        drain; EOF while merely 'draining' (the in-flight grace
        window, before the stop was sent) is a real crash — it must
        count as a death and trigger the flight dump."""
        with self._lock:
            reason = ("drain"
                      if self._state.get(rep.idx) in ("stopping",
                                                      "drained")
                      else "death")
        self._leave_rotation(rep, reason=reason)

    def _leave_rotation(self, rep: _Replica, *, reason: str) -> None:
        """THE one path a replica leaves the rotation by — crash/EOF
        ('death') and graceful scale-down ('drain') share it end to end
        (ISSUE 14 satellite: the two used to be separate code, so pin
        orphaning and in-flight handling could drift). First observer
        marks the terminal state and owns the cleanup (idempotent:
        later observers find it terminal and an empty map); session
        pins drop with `serve_router_session_orphans` accounting and
        in-flight requests resolve exactly once — rerouted, expired, or
        typed — identically in both paths. Futures resolve exactly
        once: ownership transfers by popping from the in-flight map."""
        terminal = "drained" if reason == "drain" else "dead"
        with self._lock:
            already = self._state.get(rep.idx) in ("dead", "drained")
            self._state[rep.idx] = terminal
        if already:
            return
        draining = self._stop.is_set()
        if not draining:
            if reason == "drain":
                # graceful exits are flight events, not deaths: the
                # scaler's own decision trail must not read as crashes
                self.flight.record("scale_down", replica=rep.idx)
            else:
                self.metrics.counter("serve_router_replica_deaths").inc()
                # replica death is a flight-dump trigger (ISSUE 11):
                # the router's ring holds the routing/shed decisions
                # that led up to it
                self.flight.note_death("replica_death", replica=rep.idx)
        # drop the replica's session pins FIRST: a submit racing this
        # exit must find no pin (typed SessionExpired at the door),
        # never a pin pointing at a corpse/drained store
        with self._lock:
            orphan_sids = [sid for sid, i in self._sessions.items()
                           if i == rep.idx]
            for sid in orphan_sids:
                del self._sessions[sid]
        if orphan_sids and not draining:
            self.metrics.counter("serve_router_session_orphans").inc(
                len(orphan_sids))
        self._publish_pins()
        with rep.lock:
            orphans = list(rep.inflight.items())
            rep.inflight.clear()
        for _rid, pending in orphans:
            if pending.future.done():
                continue
            if pending.op == "decode_si":
                # the session's prep lived only in the departed replica
                # — rerouting would hit a store that never heard of it;
                # fail typed with the one recovery that works
                pending.future.set_exception(SessionExpired(
                    f"replica {rep.idx} left the rotation ({reason}) "
                    f"holding this SI request — its session's prep "
                    f"went with it; re-open the session"))
                continue
            if pending.op in CONTROL_OPS:
                # a swap phase is pinned to ITS replica — rerouting a
                # prepare/commit to a different process would corrupt
                # the two-phase bookkeeping; the coordinator (swap_model)
                # sees the typed failure and aborts the fleet
                pending.future.set_exception(ServiceUnavailable(
                    f"replica {rep.idx} died during {pending.op}"))
                continue
            rem = pending.remaining_ms()
            if rem is not None and rem <= 0.0:
                # budget spent while the dead replica held it: expire
                # typed instead of rerouting zombie work
                self.metrics.counter(
                    f"serve_router_expired_{pending.priority}").inc()
                pending.future.set_exception(DeadlineExceeded(
                    f"replica {rep.idx} died holding this request and "
                    f"its deadline has already passed (class "
                    f"{pending.priority!r})", priority=pending.priority))
                continue
            if pending.retries > 0 and not draining:
                pending.retries -= 1
                self.metrics.counter("serve_router_reroutes").inc()
                try:
                    self._dispatch(pending)
                    continue
                except ServiceUnavailable as e:
                    pending.future.set_exception(e)
                    continue
            pending.future.set_exception(ServiceUnavailable(
                f"replica {rep.idx} went away with this request in "
                f"flight" + ("" if draining else " (no retry left)")))
        # terminal exit owns the shm segments too: unlink NOW (death
        # never reaches _reap) so a /dev/shm census after any exit —
        # crash or drain — is clean. Idempotent with _reap's unlink.
        rep.close_rings()
        self._publish_replica_gauges()

    # -- pre-warmed replica template (ISSUE 17) -------------------------------

    def _kick_restock(self) -> None:
        """Start a background stock of the template slot unless one is
        already running, one is already stocked, or the router is
        draining. Never blocks the caller on a spawn."""
        if not self._template_enabled or self._stop.is_set():
            return
        with self._template_lock:
            if self._template is not None:
                return
            t = self._template_thread
            if t is not None and t.is_alive():
                return
            self._template_thread = threading.Thread(
                target=self._stock_template, name="router-template",
                daemon=True)
            self._template_thread.start()
        self.metrics.counter("serve_template_restocks").inc()

    def _stock_template(self) -> None:
        """Background thread body: spawn + census-warm ONE reserve
        replica and park it OUTSIDE the rotation (no reader thread —
        it is paused: its service sits recv-blocked with zero traffic,
        executables warm, shm lanes pre-mapped). Runs WITHOUT the scale
        claim: stocking for seconds must not block a drain; only the
        O(1) admit runs under add_replica's claim."""
        import multiprocessing
        ctx = multiprocessing.get_context("spawn")
        rep = None
        try:
            with self._lock:
                idx = len(self._replicas)
            rep = self._launch(idx, ctx, tag="t")
            rep.info = self._wait_ready(
                rep, time.monotonic() + self.start_timeout_s,
                abort_on_stop=True)
        except BaseException as e:  # noqa: BLE001 — background, log+count
            if not self._stop.is_set():
                # a drain abort is a clean shutdown, not a stock failure
                self.metrics.counter("serve_template_failures").inc()
                self.flight.record("template_stock_failed",
                                   error=f"{type(e).__name__}: {e}")
            if rep is not None:
                self._reap(rep, timeout_s=5.0)
            return
        stale = None
        with self._template_lock:
            if self._stop.is_set() or self._template is not None:
                stale = rep    # drained while stocking / lost a race
            else:
                self._template = rep
        if stale is not None:
            self._reap(stale, stop_first=True, timeout_s=5.0)
            return
        self.metrics.gauge("serve_template_ready").set(1)
        self.flight.record("template_stocked",
                           digest=(rep.info or {}).get("params_digest"))

    def _take_template(self) -> Optional[_Replica]:
        with self._template_lock:
            rep, self._template = self._template, None
        if rep is not None:
            self.metrics.gauge("serve_template_ready").set(0)
        return rep

    def template_ready(self) -> bool:
        """True while a warmed reserve replica is stocked (the
        autoscale bench waits on this before timing the fast path)."""
        with self._template_lock:
            return self._template is not None

    def _discard_template(self, *, restock: bool) -> None:
        """Reap the stocked template (drain, or a fleet swap made its
        digest stale) and optionally stock a fresh one."""
        rep = self._take_template()
        if rep is not None:
            self._reap(rep, stop_first=True, timeout_s=5.0)
        if restock:
            self._kick_restock()

    def _revalidate_template(self) -> None:
        """After a fleet swap/rollback: a template warmed on the OLD
        digest can never be admitted (the admit handshake would refuse
        it) — discard it now and restock on the new model, instead of
        paying the miss at the next scale-up."""
        if not self._template_enabled:
            return
        with self._template_lock:
            rep = self._template
            digest = (rep.info or {}).get("params_digest") if rep else None
        if rep is not None and self.params_digest is not None \
                and digest != self.params_digest:
            self.metrics.counter("serve_template_stale").inc()
            self._discard_template(restock=True)

    def _admit_template(self, rep: _Replica) -> Optional[dict]:
        """The fast half of add_replica (caller holds the scale claim):
        digest handshake + unpause. The template already paid spawn +
        build + census warm when it was stocked; admit is appending it
        to the rotation and starting its reader — O(ms). Returns None
        (template unusable: died in reserve, or its digest went stale)
        to fall through to the cold path."""
        info = rep.info or {}
        digest = info.get("params_digest")
        alive = rep.proc is None or rep.proc.is_alive()
        if not alive or (self.params_digest is not None
                         and digest != self.params_digest):
            self.metrics.counter("serve_template_misses").inc()
            if not alive:
                self.flight.record("template_miss", reason="dead")
            else:
                self.metrics.counter("serve_template_stale").inc()
                self.flight.record("template_miss", reason="digest",
                                   template_digest=digest,
                                   fleet_digest=self.params_digest)
            self._reap(rep, stop_first=alive, timeout_s=5.0)
            return None
        if self.params_digest is None:
            self.params_digest = digest
        with self._lock:
            idx = len(self._replicas)
            rep.idx = idx     # the child's provisional id is cosmetic:
            #                   the reader matches answers on rid
            rep.info = dict(info, replica=idx)
            self._replicas.append(rep)
            self.num_replicas = len(self._replicas)
            self._state[idx] = "live"
            self._fails[idx] = 0
        rep.reader = threading.Thread(
            target=self._reader, args=(rep,),
            name=f"router-reader-{idx}", daemon=True)
        rep.reader.start()
        self.metrics.counter("serve_router_scale_ups").inc()
        self.metrics.counter("serve_template_admits").inc()
        self.flight.record("scale_up", replica=idx, digest=digest,
                           template=True,
                           warmup_compiles=info.get("warmup_compiles"))
        self._publish_replica_gauges()
        return dict(rep.info, replica=idx, template_admit=True)

    # -- elastic fleet: runtime replica mutation (ISSUE 14) -------------------

    def add_replica(self, timeout_s: Optional[float] = None) -> dict:
        """Spawn ONE cold replica and admit it to the rotation — but
        only after the full warm-before-admit handshake: the child
        builds + warms its entire executable census (the persistent
        compile cache makes a cold start cheap) and answers its
        `params_digest`, which must equal the fleet's. A mismatch (or a
        startup failure) kills the newcomer and raises typed
        `FleetScaleError` BEFORE it could take a single request: the
        fleet never splits and never compiles in steady state on
        scale-up. Returns the admitted replica's ready info (idx, pid,
        healthz port, warmup compile/cache-hit counts)."""
        assert self._started, "start() the router before scaling"
        self._claim_scale("add_replica")
        try:
            # fast path (ISSUE 17): a stocked pre-warmed template turns
            # admit into digest-handshake + unpause. A miss (stale
            # digest, died in reserve) falls through to the cold spawn
            # below; either way the slot restocks in the background.
            if self._template_enabled:
                tpl = self._take_template()
                admitted = (None if tpl is None
                            else self._admit_template(tpl))
                self._kick_restock()
                if admitted is not None:
                    return admitted
                if tpl is None:
                    self.metrics.counter("serve_template_misses").inc()
                    self.flight.record("template_miss",
                                       reason="not_stocked")
            import multiprocessing
            ctx = multiprocessing.get_context("spawn")
            with self._lock:
                idx = len(self._replicas)
            try:
                rep = self._launch(idx, ctx)
            except Exception as e:  # noqa: BLE001 — typed contract
                raise FleetScaleError(
                    f"replica {idx} could not be launched for "
                    f"scale-up ({type(e).__name__}: {e})") from e
            deadline = time.monotonic() + (self.start_timeout_s
                                           if timeout_s is None
                                           else float(timeout_s))
            try:
                rep.info = self._wait_ready(rep, deadline)
            except BaseException as e:
                self._reap(rep, stop_first=True)
                raise FleetScaleError(
                    f"replica {idx} failed to start for scale-up: "
                    f"{e}") from e
            digest = rep.info.get("params_digest")
            if self.params_digest is not None \
                    and digest != self.params_digest:
                self._reap(rep, stop_first=True)
                self.metrics.counter("serve_router_digest_skew").inc()
                raise FleetScaleError(
                    f"scale-up replica {idx} built model {digest!r} but "
                    f"the fleet serves {self.params_digest!r} — killed "
                    f"before it could answer a request (re-point the "
                    f"config's checkpoint or re-swap the fleet first)")
            # ADMIT: only now does the replica become routable
            if self.params_digest is None:
                # the fleet digest was UNKNOWN (an all-skipped
                # conditional rollback): adopt the newcomer's — it just
                # passed the same build the rest of the fleet did
                self.params_digest = digest
            with self._lock:
                self._replicas.append(rep)
                self.num_replicas = len(self._replicas)
                self._state[rep.idx] = "live"
                self._fails[rep.idx] = 0
            rep.reader = threading.Thread(
                target=self._reader, args=(rep,),
                name=f"router-reader-{rep.idx}", daemon=True)
            rep.reader.start()
            self.metrics.counter("serve_router_scale_ups").inc()
            self.flight.record("scale_up", replica=rep.idx,
                               digest=digest,
                               warmup_compiles=rep.info.get(
                                   "warmup_compiles"))
            self._publish_replica_gauges()
            return dict(rep.info, replica=rep.idx)
        finally:
            with self._lock:
                self._scaling = False

    def drain_replica(self, idx: Optional[int] = None,
                      timeout_s: float = 30.0) -> dict:
        """Gracefully remove one replica from the fleet. The victim
        (given, or auto-picked: fewest session pins, then fewest
        in-flight, then the newest) leaves the dispatch rotation
        IMMEDIATELY (state 'draining': `_pick` skips it and pinned SI
        submits answer typed SessionExpired at the door), its in-flight
        work gets up to `timeout_s` to finish on it, then it exits
        through the SAME leave-rotation path a crash uses — stragglers
        re-dispatch / typed-fail identically, pinned sessions orphan
        with the same accounting — and the process is reaped. Refused
        typed when it would empty the fleet."""
        assert self._started, "start() the router before scaling"
        self._claim_scale("drain_replica")
        try:
            with self._lock:
                live = [rep for rep in self._replicas
                        if self._state.get(rep.idx) == "live"]
                if idx is not None:
                    victim = next((rep for rep in self._replicas
                                   if rep.idx == idx), None)
                    if victim is None or \
                            self._state.get(idx) != "live":
                        raise FleetScaleError(
                            f"replica {idx} is not live "
                            f"({self._state.get(idx, 'unknown')!r}) — "
                            f"nothing to drain")
                else:
                    pins: Dict[int, int] = {}
                    for _sid, i in self._sessions.items():
                        pins[i] = pins.get(i, 0) + 1
                    depth: Dict[int, int] = {}
                    for rep in live:
                        with rep.lock:   # 4 -> 6: legal nesting
                            depth[rep.idx] = len(rep.inflight)
                    victim = min(
                        live, default=None,
                        key=lambda rep: (pins.get(rep.idx, 0),
                                         depth[rep.idx], -rep.idx))
                if victim is None or len(live) <= 1:
                    raise FleetScaleError(
                        f"refusing to drain replica "
                        f"{getattr(victim, 'idx', idx)}: it is the last "
                        f"live replica ({len(live)} live) — the fleet "
                        f"must keep serving")
                # out of the rotation NOW: no new dispatch picks it,
                # pinned submits answer typed at the door
                self._state[victim.idx] = "draining"
            self._publish_replica_gauges()
            # bounded grace for in-flight work to resolve ON the victim
            deadline = time.monotonic() + timeout_s
            inflight_left = 0
            while time.monotonic() < deadline:
                with victim.lock:
                    inflight_left = len(victim.inflight)
                if inflight_left == 0:
                    break
                time.sleep(0.01)
            # graceful stop: the child drains its service and answers
            # "bye"; the reader's EOF handling routes into
            # _leave_rotation(reason="drain") — stragglers (a wedged
            # victim) re-dispatch there exactly like a death's orphans.
            # 'stopping' marks that the EOF is now EXPECTED: a crash
            # BEFORE this point (state still 'draining') classifies as
            # a death, never a routine scale-down.
            with self._lock:
                if self._state.get(victim.idx) == "draining":
                    self._state[victim.idx] = "stopping"
            with victim.lock:
                try:
                    # jaxlint: disable=lockgraph-blocking-reachable-under-lock -- tiny one-tuple stop frame under serve.replica(6); the drained child is idle and recv-blocked, and a dead one raises instead of blocking
                    victim.conn.send(protocol.stop_msg())
                except (OSError, ValueError, BrokenPipeError):
                    pass
            if victim.reader is not None:
                victim.reader.join(timeout=timeout_s)
            self._leave_rotation(victim, reason="drain")  # idempotent
            self._reap(victim, timeout_s=timeout_s)
            self.metrics.counter("serve_router_scale_downs").inc()
            self._publish_replica_gauges()
            return {"replica": victim.idx,
                    "inflight_at_stop": inflight_left}
        finally:
            with self._lock:
                self._scaling = False

    def _claim_scale(self, op: str) -> None:
        with self._lock:
            if self._scaling:
                raise FleetScaleError(
                    f"{op}: a fleet scale op is already in flight — "
                    f"one at a time")
            if self._swapping:
                raise FleetScaleError(
                    f"{op}: a fleet swap is in flight — a replica "
                    f"admitted or drained mid-commit could split the "
                    f"fleet; retry after the swap settles")
            self._scaling = True

    def _reap(self, rep: _Replica, timeout_s: float = 10.0,
              stop_first: bool = False) -> None:
        """Retire one replica's process and close its pipe. The
        post-drain path already told the child to stop; the
        refused-newcomer paths pass `stop_first` so the (healthy,
        still-serving) child gets a graceful exit to react to instead
        of burning the whole join timeout. Terminate is always followed
        by a join — a SIGTERMed child whose status is never collected
        is a zombie until router shutdown."""
        if stop_first:
            try:
                rep.conn.send(protocol.stop_msg())
            except (OSError, ValueError, BrokenPipeError):
                pass
        if rep.proc is not None:
            rep.proc.join(timeout=timeout_s)
            if rep.proc.is_alive():
                rep.proc.terminate()
                rep.proc.join(timeout=5.0)
        try:
            rep.conn.close()
        except OSError:
            pass
        rep.close_rings()

    # -- fleet-coordinated hot swap (ISSUE 9) --------------------------------

    def _control(self, rep: _Replica, op: str, payload=None) -> Future:
        """Ship one swap-phase op to a SPECIFIC replica; the returned
        future resolves with the replica's outcome dict, or typed
        ServiceUnavailable if it dies first (never rerouted)."""
        pending = _Pending(op, payload, "control", None, 0)
        with self._lock:
            rid = self._next_rid_locked()
        sent = False
        with rep.lock:
            rep.inflight[rid] = pending
            try:
                # jaxlint: disable=lockgraph-blocking-reachable-under-lock -- control-op send under serve.replica(6): one small tuple, child recv-loop always draining; pipe death surfaces as BrokenPipeError below
                rep.conn.send(protocol.control_msg(op, rid, payload))
                sent = True
            except (OSError, ValueError, BrokenPipeError):
                del rep.inflight[rid]
        if not sent:
            self._on_disconnect(rep)
            pending.future.set_exception(ServiceUnavailable(
                f"replica {rep.idx} pipe is gone — cannot drive {op}"))
        return pending.future

    def _live_replicas(self) -> List[_Replica]:
        with self._lock:
            return [rep for rep in self._replicas
                    if self._state.get(rep.idx) == "live"]

    def _broadcast(self, reps, op: str, payload, timeout_s: float):
        """op to every rep; returns ({idx: result}, {idx: exception})."""
        futs = [(rep, self._control(rep, op, payload)) for rep in reps]
        deadline = time.monotonic() + timeout_s
        results: Dict[int, Any] = {}
        errors: Dict[int, BaseException] = {}
        for rep, fut in futs:
            remaining = max(0.0, deadline - time.monotonic())
            try:
                exc = fut.exception(timeout=remaining)
            except TimeoutError:
                errors[rep.idx] = TimeoutError(
                    f"replica {rep.idx} did not answer {op} within "
                    f"{timeout_s}s")
                continue
            if exc is None:
                results[rep.idx] = fut.result(timeout=0)
            else:
                errors[rep.idx] = exc
        return results, errors

    def swap_model(self, ckpt_dir: str, prepare_timeout_s: float = 600.0,
                   commit_timeout_s: float = 60.0) -> dict:
        """Two-phase fleet hot swap. Prepare on every live replica
        (each loads + manifest-verifies + warms in the background of
        its own traffic and reports the digest it built); commit only
        on a UNANIMOUS digest, under the brief dispatch gate. Any
        prepare failure aborts the whole fleet back to the old model;
        a commit failure rolls the committed replicas back — the fleet
        converges on ONE model either way, and this raises typed
        FleetSwapError naming each replica's outcome. Only LIVE
        replicas participate: one that sits out a swap evicted is
        refused readmission while its digest disagrees with the
        fleet's (`serve_router_digest_skew`) — re-swap or restart it."""
        assert self._started, "start() the router before swapping"
        with self._lock:
            if self._swapping:
                raise FleetSwapError("a fleet swap is already in flight "
                                     "— one at a time")
            if self._scaling:
                raise FleetSwapError(
                    "a fleet scale op (add/drain replica) is in flight "
                    "— a swap racing it could commit past a replica "
                    "entering or leaving the rotation; retry shortly")
            self._swapping = True
        try:
            reps = self._live_replicas()
            if not reps:
                raise ServiceUnavailable("no live replica to swap")
            prepared, errors = self._broadcast(
                reps, "swap_prepare", ckpt_dir, prepare_timeout_s)
            digests = {info["digest"] for info in prepared.values()}
            if errors or len(digests) != 1:
                # abort EVERY replica, not just the ones that answered:
                # a replica whose prepare merely TIMED OUT may still
                # stage later — the abort cancels the in-flight prepare
                # (SwapCoordinator refuses the late stage) so it cannot
                # park a bundle that would wedge every future swap.
                # Abort is a safe no-op where nothing is staged.
                self._broadcast(reps, "swap_abort", None,
                                commit_timeout_s)
                self.metrics.counter("serve_router_swap_aborts").inc()
                outcome = {i: f"prepared digest "
                              f"{prepared[i]['digest']}"
                           for i in prepared}
                outcome.update({i: e for i, e in errors.items()})
                raise FleetSwapError(
                    f"fleet prepare did not converge (digests "
                    f"{sorted(digests)!r}, {len(errors)} failure(s)) — "
                    f"aborted; every replica still serves the old "
                    f"model", per_replica=outcome)
            digest = digests.pop()
            # the never-two-models window: dispatch parks while every
            # replica executes its O(1) commit of the SAME digest
            self._swap_gate.clear()
            try:
                committed, commit_errors = self._broadcast(
                    reps, "swap_commit", digest, commit_timeout_s)
            finally:
                self._swap_gate.set()
            if not commit_errors:
                # every replica committed: their session stores were
                # invalidated by commit_swap, so the pins are all stale
                self._drop_all_pins("swap")
            if commit_errors:
                # converge DOWN. A commit that merely TIMED OUT may
                # still land later (the pipe is FIFO), so recovery for
                # the errored replicas is abort (clears a still-staged
                # bundle — the late commit then finds nothing) followed
                # by a CONDITIONAL rollback sent to EVERYONE: it only
                # fires where the serving digest IS the new one (a
                # late commit that did land gets rolled back; a replica
                # that never committed refuses typed). Either way each
                # replica ends on the OLD model.
                abort_reps = [r for r in reps if r.idx in commit_errors]
                self._broadcast(abort_reps, "swap_abort", None,
                                commit_timeout_s)
                self._broadcast(reps, "rollback", digest,
                                commit_timeout_s)
                self.metrics.counter("serve_router_swap_aborts").inc()
                # committed-then-rolled-back replicas cleared their
                # stores; conservatively drop EVERY pin (re-open is the
                # one client recovery anyway) rather than track which
                # replica kept its sessions through the partial commit
                self._drop_all_pins("swap")
                outcome = {i: "committed, rolled back" for i in committed}
                outcome.update({i: e for i, e in commit_errors.items()})
                raise FleetSwapError(
                    f"fleet commit failed on {len(commit_errors)} "
                    f"replica(s) — committed replicas rolled back; the "
                    f"fleet serves the OLD model", per_replica=outcome)
            self.params_digest = digest
            self.metrics.counter("serve_router_swaps").inc()
            return {"digest": digest,
                    "replicas": sorted(committed),
                    "prepare": prepared}
        finally:
            with self._lock:
                self._swapping = False
            # a template warmed pre-swap is stale now — refresh it in
            # the background rather than paying a miss at scale-up
            self._revalidate_template()

    def rollback(self, timeout_s: float = 60.0,
                 expect_digest: Optional[str] = None) -> dict:
        """Fleet-wide instant rollback (every replica re-instates its
        warm previous bundle) under the same dispatch gate. Partial
        failure raises FleetSwapError — the operator must know the
        fleet split rather than discover it as bit-identity flakes.

        `expect_digest` makes it CONDITIONAL per replica (ISSUE 14, the
        fleet-health driver's mode): each replica rolls back only if
        its serving digest IS the sick one; a replica already off it —
        typically because its OWN RollbackWatchdog fired first — refuses
        typed and is reported as skipped rather than failed, so the
        fleet driver converges with (never fights) a per-replica
        watchdog."""
        assert self._started, "start() the router before rollback"
        # a rollback is a fleet digest transition like a swap: claim
        # the same exclusivity so a scale op cannot admit/drain a
        # replica across the flip (the newcomer would be validated
        # against the pre-rollback digest)
        with self._lock:
            if self._swapping:
                raise FleetSwapError("a fleet swap/rollback is already "
                                     "in flight — one at a time")
            if self._scaling:
                raise FleetSwapError(
                    "a fleet scale op (add/drain replica) is in flight "
                    "— a rollback racing it could flip the digest "
                    "under an admit; retry shortly")
            self._swapping = True
        try:
            reps = self._live_replicas()
            if not reps:
                raise ServiceUnavailable("no live replica to roll back")
            self._swap_gate.clear()
            try:
                results, errors = self._broadcast(
                    reps, "rollback", expect_digest, timeout_s)
            finally:
                self._swap_gate.set()
            # every replica that rolled back invalidated its session
            # store
            self._drop_all_pins("rollback")
            skipped = {}
            if expect_digest is not None:
                # ONLY the conditional refusal counts as converged:
                # "this replica is not serving the sick digest" —
                # already rolled back (its own watchdog won the race)
                # or it never committed. Any OTHER SwapError (e.g.
                # "nothing to roll back to" from a replica that IS
                # serving the sick model with no prev bundle) is a
                # real failure — treating it as skipped would report
                # success over a split fleet.
                skipped = {i: e for i, e in errors.items()
                           if isinstance(e, SwapError)
                           and "conditional rollback refused" in str(e)}
                for i in skipped:
                    del errors[i]
            digests = {info["digest"] for info in results.values()}
            if errors or len(digests) > 1 \
                    or (not results and not skipped):
                self.metrics.counter("serve_router_swap_aborts").inc()
                outcome = {i: f"rolled back to {results[i]['digest']}"
                           for i in results}
                outcome.update({i: f"skipped: {e}"
                                for i, e in skipped.items()})
                outcome.update({i: e for i, e in errors.items()})
                raise FleetSwapError(
                    f"fleet rollback did not converge (digests "
                    f"{sorted(digests)!r}, {len(errors)} failure(s), "
                    f"{len(skipped)} skipped)", per_replica=outcome)
            if digests:
                self.params_digest = digests.pop()
            elif skipped:
                # EVERY replica had already rolled itself back: the
                # fleet is off the sick digest but nobody told this
                # router which digest it converged on — learn it from
                # /healthz instead of keeping the sick name (a stale
                # params_digest would refuse every healthy scale-up
                # newcomer). When the polls cannot resolve it (timeout,
                # split answers), record UNKNOWN rather than the sick
                # digest — the health poller re-learns it from the next
                # successful poll, and an unknown digest admits rather
                # than wedging every future scale-up on a stale value.
                polled = {d for ok, d in (self._healthz_ok(rep)
                                          for rep in reps) if ok and d}
                self.params_digest = (polled.pop() if len(polled) == 1
                                      else None)
            self.metrics.counter("serve_router_rollbacks").inc()
            return {"digest": self.params_digest,
                    "replicas": sorted(results),
                    "skipped": sorted(skipped)}
        finally:
            with self._lock:
                self._swapping = False
            # a template warmed pre-swap is stale now — refresh it in
            # the background rather than paying a miss at scale-up
            self._revalidate_template()

    # -- health -------------------------------------------------------------

    def _healthz_ok(self, rep: _Replica):
        """One /healthz poll -> (ok, serving_model_digest). Replicas
        without a port (test fakes) count as healthy while their
        transport lives, with no digest claim."""
        port = (rep.info or {}).get("healthz_port")
        if port is None:
            return (rep.proc is None or rep.proc.is_alive()), None
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz",
                    timeout=self.health_timeout_s) as resp:
                if resp.status != 200:
                    return False, None
                body = json.loads(resp.read().decode("utf-8"))
                return True, (body.get("model") or {}).get("digest")
        except Exception:   # noqa: BLE001 — any poll failure is a failure
            return False, None

    def _poll_loop(self) -> None:
        """Eviction/readmission: `evict_after` consecutive failed polls
        stop NEW traffic to a replica (in-flight work, if it is merely
        slow, still completes); one healthy poll readmits it. 'dead'
        (transport gone) is terminal — there is nobody to talk to."""
        while not self._stop.wait(self.poll_every_s):
            for rep in self._all_replicas():
                with self._lock:
                    state = self._state.get(rep.idx)
                if state in ("dead", "drained", "draining", "stopping"):
                    # terminal (nobody to talk to) or already leaving
                    # the rotation on purpose — polling it could only
                    # readmit a replica mid-drain
                    continue
                # no locks across the poll
                ok, digest = self._healthz_ok(rep)
                with self._lock:
                    if self._state.get(rep.idx) in ("dead", "drained",
                                                    "draining",
                                                    "stopping"):
                        continue
                    if ok:
                        self._fails[rep.idx] = 0
                        if (self.params_digest is None
                                and digest is not None
                                and self._state[rep.idx] == "live"):
                            # an all-skipped conditional rollback left
                            # the fleet digest UNKNOWN — re-learn it
                            # from the first live replica that answers
                            self.params_digest = digest
                        if self._state[rep.idx] == "evicted":
                            if (digest is not None
                                    and self.params_digest is not None
                                    and digest != self.params_digest):
                                # healthy but serving the WRONG model —
                                # it missed a fleet swap while evicted.
                                # Readmitting it would split the fleet;
                                # keep it out and surface the skew for
                                # the operator (re-swap or restart it).
                                self.metrics.counter(
                                    "serve_router_digest_skew").inc()
                                continue
                            self._state[rep.idx] = "live"
                            self.metrics.counter(
                                "serve_router_readmissions").inc()
                    else:
                        self._fails[rep.idx] += 1
                        if (self._fails[rep.idx] >= self.evict_after
                                and self._state[rep.idx] == "live"):
                            self._state[rep.idx] = "evicted"
                            self.metrics.counter(
                                "serve_router_evictions").inc()

    def health(self) -> dict:
        with self._lock:
            states = {str(rep.idx): self._state.get(rep.idx, "unknown")
                      for rep in self._replicas}
        live = sum(1 for s in states.values() if s == "live")
        # drained/draining/stopping replicas are leaving the fleet ON
        # PURPOSE (ISSUE 14): they are not degradation — only non-live
        # replicas that are still SUPPOSED to be serving count against
        # the status (a routine scale-down must not page anyone)
        expected = sum(1 for s in states.values()
                       if s not in ("drained", "draining", "stopping"))
        status = ("ok" if live and live == expected
                  else "degraded" if live else "unhealthy")
        return {"status": status, "live": live, "replicas": states,
                "outstanding": self.admission.outstanding(),
                "params_digest": self.params_digest}

    # -- shutdown -----------------------------------------------------------

    def drain(self, timeout_s: float = 60.0) -> None:
        """Graceful: stop polling, ask every replica to drain (their
        queued work resolves typed there and the answers flow back),
        join, then fail anything still unresolved — no hung futures."""
        self._stop.set()
        self._swap_gate.set()     # never strand a dispatcher on drain
        if self._template_enabled:
            # the reserve replica never took traffic; stop it like any
            # other child. A stock still in flight sees _stop set,
            # aborts its wait, and reaps its own spawn (rings included)
            # — JOIN it so the /dev/shm census is clean when drain
            # returns, then sweep anything stocked in between.
            self._discard_template(restock=False)
            with self._template_lock:
                stocker = self._template_thread
            if stocker is not None and stocker.is_alive():
                stocker.join(timeout=timeout_s)
            self._discard_template(restock=False)
        if self._metrics_server is not None:
            self._metrics_server.stop()
            self._metrics_server = None
        if self._poller is not None:
            self._poller.join(timeout=timeout_s)
        replicas = self._all_replicas()
        for rep in replicas:
            with rep.lock:
                try:
                    # jaxlint: disable=lockgraph-blocking-reachable-under-lock -- shutdown stop frame under serve.replica(6): tiny tuple, and the subsequent reader.join(timeout) bounds how long a wedged child can be waited on
                    rep.conn.send(protocol.stop_msg())
                except (OSError, ValueError, BrokenPipeError):
                    pass
        for rep in replicas:
            if rep.reader is not None:
                rep.reader.join(timeout=timeout_s)
        for rep in replicas:
            if rep.proc is not None:
                rep.proc.join(timeout=timeout_s)
                if rep.proc.is_alive():
                    rep.proc.terminate()
            try:
                rep.conn.close()
            except OSError:
                pass
            rep.close_rings()
            with rep.lock:
                leftovers = list(rep.inflight.values())
                rep.inflight.clear()
            for pending in leftovers:
                if not pending.future.done():
                    pending.future.set_exception(ServiceUnavailable(
                        "front door drained with this request in flight"))
        self.flight.flush(timeout=5.0)
        self.flight.close()


# -- router-level /metrics aggregation (ISSUE 9 satellite) --------------------

class AggregatedMetrics:
    """ONE fleet-wide metrics view: the router's own registry merged
    with a live scrape of every replica's `/metrics?format=json`.

    Merge rules (each scrape is a fresh fan-out — no caching, matching
    a single service's scrape semantics): counters, gauges, and
    accumulators SUM across the router + replicas (queue depths, worker
    counts, stage milliseconds all add meaningfully); histograms merge
    as total count, count-weighted mean, and the fleet-wide MAX p50/p99
    (quantiles do not compose exactly from summaries, and for an
    operator's SLO view the worst replica is the honest aggregate —
    per-replica values remain one port away). The info section carries
    the router's own info, each replica's scraped info + model digest
    (the fleet-version-skew view the two-phase swap maintains), and
    which replicas failed to answer the scrape. Duck-types the
    `MetricsRegistry` surface `MetricsServer` needs (`snapshot()` /
    `render_text()`), so `FrontDoorRouter(metrics_port=...)` serves it
    over the standard endpoint.

    Staleness (ISSUE 11 satellite): a scrape that ANSWERS is not
    necessarily FRESH — a wedged replica (or an interposed cache) can
    keep serving the same frozen snapshot while its dataplane is dead.
    Registry snapshots therefore carry a per-process monotonic `seq`
    (incremented by the snapshot itself) and a `captured_at` wall
    timestamp; this view remembers the last seq it saw per replica and
    treats a non-advancing seq, or a capture older than
    `stale_after_s`, as STALE — flagged in `info.replicas_stale` and
    excluded from the merge, never silently averaged in."""

    #: capture-timestamp slack before a scrape counts as stale (same
    #: host, so clock skew is not a concern at this scale)
    stale_after_s = 5.0

    def __init__(self, router: "FrontDoorRouter"):
        self._router = router
        # last seen snapshot seq per replica idx; the scrape loop may
        # run concurrently from ThreadingHTTPServer handler threads
        self._seq_lock = locks_lib.RankedLock("metrics.registry")
        self._last_seq: Dict[int, int] = {}   # guarded-by: self._seq_lock

    def _is_stale(self, idx: int, snap: dict, now: float) -> bool:
        """Freshness verdict for one replica scrape. A missing seq
        (pre-ISSUE-11 replica, test fake) is not judged — only
        POSITIVE evidence of staleness flags a replica. The seq test is
        EQUALITY, not <=: a live registry mints a fresh seq per
        snapshot, so two concurrent scrapes legitimately observe
        adjacent seqs in either order (a <= test would falsely flag the
        loser of that race), while a frozen/cached response replays the
        IDENTICAL seq — the signature being hunted. A seq that went
        BACKWARDS (replica restart) is fresh numbers, not stale ones."""
        seq = snap.get("seq")
        captured = snap.get("captured_at")
        stale = False
        if seq is not None:
            with self._seq_lock:
                prev = self._last_seq.get(idx)
                if prev is not None and seq == prev:
                    stale = True
                else:
                    self._last_seq[idx] = seq
        if captured is not None and now - captured > self.stale_after_s:
            stale = True
        return stale

    def _scrape(self, rep: _Replica) -> Optional[dict]:
        port = (rep.info or {}).get("healthz_port")
        if port is None:
            return None
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics?format=json",
                timeout=self._router.health_timeout_s) as resp:
            return json.loads(resp.read().decode("utf-8"))

    def snapshot(self) -> dict:
        own = self._router.metrics.snapshot()
        counters = dict(own["counters"])
        gauges = dict(own["gauges"])
        accumulators = dict(own["accumulators"])
        # histogram partials ride the shared two-tier merge helpers
        # (serve/metrics.py, ISSUE 18) — the federation applies the
        # identical rules to MEMBER roll-ups, one implementation
        hist: Dict[str, list] = metrics_lib.hist_partials(
            own["histograms"])
        per_replica_info: Dict[str, dict] = {}
        digests: Dict[str, Optional[str]] = {}
        unreachable = []
        stale = []
        # fan the scrapes out: unreachable replicas each burn up to
        # health_timeout_s, and paying that N times IN SERIES would
        # blow the operator's scrape interval — concurrent GETs bound
        # the endpoint at ~one timeout total
        from concurrent.futures import ThreadPoolExecutor

        def _safe_scrape(rep):
            try:
                return self._scrape(rep)
            except Exception:   # noqa: BLE001 — a dead scrape is data
                return None
        replicas = self._router._all_replicas()
        with self._router._lock:
            replica_states = {str(rep.idx):
                              self._router._state.get(rep.idx, "unknown")
                              for rep in replicas}
        # per-replica occupancy (ISSUE 14 satellite): the scaler's
        # primary input, published as a structural fact instead of
        # being hand-derived from counters. The router-side outstanding
        # depth (its in-flight map) is available even for a replica
        # whose scrape fails; the replica-side queue depth and batch
        # occupancy join it where the scrape answers.
        occupancy: Dict[str, dict] = {}
        for rep in replicas:
            with rep.lock:
                outstanding = len(rep.inflight)
            occupancy[str(rep.idx)] = {
                "state": replica_states[str(rep.idx)],
                "outstanding": outstanding,
                "queue_depth": None,
                "batch_occupancy_mean": None,
            }
        replica_errors: Dict[str, dict] = {}
        # scrape only replicas that can still answer: a long-lived
        # autoscaled fleet accretes terminally dead/drained slots in
        # the append-only list, and paying a doomed HTTP timeout per
        # retired replica on EVERY snapshot (while permanently
        # polluting replicas_unreachable) would mask a genuinely
        # unreachable LIVE replica. Their identity stays in
        # replica_digests/replica_states/replica_occupancy.
        targets = [rep for rep in replicas
                   if replica_states[str(rep.idx)]
                   not in ("dead", "drained")]
        for rep in replicas:
            if rep not in targets:
                digests[str(rep.idx)] = (rep.info or {}).get(
                    "params_digest")
        with ThreadPoolExecutor(
                max_workers=max(1, len(targets) or 1)) as pool:
            snaps = list(pool.map(_safe_scrape, targets))
        now = time.time()
        for rep, snap in zip(targets, snaps):
            if snap is None:
                unreachable.append(rep.idx)
                digests[str(rep.idx)] = (rep.info or {}).get(
                    "params_digest")
                continue
            if self._is_stale(rep.idx, snap, now):
                # frozen numbers are worse than missing ones: flag the
                # replica and keep its stale values OUT of the merge
                stale.append(rep.idx)
                digests[str(rep.idx)] = (rep.info or {}).get(
                    "params_digest")
                continue
            metrics_lib.merge_numeric_sections(
                counters, gauges, accumulators, hist, snap)
            info = snap.get("info", {})
            per_replica_info[str(rep.idx)] = info
            model = info.get("serve_model_digest") or {}
            digests[str(rep.idx)] = (model.get("digest")
                                     or (rep.info or {}).get(
                                         "params_digest"))
            occ = occupancy[str(rep.idx)]
            occ["queue_depth"] = snap.get("gauges", {}).get(
                "serve_queue_depth")
            bo = snap.get("histograms", {}).get("serve_batch_occupancy")
            if bo:
                occ["batch_occupancy_mean"] = bo.get("mean")
            # per-replica typed-error evidence (ISSUE 14): the fleet
            # health driver needs the SKEW across replicas — a summed
            # counter cannot say whether one replica or the whole
            # model is sick
            replica_errors[str(rep.idx)] = {
                "typed_errors": snap.get("counters", {}).get(
                    "serve_typed_errors", 0),
                "resolved": snap.get("counters", {}).get(
                    "serve_resolved", 0),
            }
        histograms = metrics_lib.fold_hist_partials(hist)
        # fleet model-health roll-up (ISSUE 13): the per-bucket gap/bpp
        # histograms merge through the generic rules above; the canary
        # verdicts are per-replica structural facts, so the aggregate
        # names WHICH replicas' canaries are failing instead of letting
        # a summed gauge average a sick replica away
        canary: Dict[str, Any] = {}
        canary_failing = []
        for idx, info in per_replica_info.items():
            c = info.get("serve_canary")
            if isinstance(c, dict):
                canary[idx] = {"status": c.get("status"),
                               "digest": c.get("digest")}
                if c.get("status") == "failed":
                    canary_failing.append(int(idx))
        return {
            "info": {
                "router": own["info"],
                "replica_digests": digests,
                "replica_states": replica_states,
                "replica_occupancy": occupancy,
                "per_replica": per_replica_info,
                "replicas_scraped": len(per_replica_info),
                "replicas_unreachable": unreachable,
                "replicas_stale": stale,
                "quality": {
                    "canary": canary,
                    "replicas_canary_failing": sorted(canary_failing),
                    "fleet_canary_ok": (not canary_failing) if canary
                    else None,
                    "replica_errors": replica_errors,
                },
            },
            "counters": dict(sorted(counters.items())),
            "gauges": dict(sorted(gauges.items())),
            "accumulators": dict(sorted(accumulators.items())),
            "histograms": histograms,
            # lock ledgers are per-process by nature; the aggregate
            # carries the ROUTER process's own (each replica's stay on
            # its port)
            "locks": own["locks"],
            "lock_order_inversions": own["lock_order_inversions"],
            # freshness evidence one tier up (ISSUE 18): the federation
            # applies the exact seq-equality + capture-age protocol to
            # MEMBER scrapes that this view applies to replica scrapes,
            # so the aggregate must carry its own router registry's seq
            # and capture timestamp (a frozen/cached member response
            # replays the identical pair)
            "seq": own.get("seq"),
            "captured_at": own.get("captured_at"),
        }

    def render_text(self) -> str:
        return metrics_lib.render_snapshot_text(self.snapshot())


# -- router-level /trace aggregation (ISSUE 11) -------------------------------

class AggregatedTraces:
    """ONE fleet-wide trace view: the router's own span ring merged
    with a live `/trace` scrape of every replica, stitched onto one
    wall-clock timeline (spans carry wall anchors precisely because
    monotonic bases do not compare across processes).

    A front-door request's trace id indexes the router.dispatch span
    (minted router-side, the context crossed the pipe) AND the
    replica-internal queue/device/entropy/SI spans — the scrape
    forwards the `?id=` filter so per-trace lookups stay cheap at the
    replicas. Mirrors AggregatedMetrics' scrape semantics: fresh
    fan-out per call, unreachable replicas reported, concurrent GETs so
    N dead replicas cost ~one timeout total."""

    def __init__(self, router: "FrontDoorRouter"):
        self._router = router

    def _scrape(self, rep: _Replica,
                trace_id: Optional[str]) -> Optional[dict]:
        port = (rep.info or {}).get("healthz_port")
        if port is None:
            return None
        url = f"http://127.0.0.1:{port}/trace"
        if trace_id is not None:
            url += f"?id={trace_id}"
        with urllib.request.urlopen(
                url, timeout=self._router.health_timeout_s) as resp:
            return json.loads(resp.read().decode("utf-8"))

    def snapshot(self, trace_id: Optional[str] = None) -> dict:
        own = self._router.tracer.snapshot(trace_id=trace_id)
        from concurrent.futures import ThreadPoolExecutor

        def _safe(rep):
            try:
                return self._scrape(rep, trace_id)
            except Exception:   # noqa: BLE001 — a dead scrape is data
                return None
        all_replicas = self._router._all_replicas()
        with self._router._lock:
            # retired (dead/drained) replicas cannot answer: scraping
            # them pays a doomed timeout per snapshot forever
            replicas = [rep for rep in all_replicas
                        if self._router._state.get(rep.idx)
                        not in ("dead", "drained")]
        scraped = 0
        unreachable = []
        parts = [own]
        if replicas:
            with ThreadPoolExecutor(max_workers=len(replicas)) as pool:
                snaps = list(pool.map(_safe, replicas))
            for rep, snap in zip(replicas, snaps):
                if snap is None:
                    unreachable.append(rep.idx)
                    continue
                scraped += 1
                parts.append(snap)
        return {
            "spans": trace_lib.merge_trace_snapshots(parts),
            "router_spans": len(own["spans"]),
            "replicas_scraped": scraped,
            "replicas_unreachable": unreachable,
            "flight": self._router.flight.meta(),
        }

    def http_snapshot(self, params: Mapping[str, str]) -> object:
        """/trace provider for the router's MetricsServer: same query
        surface as a single service (`?id=`, `?format=chrome`), fleet-
        merged."""
        snap = self.snapshot(trace_id=params.get("id"))
        if params.get("format") == "chrome":
            return trace_lib.chrome_trace(snap["spans"])
        return snap
