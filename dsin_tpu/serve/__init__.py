"""dsin_tpu.serve — long-lived micro-batching compression service.

Layering (each module stands alone below the next):
    buckets.py   — static shape buckets (fixed executable census)
    batcher.py   — bounded queues, same-bucket coalescing, priority
                   classes (per-class deadlines + shed order),
                   backpressure, drain (pure stdlib threading)
    router.py    — front door: per-class admission control + the
                   shared-nothing multi-replica router (spawned
                   service processes, /healthz-fed eviction, runtime
                   add/drain replica fleet mutation)
    autoscale.py — elastic-fleet control plane (ISSUE 14): windowed
                   autoscale + fleet-health policies (pure) and the
                   Autoscaler loop that calls add/drain/rollback
                   (+ the ISSUE 18 federation-tier health driver)
    federation.py— federated fleet tier (ISSUE 18): router-of-routers
                   over whole member fleets — staged rollout waves
                   gated by the wave canary + a health soak window,
                   partition-tolerant conditional auto-rollback,
                   host-sticky session pins, hierarchical admission
    placement.py — bucket ladder -> device mesh assignment (replica
                   policy + per-device shardings via parallel/mesh.py)
    session.py   — side-information session cache: LRU/TTL/byte-bounded
                   store of device-resident SidePrep bundles (ISSUE 10)
    quality.py   — model-health telemetry (ISSUE 13): coding-gap +
                   bpp export, per-session SI-match quality with a
                   floor alarm, and the golden canary that gates swaps
    trace.py     — span-based request tracer + crash flight recorder
                   (ISSUE 11): per-request TraceContexts, bounded span/
                   event rings, /trace + Chrome export, JSONL dumps
    metrics.py   — lock-guarded counters/gauges/histograms + http.server
                   /healthz + /metrics (+ /trace) endpoint
    service.py   — device-affine executor threads over the batched
                   jitted codec; model state loaded once via
                   coding/loader.py

Driven by tools/serve_bench.py (open-loop load + --devices scaling axis,
SERVE_BENCH.json).
"""

from dsin_tpu.serve.autoscale import (Autoscaler, AutoscaleConfig,
                                      AutoscaleError, AutoscalePolicy,
                                      FederationHealthDriver,
                                      FleetHealthPolicy,
                                      FleetHealthSignals, ScaleSignals,
                                      federation_health_from_snapshot,
                                      health_from_snapshot,
                                      signals_from_snapshot)
from dsin_tpu.serve.batcher import (BULK, INTERACTIVE, DeadlineExceeded,
                                    Future, MicroBatcher, PriorityClass,
                                    Request, ServeError, ServiceDraining,
                                    ServiceOverloaded, ServiceUnavailable,
                                    default_priority_classes)
from dsin_tpu.serve.buckets import (BucketPolicy, NoBucketFits,
                                    crop_from_bucket, pad_to_bucket)
from dsin_tpu.serve.federation import (FederatedMetrics, FederatedRouter,
                                       FederatedTraces, FederationError,
                                       Member, MemberUnreachable,
                                       RolloutAborted, RolloutPlan)
from dsin_tpu.serve.metrics import MetricsRegistry, MetricsServer
from dsin_tpu.serve.quality import CanaryFailed, QualityMonitor
from dsin_tpu.serve.placement import (DevicePlacement, PlacementError,
                                      PlacementPlan, RebalanceTrigger,
                                      plan_placement)
from dsin_tpu.serve.router import (AdmissionController, AggregatedMetrics,
                                   AggregatedTraces, FleetScaleError,
                                   FleetSwapError, FrontDoorRouter)
from dsin_tpu.serve.service import (CompressionService, EncodeResult,
                                    ServiceConfig)
from dsin_tpu.serve.session import (SessionEntry, SessionError,
                                    SessionExpired, SessionOverCapacity,
                                    SessionStore)
from dsin_tpu.serve.swap import (ConditionalRollbackRefused, ModelBundle,
                                 RollbackWatchdog, SwapCoordinator,
                                 SwapError)
from dsin_tpu.serve.trace import FlightRecorder, TraceContext, Tracer
from dsin_tpu.train.checkpoint import ManifestMismatch
from dsin_tpu.utils.integrity import IntegrityError

__all__ = [
    "BULK", "INTERACTIVE",
    "AdmissionController", "AggregatedMetrics", "AggregatedTraces",
    "Autoscaler", "AutoscaleConfig", "AutoscaleError",
    "AutoscalePolicy",
    "BucketPolicy", "CanaryFailed", "CompressionService",
    "ConditionalRollbackRefused", "DeadlineExceeded",
    "DevicePlacement", "EncodeResult",
    "FederatedMetrics", "FederatedRouter", "FederatedTraces",
    "FederationError", "FederationHealthDriver",
    "FleetHealthPolicy",
    "FleetHealthSignals", "FleetScaleError", "FleetSwapError",
    "Member", "MemberUnreachable",
    "RolloutAborted", "RolloutPlan", "ScaleSignals",
    "FlightRecorder", "FrontDoorRouter", "Future",
    "IntegrityError", "ManifestMismatch", "MetricsRegistry",
    "MetricsServer", "MicroBatcher", "ModelBundle", "NoBucketFits",
    "PlacementError", "PlacementPlan", "PriorityClass",
    "QualityMonitor", "RebalanceTrigger", "Request", "RollbackWatchdog",
    "ServeError",
    "ServiceConfig", "ServiceDraining", "ServiceOverloaded",
    "ServiceUnavailable", "SessionEntry", "SessionError",
    "SessionExpired", "SessionOverCapacity", "SessionStore",
    "SwapCoordinator", "SwapError", "TraceContext", "Tracer",
    "crop_from_bucket", "default_priority_classes",
    "federation_health_from_snapshot",
    "health_from_snapshot", "pad_to_bucket",
    "plan_placement", "signals_from_snapshot",
]
