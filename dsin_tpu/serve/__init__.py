"""dsin_tpu.serve — long-lived micro-batching compression service.

Layering (each module stands alone below the next):
    buckets.py   — static shape buckets (fixed executable census)
    batcher.py   — bounded queue, same-bucket coalescing, backpressure,
                   deadlines, drain (pure stdlib threading)
    placement.py — bucket ladder -> device mesh assignment (replica
                   policy + per-device shardings via parallel/mesh.py)
    metrics.py   — lock-guarded counters/gauges/histograms + http.server
                   /healthz + /metrics endpoint
    service.py   — device-affine executor threads over the batched
                   jitted codec; model state loaded once via
                   coding/loader.py

Driven by tools/serve_bench.py (open-loop load + --devices scaling axis,
SERVE_BENCH.json).
"""

from dsin_tpu.serve.batcher import (DeadlineExceeded, Future, MicroBatcher,
                                    Request, ServeError, ServiceDraining,
                                    ServiceOverloaded, ServiceUnavailable)
from dsin_tpu.serve.buckets import (BucketPolicy, NoBucketFits,
                                    crop_from_bucket, pad_to_bucket)
from dsin_tpu.serve.metrics import MetricsRegistry, MetricsServer
from dsin_tpu.serve.placement import (DevicePlacement, PlacementError,
                                      PlacementPlan, plan_placement)
from dsin_tpu.serve.service import (CompressionService, EncodeResult,
                                    ServiceConfig)
from dsin_tpu.utils.integrity import IntegrityError

__all__ = [
    "BucketPolicy", "CompressionService", "DeadlineExceeded",
    "DevicePlacement", "EncodeResult", "Future", "IntegrityError",
    "MetricsRegistry", "MetricsServer", "MicroBatcher", "NoBucketFits",
    "PlacementError", "PlacementPlan", "Request", "ServeError",
    "ServiceConfig", "ServiceDraining", "ServiceOverloaded",
    "ServiceUnavailable", "crop_from_bucket", "pad_to_bucket",
    "plan_placement",
]
